"""Fleet capacity and elasticity (PR 16): drive the REAL multi-process
serve fleet — subprocess `cli.serve` replicas behind the readiness-
routing proxy — with the open-loop generator, and publish capacity vs
replica count off the scrapes.

    JAX_PLATFORMS=cpu python benchmarks/bench_fleet.py \
        --csv benchmarks/fleet_cpu.csv --out benchmarks/FLEET.md

    python benchmarks/bench_fleet.py --smoke   # the fleet-smoke tier-1 gate

The sweep runs FIXED fleets (autoscaler off) of 1, 2, and 3 replicas,
measures each fleet's saturation with the same doubling calibration
ramp bench_load.py uses, and annotates the knee and the replication
efficiency (capacity_n / (n * capacity_1)). Latency percentiles are
scrape-derived per replica (`tdc_serve_latency_ms` bucket deltas); the
table reports the WORST replica's p99 — the number a per-replica SLO
alert would fire on. Service time is emulated on every replica
(`--service_ms`, forwarded to `cli.serve`) exactly as in bench_load:
CPU CI's tiny-model predict is so fast that saturation would otherwise
measure the harness, not the serving stack.

The `--smoke` contract (gated in scripts/ci_tier1.sh) is the whole
elasticity loop against a 1→3 replica fleet with the autoscaler ON:

  - a sustained spike well past single-replica saturation makes the
    lone replica shed (scrape-verified admission state);
  - the autoscaler scales OUT (`tdc_fleet_scale_events_total{
    direction="up"}` >= 1 on the router scrape) and, with the fleet
    grown, the SAME super-single-replica offered load sheds NOTHING —
    shedding stopped because capacity arrived, not because load left;
  - when the load drops, the autoscaler scales back IN
    (direction="down" >= 1) through the SIGTERM→drain→exit-75 contract,
    and the draining replica takes ZERO routed requests while live
    traffic continues (router `tdc_fleet_routed_total{replica=...}`
    delta == 0 — the no-traffic-to-not-ready acceptance);
  - zero requests hang in any phase, and every fleet-level rejection is
    an accounted 503, never a connection error.
"""

from __future__ import annotations

import argparse
import csv
import json
import math
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tdc_tpu.fleet import (  # noqa: E402
    Autoscaler,
    AutoscalerConfig,
    DRAINING,
    FleetRouter,
    ServeFleet,
    subprocess_spawner,
)
from tdc_tpu.obs.loadgen import (  # noqa: E402
    HttpTarget,
    make_shape,
    run_open_loop,
)
from tdc_tpu.obs.metrics import (  # noqa: E402
    scrape_counter,
    scrape_quantile,
)

D = 16
MIX = {"km": 1.0}


def _models_dir() -> str:
    import jax

    from tdc_tpu.models.kmeans import kmeans_fit
    from tdc_tpu.models.persist import save_fitted

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, D)).astype(np.float32)
    root = tempfile.mkdtemp(prefix="tdc_bench_fleet_")
    km = kmeans_fit(x, 16, key=jax.random.PRNGKey(0), max_iters=4)
    save_fitted(os.path.join(root, "km"), km)
    return root


def _replica_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no simulated 8-device mesh per replica
    env.pop("TDC_FAULTS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _replica_args(model_root: str, args, *, service_ms=None,
                  max_wait_ms="4.0") -> list[str]:
    """cli.serve argv tail tuned so one replica saturates in seconds at
    CI scale: small batches + queue, fast governor, short linger."""
    if service_ms is None:
        service_ms = args.service_ms
    return [
        "--model_root", model_root,
        "--poll_interval", "0",
        "--max_batch_rows", str(args.max_batch_rows),
        "--max_queue_rows", str(args.max_queue_rows),
        "--max_wait_ms", max_wait_ms,
        "--warmup_buckets", "2,4,8,16,32",
        "--service_ms", str(service_ms),
        "--shed_p99_wait_ms", "250",
        "--shed_min_hold_s", "0.5",
        "--shed_retry_after_s", "0.5",
        "--drain_linger", "1.0",
        "--backend", "cpu",
    ]


class FleetHarness:
    """One fleet + router + (optional, caller-started) autoscaler.

    `slow_names`/`slow_service_ms` make the named replicas emulate a
    longer per-batch service time — the skewed-load scenario's one slow
    replica (a noisy neighbor / thermally-throttled host stand-in)."""

    def __init__(self, model_root, args, *, max_replicas: int,
                 balance: str = "p2c", pool_max_idle: int = 8,
                 replica_max_wait_ms: str = "4.0",
                 slow_names=(), slow_service_ms=None):
        fast = subprocess_spawner(
            _replica_args(model_root, args,
                          max_wait_ms=replica_max_wait_ms),
            env=_replica_env())
        if slow_names:
            slow = subprocess_spawner(
                _replica_args(model_root, args, service_ms=slow_service_ms,
                              max_wait_ms=replica_max_wait_ms),
                env=_replica_env())
            slow_set = frozenset(slow_names)

            def spawn(name):
                return (slow if name in slow_set else fast)(name)
        else:
            spawn = fast
        self.fleet = ServeFleet(
            spawn,
            poll_interval=0.1,
            drain_grace_s=60.0,
        )
        self.router = FleetRouter(self.fleet, forward_timeout_s=30.0,
                                  balance=balance,
                                  pool_max_idle=pool_max_idle)
        self.scaler = Autoscaler(self.fleet, AutoscalerConfig(
            min_replicas=1,
            max_replicas=max_replicas,
            eval_interval_s=0.25,
            up_hold_s=0.5,
            # Long enough that a briefly-calm spike tail can't shrink
            # the fleet mid-measurement; short enough that the smoke's
            # calm window sees the scale-in.
            down_hold_s=6.0,
            cooldown_s=2.0,
            shed_frac_high=0.5,
        ), registry=self.router.registry)
        self.port = None

    def start(self, n: int, timeout: float = 240.0) -> "HttpTarget":
        self.fleet.start(n)
        if not self.fleet.wait_ready(n, timeout=timeout):
            raise RuntimeError(f"fleet never reached {n} ready: "
                               f"{self.fleet.counts()}")
        self.port = self.router.start_http("127.0.0.1", 0)
        return HttpTarget(f"http://127.0.0.1:{self.port}", timeout=30.0)

    def replica_scrapes(self) -> dict[str, str]:
        out = {}
        for r in self.fleet.ready_replicas():
            text = r.scrape()
            if text is not None:
                out[r.name] = text
        return out

    def settle(self, timeout_s: float = 15.0) -> bool:
        """All live replicas admitting (scrape-verified), queues drained
        — the inter-cell baseline."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            scrapes = self.replica_scrapes().values()
            if scrapes and all(
                scrape_counter(s, "tdc_serve_admission_state") == 0
                for s in scrapes
            ):
                return True
            time.sleep(0.2)
        return False

    def stop(self):
        self.scaler.stop()
        self.router.stop_http()
        self.fleet.stop(drain=True)


def run_cell(harness, target, *, rps: float, duration_s: float,
             seed: int, max_workers: int = 256) -> dict:
    before = harness.replica_scrapes()
    rep = run_open_loop(
        target,
        make_shape("constant", base_rps=rps, duration_s=duration_s),
        duration_s, d=D, model_mix=MIX, seed=seed,
        max_workers=max_workers, hang_timeout_s=60.0,
    )
    after = harness.replica_scrapes()
    worst_p99 = float("nan")
    sheds = 0.0
    for name, text in after.items():
        base = before.get(name)
        q = scrape_quantile(text, "tdc_serve_latency_ms", 0.99,
                            {"endpoint": "predict"}, baseline=base)
        if not math.isnan(q) and not (worst_p99 >= q):
            worst_p99 = q
        sheds += scrape_counter(text, "tdc_serve_shed_total") - (
            scrape_counter(base, "tdc_serve_shed_total") if base else 0.0)
    return {
        "offered_rps": round(rep.offered_rps, 1),
        "goodput_rps": round(rep.goodput_rps, 1),
        "ok": rep.counts["ok"],
        "shed": rep.counts["shed"],
        "backpressure": rep.counts["backpressure"],
        "drain": rep.counts["drain"],
        "error": rep.counts["error"],
        "hung": rep.hung,
        # None (blank CSV cell, em-dash in the table) when every
        # replica's bucket delta came back empty — a scrape gap must
        # read as "absent", never as a literal nan committed as data.
        "p99_worst_replica_ms":
            round(worst_p99, 2) if worst_p99 == worst_p99 else None,
        "client_p50_ms": round(rep.client_percentile(0.50), 2),
        "client_p99_ms": round(rep.client_percentile(0.99), 2),
        "shed_scrape": int(sheds),
    }


def measure_capacity(harness, target, *, start_rps: float, cell_s: float,
                     seed: int) -> tuple[float, list[dict]]:
    """The bench_load doubling ramp, against the fleet's front door:
    double a constant offered rate until goodput stops following it.
    Returns (best goodput seen, the ramp cells)."""
    best, rps, cells = 0.0, start_rps, []
    for i in range(8):
        cell = run_cell(harness, target, rps=rps, duration_s=cell_s,
                        seed=seed + i)
        cell["ramp_rps"] = round(rps, 1)
        cells.append(cell)
        best = max(best, cell["goodput_rps"])
        print(f"  calibrate: offered={cell['offered_rps']} "
              f"goodput={cell['goodput_rps']} shed={cell['shed_scrape']}",
              flush=True)
        harness.settle()
        if cell["goodput_rps"] < 0.8 * cell["offered_rps"]:
            break
        rps *= 2.0
    return best, cells


# ---------------------------------------------------------------------------
# Router overhead: direct vs through-router, pooled vs per-request dial
# ---------------------------------------------------------------------------


def _closed_loop_lat(port: int, body: bytes, n: int,
                     warmup: int = 20) -> list[float]:
    """Sequential closed-loop request latencies (ms, sorted) over ONE
    keep-alive client connection — the client hop is identical for the
    direct and through-router cells, so their difference isolates the
    router's own data-plane cost."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    lat = []
    try:
        for i in range(n + warmup):
            t0 = time.perf_counter()
            conn.request("POST", "/predict", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            if resp.status != 200:
                raise RuntimeError(f"overhead cell got {resp.status}")
            if i >= warmup:
                lat.append((time.perf_counter() - t0) * 1000.0)
    finally:
        conn.close()
    lat.sort()
    return lat


def _pct(sorted_ms: list[float], q: float) -> float:
    i = min(len(sorted_ms) - 1, max(0, math.ceil(q * len(sorted_ms)) - 1))
    return sorted_ms[i]


def _legacy_proxy(upstream_base: str) -> tuple[object, int]:
    """A faithful copy of the PR-16 router data plane, kept here as the
    baseline half of the overhead A/B: one `urllib.request.urlopen` per
    proxied request (fresh TCP dial), whole-body `resp.read()`, and the
    `BaseHTTPRequestHandler` default UNBUFFERED response write (status
    line, headers, and body leave as separate small TCP segments — the
    Nagle/delayed-ACK stall this PR removed from the live handlers).
    Returns (httpd, port); caller shuts it down."""
    import http.server
    import urllib.request

    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) if length else None
            req = urllib.request.Request(
                upstream_base + self.path, data=body, method="POST"
            )
            if body is not None:
                req.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(req, timeout=30) as resp:
                data = resp.read()
                status = resp.status
                ctype = resp.headers.get("Content-Type",
                                         "application/json")
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


def run_overhead(model_root, args) -> dict:
    """Per-request router overhead at service_ms=0: the same replica
    measured direct, through the pooled router, through the router with
    the pool disabled (isolates the dial cost alone), and through a
    verbatim PR-16 legacy proxy (`_legacy_proxy`: per-request urllib
    dial + whole-body buffering + unbuffered response writes) — the
    committed-baseline half of the overhead_cut A/B."""
    oargs = argparse.Namespace(**{**vars(args), "service_ms": 0.0})
    harness = FleetHarness(model_root, oargs, max_replicas=1,
                           replica_max_wait_ms="0.5")
    legacy_httpd = None
    try:
        harness.start(1)
        replica = harness.fleet.ready_replicas()[0]
        rport = int(replica.base_url.rsplit(":", 1)[1])
        rng = np.random.default_rng(5)
        body = json.dumps({
            "model": "km", "points": rng.normal(size=(4, D)).tolist(),
        }).encode()
        n = args.overhead_n
        direct = _closed_loop_lat(rport, body, n)
        pooled = _closed_loop_lat(harness.port, body, n)
        harness.router.pool.flush_all(reason="bench_overhead")
        harness.router.pool.max_idle_per_replica = 0
        nopool = _closed_loop_lat(harness.port, body, n)
        legacy_httpd, lport = _legacy_proxy(replica.base_url)
        legacy = _closed_loop_lat(lport, body, n)
    finally:
        if legacy_httpd is not None:
            legacy_httpd.shutdown()
            legacy_httpd.server_close()
        harness.stop()
    row = {
        "scenario": "overhead",
        "replicas": 1,
        "direct_p50_ms": round(_pct(direct, 0.5), 3),
        "direct_p99_ms": round(_pct(direct, 0.99), 3),
        "router_p50_ms": round(_pct(pooled, 0.5), 3),
        "router_p99_ms": round(_pct(pooled, 0.99), 3),
        "router_nopool_p50_ms": round(_pct(nopool, 0.5), 3),
        "router_nopool_p99_ms": round(_pct(nopool, 0.99), 3),
        "legacy_p50_ms": round(_pct(legacy, 0.5), 3),
        "legacy_p99_ms": round(_pct(legacy, 0.99), 3),
    }
    over = row["router_p50_ms"] - row["direct_p50_ms"]
    over_np = row["router_nopool_p50_ms"] - row["direct_p50_ms"]
    over_legacy = row["legacy_p50_ms"] - row["direct_p50_ms"]
    row["overhead_p50_ms"] = round(over, 3)
    row["nopool_overhead_p50_ms"] = round(over_np, 3)
    row["legacy_overhead_p50_ms"] = round(over_legacy, 3)
    row["overhead_cut"] = round(over_legacy / over, 2) if over > 0 \
        else float("inf")
    return row


# ---------------------------------------------------------------------------
# Skewed load: one slow replica, round-robin vs queue-aware p2c
# ---------------------------------------------------------------------------


def run_skew_cells(model_root, args, *, rps: float,
                   cell_s: float) -> tuple[dict, dict]:
    """3 replicas with r2 at `--skew_slow_mult`x the service time, the
    SAME offered load routed round-robin then p2c on the same fleet.
    Each cell reports the slow replica's routed share (router scrape
    deltas) alongside the client percentiles."""
    import threading

    slow_ms = args.skew_slow_mult * args.service_ms
    harness = FleetHarness(model_root, args, max_replicas=3, balance="rr",
                           slow_names=("r2",), slow_service_ms=slow_ms)
    out = {}
    # The autoscaler's scrape pass is what stamps replica.queue_p99_ms
    # for the p2c score; run JUST that (signals(), never
    # evaluate_once()) on its cadence so the balancer sees the same
    # queue-wait signal a production fleet would — without any scale
    # decisions mutating the fleet mid-measurement.
    stop_stamp = threading.Event()

    def stamp_loop():
        while not stop_stamp.is_set():
            try:
                harness.scaler.signals()
            except Exception:
                pass
            stop_stamp.wait(0.5)

    stamper = threading.Thread(target=stamp_loop, daemon=True)
    try:
        target = harness.start(3)
        stamper.start()
        # Warm every replica's serving path before the measured cells.
        run_cell(harness, target, rps=max(4.0, 0.2 * rps),
                 duration_s=1.5, seed=41)
        harness.settle()

        def routed(name):
            return scrape_counter(harness.router.registry.render(),
                                  "tdc_fleet_routed_total",
                                  {"replica": name})

        names = [r.name for r in harness.fleet.snapshot()]
        for strat, seed in (("rr", 51), ("p2c", 52)):
            harness.router.balance = strat
            base = {name: routed(name) for name in names}
            cell = run_cell(harness, target, rps=rps, duration_s=cell_s,
                            seed=seed, max_workers=args.max_workers)
            deltas = {name: routed(name) - base[name] for name in names}
            total = sum(deltas.values())
            cell["scenario"] = f"skew_{strat}"
            cell["replicas"] = 3
            cell["balance"] = strat
            cell["slow_share"] = (
                round(deltas.get("r2", 0.0) / total, 3) if total else 0.0)
            out[strat] = cell
            print(f"  skew {strat}: offered={cell['offered_rps']} "
                  f"client_p99={cell['client_p99_ms']}ms "
                  f"slow_share={cell['slow_share']} "
                  f"shed={cell['shed_scrape']}", flush=True)
            harness.settle()
    finally:
        stop_stamp.set()
        if stamper.is_alive():
            stamper.join(timeout=5.0)
        harness.stop()
    return out["rr"], out["p2c"]


# ---------------------------------------------------------------------------
# The committed sweep (fleet_cpu.csv + FLEET.md)
# ---------------------------------------------------------------------------

CSV_COLUMNS = (
    "scenario", "replicas", "capacity_rps", "efficiency", "offered_rps",
    "goodput_rps", "ok", "shed_scrape", "backpressure", "hung",
    "p99_worst_replica_ms", "client_p50_ms", "client_p99_ms", "balance",
    "slow_share", "direct_p50_ms", "direct_p99_ms", "router_p50_ms",
    "router_p99_ms", "router_nopool_p50_ms", "router_nopool_p99_ms",
    "legacy_p50_ms", "legacy_p99_ms", "overhead_p50_ms",
    "nopool_overhead_p50_ms", "legacy_overhead_p50_ms", "overhead_cut",
)


def run_sweep(model_root, args) -> list[dict]:
    rows = []
    cap1 = None
    for n in (1, 2, 3):
        print(f"fleet n={n}: starting", flush=True)
        harness = FleetHarness(model_root, args, max_replicas=n)
        try:
            target = harness.start(n)
            cap, _ = measure_capacity(
                harness, target, start_rps=args.start_rps,
                cell_s=args.cell_s, seed=11 * n)
            harness.settle()
            # The reported cell: hold the fleet AT its measured capacity.
            cell = run_cell(harness, target, rps=cap,
                            duration_s=args.cell_s, seed=100 + n)
        finally:
            harness.stop()
        if cap1 is None:
            cap1 = cap
        cell["scenario"] = f"capacity_n{n}"
        cell["replicas"] = n
        cell["capacity_rps"] = round(cap, 1)
        cell["efficiency"] = round(cap / (n * cap1), 2) if cap1 else 0.0
        rows.append(cell)
        print(f"fleet n={n}: capacity={cap:.1f} rps "
              f"(efficiency {cell['efficiency']})", flush=True)
    return rows


def _fmt(v) -> str:
    """Absent measurement (None / nan) renders as an em-dash."""
    return "—" if v is None or v != v else str(v)


def render_md(rows: list[dict], args, overhead: dict | None = None,
              skew: tuple[dict, dict] | None = None) -> str:
    cap1 = rows[0]["capacity_rps"]
    lines = [
        "# Fleet capacity vs replica count (benchmarks/bench_fleet.py)",
        "",
        f"Open-loop Poisson traffic against the fleet front door — real "
        f"`cli.serve` subprocess replicas (kmeans K=16 d={D}, emulated "
        f"per-batch service time {args.service_ms} ms, micro-batch cap "
        f"{args.max_batch_rows} rows, queue bound {args.max_queue_rows} "
        f"rows) behind the readiness-routing proxy, autoscaler OFF "
        "(fixed fleets). Capacity is MEASURED per fleet size with the "
        "same doubling calibration ramp as `bench_load.py`; `p99 worst` "
        "is the scrape-derived per-replica p99 of the worst replica "
        "(the per-replica SLO alert's number); client percentiles are "
        "the stopwatch cross-check.",
        "",
        "| replicas | capacity rps | efficiency | offered rps | goodput "
        "rps | shed | backpr | hung | p99 worst ms | client p50/p99 ms |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['replicas']} | {r['capacity_rps']} | {r['efficiency']} "
            f"| {r['offered_rps']} | {r['goodput_rps']} "
            f"| {r['shed_scrape']} | {r['backpressure']} | {r['hung']} "
            f"| {_fmt(r['p99_worst_replica_ms'])} "
            f"| {r['client_p50_ms']}/{r['client_p99_ms']} |"
        )
    lines.append("")
    lines.append(
        f"**Knee per replica count:** each fleet's knee sits at its own "
        f"measured capacity (the calibration ramp's last keeping-up "
        f"cell); a single replica saturates at {cap1} req/s, so the "
        f"n=2 and n=3 rows place the fleet knee at "
        f"{rows[1]['capacity_rps']} and {rows[2]['capacity_rps']} req/s "
        f"— replication efficiency {rows[1]['efficiency']} and "
        f"{rows[2]['efficiency']} of perfectly linear scaling. "
        "Efficiency is coalescing-coupled in both directions: above "
        "1.0 when the larger fleet's calibration ramp reaches higher "
        "absolute rates (thicker micro-batches per replica), below 1.0 "
        "when the router hop and thinner per-replica arrival dominate "
        "— read the trend, not the third digit."
    )
    if overhead is not None:
        o = overhead
        lines += [
            "",
            "## Router data-plane overhead (per request, service_ms=0)",
            "",
            "Sequential closed loop over one keep-alive client "
            "connection against the same replica, four ways: direct "
            "(no router), through the router with the keep-alive pool "
            "(`--pool_max_idle 8`, the default plane), through the "
            "router with the pool disabled (`--pool_max_idle 0` — "
            "isolates the per-request TCP dial), and through a verbatim "
            "copy of the PR-16 data plane (per-request `urllib` dial, "
            "whole-body buffering, UNBUFFERED response writes). "
            "Overhead = through-proxy p50 minus direct p50.",
            "",
            "| plane | p50 ms | p99 ms | overhead p50 ms |",
            "|---|---|---|---|",
            f"| direct to replica | {o['direct_p50_ms']} "
            f"| {o['direct_p99_ms']} | — |",
            f"| router, pooled | {o['router_p50_ms']} "
            f"| {o['router_p99_ms']} | {o['overhead_p50_ms']} |",
            f"| router, per-request dial | {o['router_nopool_p50_ms']} "
            f"| {o['router_nopool_p99_ms']} "
            f"| {o['nopool_overhead_p50_ms']} |",
            f"| PR-16 legacy plane | {o['legacy_p50_ms']} "
            f"| {o['legacy_p99_ms']} "
            f"| {o['legacy_overhead_p50_ms']} |",
            "",
            f"**The new data plane cuts the router's p50 hop cost "
            f"{o['overhead_cut']}x vs the PR-16 baseline** (from "
            f"{o['legacy_overhead_p50_ms']} ms to "
            f"{o['overhead_p50_ms']} ms). Most of the legacy cost is "
            "the unbuffered handler's Nagle/delayed-ACK stall — status "
            "line, headers, and body left as separate small TCP "
            "segments, costing a single-in-flight client ~40 ms per "
            "response (fixed in BOTH the router and the replica server "
            "by buffering each response into one segment); the "
            "remainder is the per-request TCP dial the keep-alive pool "
            "removes (the `per-request dial` row isolates it).",
        ]
    if skew is not None:
        rr, p2c = skew
        lines += [
            "",
            "## Skewed load: one slow replica "
            f"({args.skew_slow_mult:.0f}x service time on r2)",
            "",
            "Same fleet, same offered load "
            f"(~{rr['offered_rps']} rps), balanced round-robin then "
            "power-of-two-choices. `slow share` is the fraction of "
            "routed requests the router sent to the slow replica "
            "(`tdc_fleet_routed_total` deltas). Round-robin keeps "
            "feeding the slow replica its full share, so a third of "
            "requests queue behind a replica that cannot keep up; p2c "
            "reads the live in-flight count plus the scrape-derived "
            "queue p99 (the autoscaler's scrape pass runs during the "
            "cells, stamping it exactly as in production) and routes "
            "around the hotspot.",
            "",
            "| balance | offered rps | goodput rps | shed | "
            "client p50 ms | client p99 ms | slow share |",
            "|---|---|---|---|---|---|---|",
            f"| rr | {rr['offered_rps']} | {rr['goodput_rps']} "
            f"| {rr['shed_scrape']} | {rr['client_p50_ms']} "
            f"| {rr['client_p99_ms']} | {rr['slow_share']} |",
            f"| p2c | {p2c['offered_rps']} | {p2c['goodput_rps']} "
            f"| {p2c['shed_scrape']} | {p2c['client_p50_ms']} "
            f"| {p2c['client_p99_ms']} | {p2c['slow_share']} |",
        ]
    lines += [
        "",
        "The elasticity loop itself (shed onset → autoscale OUT → shed "
        "stops at unchanged offered load → scale back IN with zero "
        "requests routed to the draining replica) is gated by "
        "`bench_fleet.py --smoke` — the `fleet-smoke` tier-1 stage, "
        "which also replays the skewed-load scenario and asserts p2c "
        "beats round-robin on client p99 while shifting routed share "
        "off the slow replica. CPU-CI numbers; re-run with "
        "`--service_ms 0` on real silicon for production capacity.",
        "",
    ]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The tier-1 smoke: the whole elasticity loop, scrape-verified
# ---------------------------------------------------------------------------


def _scale_events(router, direction: str) -> float:
    return scrape_counter(router.registry.render(),
                          "tdc_fleet_scale_events_total",
                          {"direction": direction})


def _routed_to(router, name: str) -> float:
    return scrape_counter(router.registry.render(),
                          "tdc_fleet_routed_total", {"replica": name})


def run_smoke(args) -> int:
    import threading

    model_root = _models_dir()
    harness = FleetHarness(model_root, args, max_replicas=3)
    checks: dict[str, bool] = {}
    detail: dict[str, object] = {}
    try:
        target = harness.start(1)
        cap1, ramp = measure_capacity(
            harness, target, start_rps=args.start_rps,
            cell_s=args.cell_s, seed=7)
        if cap1 <= 0:
            print("FLEET-SMOKE FAIL: calibration measured zero goodput")
            return 1
        harness.settle()

        # Phase 1 — the spike: well past single-replica saturation, with
        # the autoscaler ON. The lone replica must shed; the autoscaler
        # must grow the fleet WHILE the spike runs (the moment load
        # stops, a correctly-working autoscaler starts shrinking again —
        # so growth is observed live, not after the fact).
        harness.scaler.start()
        spike_out: dict = {}

        def spike_load():
            spike_out["cell"] = run_cell(
                harness, target, rps=args.spike_frac * cap1,
                duration_s=args.spike_s, seed=101,
                max_workers=args.max_workers)

        spiker = threading.Thread(target=spike_load, daemon=True)
        spiker.start()
        grown = 1
        deadline = time.monotonic() + args.spike_s
        while time.monotonic() < deadline and grown < 3:
            grown = max(grown, len(harness.fleet.ready_replicas()))
            time.sleep(0.1)
        spiker.join(timeout=args.spike_s + 120.0)
        spike = spike_out["cell"]
        checks["spike_shed_onset"] = spike["shed_scrape"] > 0
        checks["scaled_out"] = (
            grown >= 2 and _scale_events(harness.router, "up") >= 1)
        checks["no_transport_errors"] = spike["error"] == 0
        detail["spike"] = spike
        detail["grown"] = grown

        # Phase 2 — shed stops: freeze the fleet at its grown size
        # (scaler paused — measurement, not intervention) and hold an
        # offered load still ABOVE one replica's capacity: with the
        # capacity the autoscaler added, nothing sheds.
        harness.scaler.stop()
        # Let the grown fleet actually stabilize before sampling its
        # size: a replica the autoscaler spawned near the spike's end
        # may still be STARTING (jax import takes seconds on a loaded
        # box), and the spiked replica can shed past a short settle
        # while it burns down the backlog. Phase 2 measures the grown
        # fleet at steady state, not the spike's tail.
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if (len(harness.fleet.ready_replicas()) >= grown
                    and harness.settle(timeout_s=5.0)):
                break
            time.sleep(0.2)
        n_now = max(1, len(harness.fleet.ready_replicas()))
        held_rps = min(args.spike_frac, 0.6 * n_now) * cap1
        held = run_cell(harness, target, rps=held_rps,
                        duration_s=args.cell_s, seed=202,
                        max_workers=args.max_workers)
        checks["shed_stops_above_cap1"] = (
            held["shed_scrape"] == 0 and held_rps > cap1)
        detail["held"] = held

        # Phase 3 — calm: scaler back on, light load; the autoscaler
        # drains a replica back out, and the draining replica takes
        # ZERO routed requests while traffic continues.
        harness.scaler.start()
        light_rps = max(2.0, 0.2 * cap1)
        light_report = {}

        def light_load():
            light_report["rep"] = run_open_loop(
                target,
                make_shape("constant", base_rps=light_rps,
                           duration_s=args.calm_s),
                args.calm_s, d=D, model_mix=MIX, seed=303,
                max_workers=64, hang_timeout_s=60.0,
            )

        loader = threading.Thread(target=light_load, daemon=True)
        loader.start()
        victim, routed_base = None, 0.0
        deadline = time.monotonic() + args.calm_s
        while time.monotonic() < deadline and victim is None:
            for r in harness.fleet.snapshot():
                if r.state == DRAINING:
                    victim = r.name
                    break
            time.sleep(0.05)
        if victim is not None:
            time.sleep(0.4)  # let pre-drain in-flight dispatches land
            routed_base = _routed_to(harness.router, victim)
            time.sleep(2.0)  # live traffic continues around the drain
        loader.join(timeout=args.calm_s + 60.0)
        rep = light_report.get("rep")
        checks["scaled_in"] = (
            victim is not None
            and _scale_events(harness.router, "down") >= 1)
        checks["drain_gets_zero_traffic"] = (
            victim is not None
            and _routed_to(harness.router, victim) == routed_base)
        checks["zero_hung"] = (
            spike["hung"] == 0 and held["hung"] == 0
            and rep is not None and rep.hung == 0)
        detail["victim"] = victim
        detail["calm_ok"] = rep.counts["ok"] if rep is not None else -1
    finally:
        harness.stop()

    # Phase 4 — skewed load: a fresh 3-replica fleet with one slow
    # replica, the SAME offered load round-robin then p2c. Queue-aware
    # balancing must beat rr on client p99 AND visibly shift routed
    # share off the slow replica.
    skew_rps = args.skew_frac * cap1
    rr, p2c = run_skew_cells(model_root, args, rps=skew_rps,
                             cell_s=max(4.0, args.cell_s))
    checks["skew_p2c_beats_rr_p99"] = (
        p2c["client_p99_ms"] < rr["client_p99_ms"])
    checks["skew_share_shifts_off_slow"] = (
        p2c["slow_share"] < rr["slow_share"] - 0.05)
    checks["skew_zero_hung"] = rr["hung"] == 0 and p2c["hung"] == 0
    detail["skew"] = (rr, p2c)

    ok = all(checks.values())
    failed = [k for k, v in checks.items() if not v]
    spike, held = detail["spike"], detail["held"]
    print(
        "FLEET-SMOKE " + ("PASS" if ok else "FAIL")
        + f": cap1={cap1:.0f} rps, spike offered={spike['offered_rps']} "
        f"({args.spike_frac}x cap1) shed={spike['shed_scrape']} "
        f"hung={spike['hung']}, grew 1->{detail['grown']} "
        f"(up={_scale_events(harness.router, 'up'):.0f}), held "
        f"offered={held['offered_rps']} (> cap1) shed="
        f"{held['shed_scrape']}, scale-in victim={detail['victim']} "
        f"(down={_scale_events(harness.router, 'down'):.0f}) routed-"
        f"while-draining=0:{checks.get('drain_gets_zero_traffic')}, "
        f"calm ok={detail['calm_ok']}, skew p99 rr="
        f"{rr['client_p99_ms']}ms p2c={p2c['client_p99_ms']}ms "
        f"slow-share rr={rr['slow_share']} p2c={p2c['slow_share']}"
        + (f" FAILED={failed}" if failed else "")
    )
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 elasticity-loop gate (PASS/FAIL line)")
    p.add_argument("--out", default=None, help="FLEET.md output path")
    p.add_argument("--csv", default=None, help="per-fleet CSV output path")
    p.add_argument("--service_ms", type=float, default=40.0,
                   help="emulated per-batch replica service time "
                        "(0 on real silicon)")
    p.add_argument("--max_batch_rows", type=int, default=16)
    p.add_argument("--max_queue_rows", type=int, default=256)
    p.add_argument("--start_rps", type=float, default=8.0,
                   help="calibration ramp starting rate")
    p.add_argument("--cell_s", type=float, default=3.0)
    p.add_argument("--spike_s", type=float, default=14.0,
                   help="smoke spike duration (covers replica startup)")
    p.add_argument("--calm_s", type=float, default=30.0,
                   help="smoke light-load window for scale-in")
    p.add_argument("--spike_frac", type=float, default=2.5,
                   help="spike offered load as a multiple of cap1")
    p.add_argument("--max_workers", type=int, default=256)
    p.add_argument("--overhead_n", type=int, default=300,
                   help="closed-loop samples per overhead cell")
    p.add_argument("--skew_slow_mult", type=float, default=4.0,
                   help="slow replica's service-time multiplier")
    p.add_argument("--skew_frac", type=float, default=1.3,
                   help="skew offered load as a multiple of cap1 "
                        "(above one fast replica, below the fleet)")
    args = p.parse_args(argv)

    if args.smoke:
        return run_smoke(args)

    model_root = _models_dir()
    rows = run_sweep(model_root, args)
    print("overhead: starting", flush=True)
    overhead = run_overhead(model_root, args)
    print(f"overhead: direct p50={overhead['direct_p50_ms']}ms, router "
          f"pooled +{overhead['overhead_p50_ms']}ms, per-request dial "
          f"+{overhead['nopool_overhead_p50_ms']}ms, PR-16 legacy "
          f"+{overhead['legacy_overhead_p50_ms']}ms "
          f"(cut {overhead['overhead_cut']}x vs legacy)", flush=True)
    print("skew: starting", flush=True)
    skew = run_skew_cells(model_root, args,
                          rps=args.skew_frac * rows[0]["capacity_rps"],
                          cell_s=2 * args.cell_s)
    if args.csv:
        with open(args.csv, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=CSV_COLUMNS,
                               extrasaction="ignore")
            w.writeheader()
            for r in rows + [overhead, *skew]:
                w.writerow(r)
        print(f"wrote {args.csv}")
    text = render_md(rows, args, overhead=overhead, skew=skew)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
