"""Fleet capacity and elasticity (PR 16): drive the REAL multi-process
serve fleet — subprocess `cli.serve` replicas behind the readiness-
routing proxy — with the open-loop generator, and publish capacity vs
replica count off the scrapes.

    JAX_PLATFORMS=cpu python benchmarks/bench_fleet.py \
        --csv benchmarks/fleet_cpu.csv --out benchmarks/FLEET.md

    python benchmarks/bench_fleet.py --smoke   # the fleet-smoke tier-1 gate

The sweep runs FIXED fleets (autoscaler off) of 1, 2, and 3 replicas,
measures each fleet's saturation with the same doubling calibration
ramp bench_load.py uses, and annotates the knee and the replication
efficiency (capacity_n / (n * capacity_1)). Latency percentiles are
scrape-derived per replica (`tdc_serve_latency_ms` bucket deltas); the
table reports the WORST replica's p99 — the number a per-replica SLO
alert would fire on. Service time is emulated on every replica
(`--service_ms`, forwarded to `cli.serve`) exactly as in bench_load:
CPU CI's tiny-model predict is so fast that saturation would otherwise
measure the harness, not the serving stack.

The `--smoke` contract (gated in scripts/ci_tier1.sh) is the whole
elasticity loop against a 1→3 replica fleet with the autoscaler ON:

  - a sustained spike well past single-replica saturation makes the
    lone replica shed (scrape-verified admission state);
  - the autoscaler scales OUT (`tdc_fleet_scale_events_total{
    direction="up"}` >= 1 on the router scrape) and, with the fleet
    grown, the SAME super-single-replica offered load sheds NOTHING —
    shedding stopped because capacity arrived, not because load left;
  - when the load drops, the autoscaler scales back IN
    (direction="down" >= 1) through the SIGTERM→drain→exit-75 contract,
    and the draining replica takes ZERO routed requests while live
    traffic continues (router `tdc_fleet_routed_total{replica=...}`
    delta == 0 — the no-traffic-to-not-ready acceptance);
  - zero requests hang in any phase, and every fleet-level rejection is
    an accounted 503, never a connection error.
"""

from __future__ import annotations

import argparse
import csv
import math
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tdc_tpu.fleet import (  # noqa: E402
    Autoscaler,
    AutoscalerConfig,
    DRAINING,
    FleetRouter,
    ServeFleet,
    subprocess_spawner,
)
from tdc_tpu.obs.loadgen import (  # noqa: E402
    HttpTarget,
    make_shape,
    run_open_loop,
)
from tdc_tpu.obs.metrics import (  # noqa: E402
    scrape_counter,
    scrape_quantile,
)

D = 16
MIX = {"km": 1.0}


def _models_dir() -> str:
    import jax

    from tdc_tpu.models.kmeans import kmeans_fit
    from tdc_tpu.models.persist import save_fitted

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, D)).astype(np.float32)
    root = tempfile.mkdtemp(prefix="tdc_bench_fleet_")
    km = kmeans_fit(x, 16, key=jax.random.PRNGKey(0), max_iters=4)
    save_fitted(os.path.join(root, "km"), km)
    return root


def _replica_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no simulated 8-device mesh per replica
    env.pop("TDC_FAULTS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _replica_args(model_root: str, args) -> list[str]:
    """cli.serve argv tail tuned so one replica saturates in seconds at
    CI scale: small batches + queue, fast governor, short linger."""
    return [
        "--model_root", model_root,
        "--poll_interval", "0",
        "--max_batch_rows", str(args.max_batch_rows),
        "--max_queue_rows", str(args.max_queue_rows),
        "--max_wait_ms", "4.0",
        "--warmup_buckets", "2,4,8,16,32",
        "--service_ms", str(args.service_ms),
        "--shed_p99_wait_ms", "250",
        "--shed_min_hold_s", "0.5",
        "--shed_retry_after_s", "0.5",
        "--drain_linger", "1.0",
        "--backend", "cpu",
    ]


class FleetHarness:
    """One fleet + router + (optional, caller-started) autoscaler."""

    def __init__(self, model_root, args, *, max_replicas: int):
        self.fleet = ServeFleet(
            subprocess_spawner(_replica_args(model_root, args),
                               env=_replica_env()),
            poll_interval=0.1,
            drain_grace_s=60.0,
        )
        self.router = FleetRouter(self.fleet, forward_timeout_s=30.0)
        self.scaler = Autoscaler(self.fleet, AutoscalerConfig(
            min_replicas=1,
            max_replicas=max_replicas,
            eval_interval_s=0.25,
            up_hold_s=0.5,
            # Long enough that a briefly-calm spike tail can't shrink
            # the fleet mid-measurement; short enough that the smoke's
            # calm window sees the scale-in.
            down_hold_s=6.0,
            cooldown_s=2.0,
            shed_frac_high=0.5,
        ), registry=self.router.registry)
        self.port = None

    def start(self, n: int, timeout: float = 240.0) -> "HttpTarget":
        self.fleet.start(n)
        if not self.fleet.wait_ready(n, timeout=timeout):
            raise RuntimeError(f"fleet never reached {n} ready: "
                               f"{self.fleet.counts()}")
        self.port = self.router.start_http("127.0.0.1", 0)
        return HttpTarget(f"http://127.0.0.1:{self.port}", timeout=30.0)

    def replica_scrapes(self) -> dict[str, str]:
        out = {}
        for r in self.fleet.ready_replicas():
            text = r.scrape()
            if text is not None:
                out[r.name] = text
        return out

    def settle(self, timeout_s: float = 15.0) -> bool:
        """All live replicas admitting (scrape-verified), queues drained
        — the inter-cell baseline."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            scrapes = self.replica_scrapes().values()
            if scrapes and all(
                scrape_counter(s, "tdc_serve_admission_state") == 0
                for s in scrapes
            ):
                return True
            time.sleep(0.2)
        return False

    def stop(self):
        self.scaler.stop()
        self.router.stop_http()
        self.fleet.stop(drain=True)


def run_cell(harness, target, *, rps: float, duration_s: float,
             seed: int, max_workers: int = 256) -> dict:
    before = harness.replica_scrapes()
    rep = run_open_loop(
        target,
        make_shape("constant", base_rps=rps, duration_s=duration_s),
        duration_s, d=D, model_mix=MIX, seed=seed,
        max_workers=max_workers, hang_timeout_s=60.0,
    )
    after = harness.replica_scrapes()
    worst_p99 = float("nan")
    sheds = 0.0
    for name, text in after.items():
        base = before.get(name)
        q = scrape_quantile(text, "tdc_serve_latency_ms", 0.99,
                            {"endpoint": "predict"}, baseline=base)
        if not math.isnan(q) and not (worst_p99 >= q):
            worst_p99 = q
        sheds += scrape_counter(text, "tdc_serve_shed_total") - (
            scrape_counter(base, "tdc_serve_shed_total") if base else 0.0)
    return {
        "offered_rps": round(rep.offered_rps, 1),
        "goodput_rps": round(rep.goodput_rps, 1),
        "ok": rep.counts["ok"],
        "shed": rep.counts["shed"],
        "backpressure": rep.counts["backpressure"],
        "drain": rep.counts["drain"],
        "error": rep.counts["error"],
        "hung": rep.hung,
        "p99_worst_replica_ms":
            round(worst_p99, 2) if worst_p99 == worst_p99 else float("nan"),
        "client_p50_ms": round(rep.client_percentile(0.50), 2),
        "client_p99_ms": round(rep.client_percentile(0.99), 2),
        "shed_scrape": int(sheds),
    }


def measure_capacity(harness, target, *, start_rps: float, cell_s: float,
                     seed: int) -> tuple[float, list[dict]]:
    """The bench_load doubling ramp, against the fleet's front door:
    double a constant offered rate until goodput stops following it.
    Returns (best goodput seen, the ramp cells)."""
    best, rps, cells = 0.0, start_rps, []
    for i in range(8):
        cell = run_cell(harness, target, rps=rps, duration_s=cell_s,
                        seed=seed + i)
        cell["ramp_rps"] = round(rps, 1)
        cells.append(cell)
        best = max(best, cell["goodput_rps"])
        print(f"  calibrate: offered={cell['offered_rps']} "
              f"goodput={cell['goodput_rps']} shed={cell['shed_scrape']}",
              flush=True)
        harness.settle()
        if cell["goodput_rps"] < 0.8 * cell["offered_rps"]:
            break
        rps *= 2.0
    return best, cells


# ---------------------------------------------------------------------------
# The committed sweep (fleet_cpu.csv + FLEET.md)
# ---------------------------------------------------------------------------

CSV_COLUMNS = (
    "replicas", "capacity_rps", "efficiency", "offered_rps", "goodput_rps",
    "ok", "shed_scrape", "backpressure", "hung", "p99_worst_replica_ms",
    "client_p50_ms", "client_p99_ms",
)


def run_sweep(model_root, args) -> list[dict]:
    rows = []
    cap1 = None
    for n in (1, 2, 3):
        print(f"fleet n={n}: starting", flush=True)
        harness = FleetHarness(model_root, args, max_replicas=n)
        try:
            target = harness.start(n)
            cap, _ = measure_capacity(
                harness, target, start_rps=args.start_rps,
                cell_s=args.cell_s, seed=11 * n)
            harness.settle()
            # The reported cell: hold the fleet AT its measured capacity.
            cell = run_cell(harness, target, rps=cap,
                            duration_s=args.cell_s, seed=100 + n)
        finally:
            harness.stop()
        if cap1 is None:
            cap1 = cap
        cell["replicas"] = n
        cell["capacity_rps"] = round(cap, 1)
        cell["efficiency"] = round(cap / (n * cap1), 2) if cap1 else 0.0
        rows.append(cell)
        print(f"fleet n={n}: capacity={cap:.1f} rps "
              f"(efficiency {cell['efficiency']})", flush=True)
    return rows


def render_md(rows: list[dict], args) -> str:
    cap1 = rows[0]["capacity_rps"]
    lines = [
        "# Fleet capacity vs replica count (benchmarks/bench_fleet.py)",
        "",
        f"Open-loop Poisson traffic against the fleet front door — real "
        f"`cli.serve` subprocess replicas (kmeans K=16 d={D}, emulated "
        f"per-batch service time {args.service_ms} ms, micro-batch cap "
        f"{args.max_batch_rows} rows, queue bound {args.max_queue_rows} "
        f"rows) behind the readiness-routing proxy, autoscaler OFF "
        "(fixed fleets). Capacity is MEASURED per fleet size with the "
        "same doubling calibration ramp as `bench_load.py`; `p99 worst` "
        "is the scrape-derived per-replica p99 of the worst replica "
        "(the per-replica SLO alert's number); client percentiles are "
        "the stopwatch cross-check.",
        "",
        "| replicas | capacity rps | efficiency | offered rps | goodput "
        "rps | shed | backpr | hung | p99 worst ms | client p50/p99 ms |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        lines.append(
            f"| {r['replicas']} | {r['capacity_rps']} | {r['efficiency']} "
            f"| {r['offered_rps']} | {r['goodput_rps']} "
            f"| {r['shed_scrape']} | {r['backpressure']} | {r['hung']} "
            f"| {r['p99_worst_replica_ms']} "
            f"| {r['client_p50_ms']}/{r['client_p99_ms']} |"
        )
    lines.append("")
    lines.append(
        f"**Knee per replica count:** each fleet's knee sits at its own "
        f"measured capacity (the calibration ramp's last keeping-up "
        f"cell); a single replica saturates at {cap1} req/s, so the "
        f"n=2 and n=3 rows place the fleet knee at "
        f"{rows[1]['capacity_rps']} and {rows[2]['capacity_rps']} req/s "
        f"— replication efficiency {rows[1]['efficiency']} and "
        f"{rows[2]['efficiency']} of perfectly linear scaling. "
        "Efficiency is coalescing-coupled in both directions: above "
        "1.0 when the larger fleet's calibration ramp reaches higher "
        "absolute rates (thicker micro-batches per replica), below 1.0 "
        "when the router hop and thinner per-replica arrival dominate "
        "— read the trend, not the third digit."
    )
    lines += [
        "",
        "The elasticity loop itself (shed onset → autoscale OUT → shed "
        "stops at unchanged offered load → scale back IN with zero "
        "requests routed to the draining replica) is gated by "
        "`bench_fleet.py --smoke` — the `fleet-smoke` tier-1 stage. "
        "CPU-CI numbers; re-run with `--service_ms 0` on real silicon "
        "for production capacity.",
        "",
    ]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The tier-1 smoke: the whole elasticity loop, scrape-verified
# ---------------------------------------------------------------------------


def _scale_events(router, direction: str) -> float:
    return scrape_counter(router.registry.render(),
                          "tdc_fleet_scale_events_total",
                          {"direction": direction})


def _routed_to(router, name: str) -> float:
    return scrape_counter(router.registry.render(),
                          "tdc_fleet_routed_total", {"replica": name})


def run_smoke(args) -> int:
    import threading

    model_root = _models_dir()
    harness = FleetHarness(model_root, args, max_replicas=3)
    checks: dict[str, bool] = {}
    detail: dict[str, object] = {}
    try:
        target = harness.start(1)
        cap1, ramp = measure_capacity(
            harness, target, start_rps=args.start_rps,
            cell_s=args.cell_s, seed=7)
        if cap1 <= 0:
            print("FLEET-SMOKE FAIL: calibration measured zero goodput")
            return 1
        harness.settle()

        # Phase 1 — the spike: well past single-replica saturation, with
        # the autoscaler ON. The lone replica must shed; the autoscaler
        # must grow the fleet WHILE the spike runs (the moment load
        # stops, a correctly-working autoscaler starts shrinking again —
        # so growth is observed live, not after the fact).
        harness.scaler.start()
        spike_out: dict = {}

        def spike_load():
            spike_out["cell"] = run_cell(
                harness, target, rps=args.spike_frac * cap1,
                duration_s=args.spike_s, seed=101,
                max_workers=args.max_workers)

        spiker = threading.Thread(target=spike_load, daemon=True)
        spiker.start()
        grown = 1
        deadline = time.monotonic() + args.spike_s
        while time.monotonic() < deadline and grown < 3:
            grown = max(grown, len(harness.fleet.ready_replicas()))
            time.sleep(0.1)
        spiker.join(timeout=args.spike_s + 120.0)
        spike = spike_out["cell"]
        checks["spike_shed_onset"] = spike["shed_scrape"] > 0
        checks["scaled_out"] = (
            grown >= 2 and _scale_events(harness.router, "up") >= 1)
        checks["no_transport_errors"] = spike["error"] == 0
        detail["spike"] = spike
        detail["grown"] = grown

        # Phase 2 — shed stops: freeze the fleet at its grown size
        # (scaler paused — measurement, not intervention) and hold an
        # offered load still ABOVE one replica's capacity: with the
        # capacity the autoscaler added, nothing sheds.
        harness.scaler.stop()
        harness.settle()
        n_now = max(1, len(harness.fleet.ready_replicas()))
        held_rps = min(args.spike_frac, 0.6 * n_now) * cap1
        held = run_cell(harness, target, rps=held_rps,
                        duration_s=args.cell_s, seed=202,
                        max_workers=args.max_workers)
        checks["shed_stops_above_cap1"] = (
            held["shed_scrape"] == 0 and held_rps > cap1)
        detail["held"] = held

        # Phase 3 — calm: scaler back on, light load; the autoscaler
        # drains a replica back out, and the draining replica takes
        # ZERO routed requests while traffic continues.
        harness.scaler.start()
        light_rps = max(2.0, 0.2 * cap1)
        light_report = {}

        def light_load():
            light_report["rep"] = run_open_loop(
                target,
                make_shape("constant", base_rps=light_rps,
                           duration_s=args.calm_s),
                args.calm_s, d=D, model_mix=MIX, seed=303,
                max_workers=64, hang_timeout_s=60.0,
            )

        loader = threading.Thread(target=light_load, daemon=True)
        loader.start()
        victim, routed_base = None, 0.0
        deadline = time.monotonic() + args.calm_s
        while time.monotonic() < deadline and victim is None:
            for r in harness.fleet.snapshot():
                if r.state == DRAINING:
                    victim = r.name
                    break
            time.sleep(0.05)
        if victim is not None:
            time.sleep(0.4)  # let pre-drain in-flight dispatches land
            routed_base = _routed_to(harness.router, victim)
            time.sleep(2.0)  # live traffic continues around the drain
        loader.join(timeout=args.calm_s + 60.0)
        rep = light_report.get("rep")
        checks["scaled_in"] = (
            victim is not None
            and _scale_events(harness.router, "down") >= 1)
        checks["drain_gets_zero_traffic"] = (
            victim is not None
            and _routed_to(harness.router, victim) == routed_base)
        checks["zero_hung"] = (
            spike["hung"] == 0 and held["hung"] == 0
            and rep is not None and rep.hung == 0)
        detail["victim"] = victim
        detail["calm_ok"] = rep.counts["ok"] if rep is not None else -1
    finally:
        harness.stop()

    ok = all(checks.values())
    failed = [k for k, v in checks.items() if not v]
    spike, held = detail["spike"], detail["held"]
    print(
        "FLEET-SMOKE " + ("PASS" if ok else "FAIL")
        + f": cap1={cap1:.0f} rps, spike offered={spike['offered_rps']} "
        f"({args.spike_frac}x cap1) shed={spike['shed_scrape']} "
        f"hung={spike['hung']}, grew 1->{detail['grown']} "
        f"(up={_scale_events(harness.router, 'up'):.0f}), held "
        f"offered={held['offered_rps']} (> cap1) shed="
        f"{held['shed_scrape']}, scale-in victim={detail['victim']} "
        f"(down={_scale_events(harness.router, 'down'):.0f}) routed-"
        f"while-draining=0:{checks.get('drain_gets_zero_traffic')}, "
        f"calm ok={detail['calm_ok']}"
        + (f" FAILED={failed}" if failed else "")
    )
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 elasticity-loop gate (PASS/FAIL line)")
    p.add_argument("--out", default=None, help="FLEET.md output path")
    p.add_argument("--csv", default=None, help="per-fleet CSV output path")
    p.add_argument("--service_ms", type=float, default=40.0,
                   help="emulated per-batch replica service time "
                        "(0 on real silicon)")
    p.add_argument("--max_batch_rows", type=int, default=16)
    p.add_argument("--max_queue_rows", type=int, default=256)
    p.add_argument("--start_rps", type=float, default=8.0,
                   help="calibration ramp starting rate")
    p.add_argument("--cell_s", type=float, default=3.0)
    p.add_argument("--spike_s", type=float, default=14.0,
                   help="smoke spike duration (covers replica startup)")
    p.add_argument("--calm_s", type=float, default=30.0,
                   help="smoke light-load window for scale-in")
    p.add_argument("--spike_frac", type=float, default=2.5,
                   help="spike offered load as a multiple of cap1")
    p.add_argument("--max_workers", type=int, default=256)
    args = p.parse_args(argv)

    if args.smoke:
        return run_smoke(args)

    model_root = _models_dir()
    rows = run_sweep(model_root, args)
    if args.csv:
        with open(args.csv, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=CSV_COLUMNS,
                               extrasaction="ignore")
            w.writeheader()
            for r in rows:
                w.writerow(r)
        print(f"wrote {args.csv}")
    text = render_md(rows, args)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
