"""MTTR micro-benchmark: kill -> resumed progress, through the real
supervisor recovery path.

Measures the wall-clock gap between the moment the gang loses a worker to
an injected kill -9 (TDC_FAULTS, tdc_tpu.testing.faults) and the moment
the relaunched gang writes its first NEW checkpoint step — i.e. the full
recovery pipeline: loss detection, survivor kill, checkpoint alignment,
backoff, respawn, jax re-import, restore, and the remainder of the
interrupted pass. That end-to-end number (not just process respawn) is
what a preempted production fit actually pays per interruption.

    JAX_PLATFORMS=cpu python benchmarks/bench_mttr.py [--runs 3] [--smoke]

Writes benchmarks/mttr_cpu.md (committed results for the CI box) unless
--no_write. Single process, CPU backend: the measured costs are dominated
by worker startup (python + jax import) and the replayed pass, both of
which scale the same way on real hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

WORKER = textwrap.dedent("""
    import os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    from tdc_tpu.models.streaming import streamed_kmeans_fit
    from tdc_tpu.utils.preempt import install_preemption_handler

    install_preemption_handler()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4096, 16)).astype(np.float32)
    x[:1024] += 4.0

    def batches():
        for i in range(0, 4096, 512):
            yield x[i:i + 512]

    res = streamed_kmeans_fit(
        batches, 8, 16, init=x[:8], max_iters=6, tol=-1.0,
        ckpt_dir=os.environ["TDC_CKPT_DIR"], ckpt_every=1,
        ckpt_keep_last_n=4,
    )
    print("FIT_DONE", int(res.n_iter), flush=True)
""")


def _steps(ckpt_dir: str) -> set[int]:
    from tdc_tpu.utils.checkpoint import _all_steps  # the one step parser

    return set(_all_steps(ckpt_dir))


def one_run(tmp: str, kill_hit: int) -> dict:
    """One supervised run with a kill injected at stream.batch hit
    `kill_hit`; returns the MTTR decomposition."""
    import shutil
    import threading

    from tdc_tpu.parallel.supervisor import run_gang

    shutil.rmtree(tmp, ignore_errors=True)
    ckpt = os.path.join(tmp, "ckpt")
    os.makedirs(ckpt)
    worker_py = os.path.join(tmp, "worker.py")
    with open(worker_py, "w") as f:
        f.write(WORKER)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["TDC_FAULTS"] = f"stream.batch=kill@{kill_hit}&attempt=0"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    marks = {}
    steps_at_kill = [set()]
    stop = threading.Event()

    def watch():
        # Poll the checkpoint dir: t_loss = supervisor echoes the failure;
        # t_progress = first step that did not exist at the loss.
        while not stop.is_set():
            if "loss" in marks and "progress" not in marks:
                if _steps(ckpt) - steps_at_kill[0]:
                    marks["progress"] = time.perf_counter()
            time.sleep(0.005)

    def echo(msg):
        if "failed" in msg and "loss" not in marks:
            marks["loss"] = time.perf_counter()
            steps_at_kill[0] = _steps(ckpt)
        if "resuming from" in msg:
            marks["relaunch"] = time.perf_counter()

    t = threading.Thread(target=watch, daemon=True)
    t.start()
    t0 = time.perf_counter()
    res = run_gang(
        [sys.executable, worker_py], 1, max_restarts=2,
        ckpt_dirs=[ckpt], log_dir=os.path.join(tmp, "logs"),
        env=env, echo=echo, backoff_base=0.0,  # measure the pipeline, not
        # the (configurable) backoff sleep
    )
    stop.set()
    t.join(timeout=1)
    total = time.perf_counter() - t0
    return {
        "attempts": res.attempts,
        "total_s": round(total, 3),
        "detect_to_relaunch_s": round(
            marks.get("relaunch", float("nan")) - marks["loss"], 3
        ),
        "mttr_s": round(
            marks.get("progress", float("nan")) - marks["loss"], 3
        ),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument("--smoke", action="store_true",
                    help="1 run, assert recovery happened, no file write")
    ap.add_argument("--no_write", action="store_true")
    args = ap.parse_args(argv)
    runs = 1 if args.smoke else args.runs

    results = []
    for i in range(runs):
        # kill in pass 3 (8 batches/pass): steps 1-2 are on disk
        r = one_run(f"/tmp/tdc_mttr_{i}", kill_hit=19)
        print(json.dumps(r), flush=True)
        assert r["attempts"] == 2, r
        results.append(r)

    mttrs = [r["mttr_s"] for r in results]
    summary = {
        "runs": runs,
        "mttr_median_s": round(statistics.median(mttrs), 3),
        "mttr_min_s": min(mttrs),
        "mttr_max_s": max(mttrs),
    }
    print("MTTR_SUMMARY", json.dumps(summary))
    if args.smoke or args.no_write:
        print("PASS: kill -> resumed progress measured through the "
              "supervisor recovery path")
        return 0

    out = os.path.join(REPO, "benchmarks", "mttr_cpu.md")
    with open(out, "w") as f:
        f.write(textwrap.dedent(f"""\
            # MTTR micro-benchmark (kill -> resumed progress)

            `benchmarks/bench_mttr.py` on the CI container (CPU backend,
            {os.cpu_count()} cores): a supervised 1-process gang runs a
            checkpointed streamed fit; TDC_FAULTS kills the worker
            (SIGKILL) at a pass-3 batch boundary; MTTR is measured from
            the supervisor observing the loss to the relaunched worker
            writing its first NEW checkpoint step — detection, alignment,
            respawn, jax import, restore, and the recovered pass all
            included. Backoff is set to 0 (its contribution is exactly
            the configured knob).

            | metric | seconds |
            |---|---|
            | MTTR median ({runs} runs) | {summary['mttr_median_s']} |
            | MTTR min | {summary['mttr_min_s']} |
            | MTTR max | {summary['mttr_max_s']} |
            | detect -> relaunch (median) | {
                round(statistics.median(
                    [r['detect_to_relaunch_s'] for r in results]), 3)} |

            Per-run data: {json.dumps(results)}

            Reading: the floor is worker startup (python + jax import,
            ~2-4 s on this box) plus the replay of the interrupted pass;
            loss detection itself is bounded by the supervisor's 0.25 s
            poll. On TPU the import cost is amortized identically, so the
            lever for production MTTR is checkpoint cadence (`ckpt_every`
            / `ckpt_every_batches`), not supervisor overhead.
            """))
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
