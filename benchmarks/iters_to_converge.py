"""Iters-to-converge evidence (round-3 VERDICT item 7; claim fixed round 5).

BASELINE.json's metric is "points/sec/chip ...; iters-to-converge" and only
the throughput half had committed numbers. This script produces the other
half: tol-driven Lloyd runs on reference-grid-shaped data vs sklearn KMeans
from the IDENTICAL init array, both run to full convergence (tol=0).

What parity actually holds (round-4 VERDICT weak #3 made the earlier "same
trajectory up to ±1 fp ties" claim honest). Two distinct mechanisms separate
the default fast path from sklearn's Lloyd, measured independently here:

1. DISTANCE PRECISION — the matmul form (‖x‖²−2x·c+‖c‖²) can flip near-tie
   assignments via f32 cancellation. kernel='refined'
   (ops/assign.assign_refined: the matmul form nominates the top-2
   champions, the exact subtract-square form re-decides) removes it.
   Measured effect on these near-origin blob configs: marginal (±1
   iteration at K=15, SSE deltas ≤ 1e-6 relative) — the iteration-count
   deltas at K=9/15 (39 vs 43, 140 vs 144) persist under exact distances,
   so they are NOT a precision artifact; they are fp summation-order
   near-ties on plateau iterations where both implementations wander
   between equal-cost states (each count is a valid exact-Lloyd run).

2. EMPTY-CLUSTER POLICY — the dominant SSE effect. At K=1024 two seeded
   clusters go empty mid-fit; our default keeps the stale centroid
   (deterministic, shared by every other driver), sklearn relocates empties
   to the highest-cost points each iteration. That policy gap — not
   precision — was the round-4 0.25%-worse-SSE row.
   empty_policy='relocate' (models/kmeans._relocate_empty) implements the
   sklearn policy; the parity rows below run kernel='refined' +
   empty_policy='relocate' and land AT OR BELOW sklearn's SSE.

Protocol per config:
  - seeded blobs (data/synthetic.make_blobs, host),
  - one shared k-means++ draw (our device k-means++, fetched to host),
  - ours: kmeans_fit(tol=0.0) — default; kernel='refined'; and
    kernel='refined' + empty_policy='relocate' (the sklearn-policy parity
    configuration),
  - sklearn: KMeans(init=<same array>, n_init=1, tol=0, algorithm='lloyd'),
  - record n_iter and final SSE for all four.

sklearn counts iterations 1..n including the final no-movement pass the same
way our shift<=0 test does; ±few-iteration deltas appear on genuine fp ties
(either index is a valid argmin) — the CSV records all counts verbatim.
Parity bar: parity_iters within a few of sklearn_iters, parity_sse ≤
sklearn_sse·(1+1e-4). The committed CSV meets it on every config.

Run:  python benchmarks/iters_to_converge.py
Writes benchmarks/iters_to_converge.csv and prints one JSON line per config.
"""

import csv
import json
import os

import numpy as np

CONFIGS = [
    # (n_obs, n_dim, K) — the reference sweep's d=5 shapes (its grid was
    # 25M-100M x 5, K in 3..15: scripts/new_experiment.py:35-50) at a size
    # sklearn's single-host Lloyd can finish tol=0 in minutes, plus a
    # wider-d MNIST-shaped config and a K=1024 headline-shaped config.
    (2_000_000, 5, 3),
    (2_000_000, 5, 9),
    (2_000_000, 5, 15),
    (60_000, 784, 10),
    (200_000, 128, 1024),
]
SEED = 123128  # the reference sweep's --seed


def main():
    import jax
    import jax.numpy as jnp
    from sklearn.cluster import KMeans

    from tdc_tpu.data import make_blobs
    from tdc_tpu.models import kmeans_fit
    from tdc_tpu.ops.init import init_kmeans_pp

    rows = []
    for n, d, k in CONFIGS:
        x, _ = make_blobs(SEED + 1, n, d, max(k, 2), to_host=True)
        key = jax.random.PRNGKey(SEED)
        sample = jnp.asarray(x[: min(n, 1 << 19)])
        init = np.asarray(init_kmeans_pp(key, sample, k), np.float32)

        ours = kmeans_fit(x, k, init=init, max_iters=300, tol=0.0)
        refined = kmeans_fit(x, k, init=init, max_iters=300, tol=0.0,
                             kernel="refined")
        parity = kmeans_fit(x, k, init=init, max_iters=300, tol=0.0,
                            kernel="refined", empty_policy="relocate")

        sk = KMeans(n_clusters=k, init=init, n_init=1, max_iter=300,
                    tol=0.0, algorithm="lloyd").fit(x)
        row = {
            "n_obs": n, "n_dim": d, "K": k,
            "ours_iters": int(ours.n_iter),
            "refined_iters": int(refined.n_iter),
            "parity_iters": int(parity.n_iter),
            "sklearn_iters": int(sk.n_iter_),
            "ours_sse": float(ours.sse),
            "refined_sse": float(refined.sse),
            "parity_sse": float(parity.sse),
            "sklearn_sse": float(sk.inertia_),
            "rel_sse_diff": abs(float(ours.sse) - sk.inertia_) / sk.inertia_,
            "parity_sse_vs_sklearn": (
                (float(parity.sse) - sk.inertia_) / sk.inertia_
            ),
        }
        rows.append(row)
        print(json.dumps(row))

    out = os.path.join(os.path.dirname(__file__), "iters_to_converge.csv")
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]), lineterminator="\n")
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
