"""Iters-to-converge evidence (round-3 VERDICT item 7).

BASELINE.json's metric is "points/sec/chip ...; iters-to-converge" and only
the throughput half had committed numbers. This script produces the other
half: tol-driven Lloyd runs on reference-grid-shaped data vs sklearn KMeans
from the IDENTICAL init array, both run to full convergence (tol=0 — exact
Lloyd from the same start converges through the same trajectory to the same
fixed point, so iteration counts and final SSE must agree up to fp ties).
That is the strongest possible parity statement: not "similar quality" but
"the same algorithm, step for step".

Protocol per config:
  - seeded blobs (data/synthetic.make_blobs, host),
  - one shared k-means++ draw (our device k-means++, fetched to host),
  - ours: kmeans_fit(tol=0.0) on the default backend (TPU when available),
  - sklearn: KMeans(init=<same array>, n_init=1, tol=0, algorithm='lloyd'),
  - record n_iter and final SSE for both.

sklearn counts iterations 1..n including the final no-movement pass the same
way our shift<=0 test does; small n_iter deltas (±1) can still appear when
an fp-tied assignment flips a point — the CSV records both counts verbatim.

Run:  python benchmarks/iters_to_converge.py
Writes benchmarks/iters_to_converge.csv and prints one JSON line per config.
"""

import csv
import json
import os

import numpy as np

CONFIGS = [
    # (n_obs, n_dim, K) — the reference sweep's d=5 shapes (its grid was
    # 25M-100M x 5, K in 3..15: scripts/new_experiment.py:35-50) at a size
    # sklearn's single-host Lloyd can finish tol=0 in minutes, plus a
    # wider-d MNIST-shaped config and a K=1024 headline-shaped config.
    (2_000_000, 5, 3),
    (2_000_000, 5, 9),
    (2_000_000, 5, 15),
    (60_000, 784, 10),
    (200_000, 128, 1024),
]
SEED = 123128  # the reference sweep's --seed


def main():
    import jax
    import jax.numpy as jnp
    from sklearn.cluster import KMeans

    from tdc_tpu.data import make_blobs
    from tdc_tpu.models import kmeans_fit
    from tdc_tpu.ops.init import init_kmeans_pp

    rows = []
    for n, d, k in CONFIGS:
        x, _ = make_blobs(SEED + 1, n, d, max(k, 2), to_host=True)
        key = jax.random.PRNGKey(SEED)
        sample = jnp.asarray(x[: min(n, 1 << 19)])
        init = np.asarray(init_kmeans_pp(key, sample, k), np.float32)

        ours = kmeans_fit(x, k, init=init, max_iters=300, tol=0.0)
        ours_iters = int(ours.n_iter)
        ours_sse = float(ours.sse)

        sk = KMeans(n_clusters=k, init=init, n_init=1, max_iter=300,
                    tol=0.0, algorithm="lloyd").fit(x)
        row = {
            "n_obs": n, "n_dim": d, "K": k,
            "ours_iters": ours_iters, "sklearn_iters": int(sk.n_iter_),
            "ours_sse": ours_sse, "sklearn_sse": float(sk.inertia_),
            "rel_sse_diff": abs(ours_sse - sk.inertia_) / sk.inertia_,
        }
        rows.append(row)
        print(json.dumps(row))

    out = os.path.join(os.path.dirname(__file__), "iters_to_converge.csv")
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]), lineterminator="\n")
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
