"""Sub-linear assignment benchmark: exact all-K vs coarse→refine stats.

What this measures, per (K, probe) config (hierarchical blobs — the
codebook-training workload's shape: coarse super-cluster structure with
per-cluster spread; a structureless uniform-random codebook is the
documented worst case for ANY IVF-style pruner, see docs/ARCHITECTURE.md
"Sub-linear assignment"):

- **assignment-phase speedup** — wall clock of ONE jitted sufficient-stats
  call (assignment + fold, the whole per-batch body the streamed drivers
  pay per pass): ops.assign.lloyd_stats (exact, distance + argmin +
  one-hot stats) vs ops.subk.lloyd_stats_subk (coarse plan + tile-pruned
  refine + sorted stats). Median of repeats, block_until_ready-bounded.
- **relative inertia loss** — (sse_coarse − sse_exact) / sse_exact of two
  full streamed_kmeans_fit runs from the same init (the fit-level number:
  assignment errors COMPOUND through centroid updates, so this is the
  honest quality metric, not single-pass agreement).
- **probe=all bit-exactness** — a streamed fit with assign="coarse",
  probe="all" must assert_array_equal the assign="exact" fit (probe
  covering every tile routes to the exact path by construction —
  ops/subk.resolve_assign; this is the safety valve the smoke pins).

CI acceptance (--smoke, the ci_tier1.sh `subk-smoke` stage): >= 2x
assignment-phase speedup at the emulated K=4096 CPU config AND
probe=all bit-exactness AND relative inertia loss <= 1e-2.
The full sweep adds the K=16,384 rows (>= 3x floor, the ROADMAP item-2
acceptance) and writes benchmarks/subk_cpu.csv.

CAVEAT (the bench_resident lesson): on CPU the exact path's matmuls run
far below an MXU's utilization, so the measured speedup tracks the FLOP
reduction less the sort/gather overhead — a conservative floor for TPU,
where the pruned path keeps feeding the MXU whole (probe·S, d) tiles by
construction (the Mesh-TensorFlow blockwise discipline).

Run:
  JAX_PLATFORMS=cpu python benchmarks/bench_subk.py           # sweep -> CSV
  JAX_PLATFORMS=cpu python benchmarks/bench_subk.py --smoke   # CI gate
"""

import csv
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "subk_cpu.csv")
FIELDS = [
    "K", "d", "n", "n_tiles", "tile_size", "probe", "scan_rows_per_block",
    "exact_stats_s", "subk_stats_s", "speedup", "rel_inertia_loss",
    "pruned_fraction", "probe_all_bitexact",
]


def hier_data(k, d, n, seed=20260804, fan=64, sub_sigma=1.0, noise=0.2):
    """Hierarchical blobs: k//fan super-centers, each fanning `fan`
    sub-centers (the codebook), points around the sub-centers. fan=64
    puts the super structure at the √K-ish granularity the coarse cells
    quantize at — the friendly end of the IVF spectrum; the ARCHITECTURE
    doc records the structureless-codebook worst case and its knobs."""
    rng = np.random.default_rng(seed)
    n_super = max(1, k // fan)
    supers = rng.uniform(-10.0, 10.0, size=(n_super, d)).astype(np.float32)
    centers = (
        np.repeat(supers, k // n_super, axis=0)
        + rng.normal(0, sub_sigma, size=(k, d))
    ).astype(np.float32)
    x = np.repeat(centers, n // k, axis=0) + rng.normal(
        0, noise, size=(n // k * k, d)
    ).astype(np.float32)
    rng.shuffle(x)
    return x, centers


def _timed(fn, xj, cj, repeats):
    jax.block_until_ready(fn(xj, cj))  # warm the compile
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(xj, cj))
        samples.append(time.perf_counter() - t0)
    return max(float(np.median(samples)), 1e-6)


def run_one(k, d, n, probe, *, iters=3, batch_rows=16384, repeats=3):
    import jax.numpy as jnp

    from tdc_tpu.data.device_cache import SizedBatches
    from tdc_tpu.models.streaming import streamed_kmeans_fit
    from tdc_tpu.ops import subk
    from tdc_tpu.ops.assign import lloyd_stats

    x, centers = hier_data(k, d, n)
    spec = subk.resolve_assign("coarse", k, probe=probe, label="bench_subk")
    xj, cj = jnp.asarray(x), jnp.asarray(centers)

    f_exact = jax.jit(lloyd_stats)
    f_subk = jax.jit(lambda xx, cc: subk.lloyd_stats_subk(xx, cc, spec))
    t_exact = _timed(f_exact, xj, cj, repeats)
    t_subk = _timed(f_subk, xj, cj, repeats)

    def mk():
        return SizedBatches(
            lambda: (x[i: i + batch_rows]
                     for i in range(0, len(x), batch_rows)),
            len(x), batch_rows,
        )

    r_exact = streamed_kmeans_fit(mk(), k, d, init=centers, max_iters=iters,
                                  tol=-1.0)
    r_coarse = streamed_kmeans_fit(mk(), k, d, init=centers, max_iters=iters,
                                   tol=-1.0, assign="coarse", probe=probe)
    r_all = streamed_kmeans_fit(mk(), k, d, init=centers, max_iters=iters,
                                tol=-1.0, assign="coarse", probe="all")
    rel = (float(r_coarse.sse) - float(r_exact.sse)) / float(r_exact.sse)
    bitexact = bool(np.array_equal(np.asarray(r_all.centroids),
                                   np.asarray(r_exact.centroids)))
    row = {
        "K": k, "d": d, "n": n,
        "n_tiles": spec.n_tiles, "tile_size": spec.tile_size,
        "probe": spec.probe,
        "scan_rows_per_block": spec.probe * spec.tile_size + spec.n_tiles,
        "exact_stats_s": round(t_exact, 6),
        "subk_stats_s": round(t_subk, 6),
        "speedup": round(t_exact / t_subk, 3),
        "rel_inertia_loss": float(f"{rel:.3e}"),
        "pruned_fraction": round(r_coarse.assign.pruned_fraction, 4),
        "probe_all_bitexact": bitexact,
    }
    print(json.dumps(row), flush=True)
    return row


def main(argv):
    smoke = "--smoke" in argv
    if smoke:
        # The emulated K=4096 CPU config: big enough that the exact pass
        # is genuinely FLOP-bound on the CI box, small enough for the CI
        # time budget. probe=8 is the √n_tiles default at T=64.
        row = run_one(4096, 32, 65536, 8, iters=3)
        ok = (
            row["speedup"] >= 2.0
            and row["probe_all_bitexact"]
            and row["rel_inertia_loss"] <= 1e-2
        )
        print(
            "SUBK-SMOKE "
            + ("PASS" if ok else "FAIL")
            + f": exact={row['exact_stats_s'] * 1e3:.0f} ms/pass, "
            f"subk={row['subk_stats_s'] * 1e3:.0f} ms/pass, "
            f"speedup={row['speedup']}x (floor 2x), "
            f"rel_inertia_loss={row['rel_inertia_loss']:.2e} "
            f"(bound 1e-2), pruned={row['pruned_fraction']}, "
            f"probe_all_bitexact={row['probe_all_bitexact']}"
        )
        return 0 if ok else 1

    rows = [
        run_one(4096, 32, 65536, 4),
        run_one(4096, 32, 65536, 8),
        run_one(4096, 32, 65536, 16),
        # The ROADMAP item-2 acceptance row: K=16,384, >= 3x floor.
        run_one(16384, 32, 65536, 8, iters=2),
        run_one(16384, 32, 65536, 11, iters=2),  # √n_tiles default at T=128
        run_one(16384, 32, 65536, 24, iters=2),
    ]
    with open(OUT, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {OUT} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
