"""CPU-mesh scaling sanity table (round-3 VERDICT missing #2).

Real multi-chip hardware is not reachable from this environment, so this
script documents the collective-efficiency story on the virtual CPU mesh
instead: a FIXED problem (strong scaling) run on 1/2/4/8 forced-host
devices, data-parallel via the same mesh/psum machinery the TPU pod path
uses. What this measures is the *overhead structure* of the sharded step —
partition + per-shard compute + XLA all-reduce — not silicon speedup: the
virtual devices share one CPU's cores, so wall-clock per step reflects how
the work partitions across the shared thread pool (it can even DROP vs
1-device, where XLA's single-device CPU executor underuses the cores), and
the signal to read is that no mesh size blows up: 8-way sharding with the
psum reduce completes within ~0.9x of the 1-device wall-clock on the same
fixed problem. Contrast the reference's empirical product — the 1-8 GPU
grid in scripts/executions_log.csv:2-321, whose K=15 rows went FLAT from
5->8 GPUs because every partial crossed PCIe to a host-side add_n reduce
(SURVEY.md §2.4): its collective cost grew with device count; psum's does
not.

Run (takes ~1 min):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/cpu_mesh_scaling.py
Writes benchmarks/cpu_mesh_scaling.csv and prints one JSON line per mesh.
"""

import csv
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

if jax.config.jax_platforms != "cpu":  # sitecustomize may pin 'axon'
    jax.config.update("jax_platforms", "cpu")

from tdc_tpu.models.kmeans import _lloyd_loop  # noqa: E402
from tdc_tpu.parallel import make_mesh  # noqa: E402
from tdc_tpu.parallel.mesh import shard_points  # noqa: E402

N, D, K, ITERS = 1 << 20, 16, 64, 8


def measure(n_dev: int, x_host, c0) -> float:
    """Seconds per Lloyd iteration on an n_dev-device mesh (fixed problem).
    min-of-reps; CPU timing needs no tunnel-safe slope machinery."""
    mesh = make_mesh(n_dev) if n_dev > 1 else None
    x = jnp.asarray(x_host)
    if mesh is not None:
        x = shard_points(x, mesh)

    def run():
        t0 = time.perf_counter()
        res = _lloyd_loop(x, c0, ITERS, -1.0, False, "xla", 0, None, None,
                          False)
        np.asarray(res.centroids)
        return time.perf_counter() - t0

    run()  # compile + warm
    return min(run() for _ in range(3)) / ITERS


def main():
    if len(jax.devices()) < 8:
        sys.exit("need 8 forced-host devices (see module docstring)")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    c0 = jnp.asarray(x[:K])
    out = os.path.join(os.path.dirname(__file__), "cpu_mesh_scaling.csv")
    rows = []
    base = None
    for n_dev in (1, 2, 4, 8):
        per = measure(n_dev, x, c0)
        base = base or per
        rows.append({
            "n_devices": n_dev,
            "ms_per_iter": round(per * 1e3, 2),
            "pt_iter_per_s": round(N / per, 1),
            "rel_wallclock_vs_1dev": round(per / base, 3),
        })
        print(json.dumps(rows[-1]))
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]), lineterminator="\n")
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
