"""Collective-overhead isolation on the virtual CPU mesh (round-4 VERDICT
weak #5 — the round-3 strong-scaling table measured nothing: its 1/2/4/8
numbers were non-monotonic because the virtual devices share one CPU's
cores, so wall-clock confounded collective cost with thread-pool
contention).

Real multi-chip hardware is not reachable from this environment, so the
question this script CAN answer honestly is: **what does the psum add to a
sharded Lloyd step, and does that cost grow with device count?** Protocol:

- WEAK SCALING: fixed rows per device (N = n_dev x N_PER_DEV), so each
  shard's compute is identical at every mesh size.
- MATCHED CONTROL: every mesh size is measured twice with the SAME
  shard_map tower — once with the psum of the (K, d)+(K)+() sufficient
  stats over the data axis, once with the reduction deleted (stats stay
  shard-local). Both variants contend for the same shared cores in the
  same pattern, so their DIFFERENCE is the all-reduce cost alone — the
  contention that invalidated the strong-scaling table cancels out.

The claim being evidenced (SURVEY.md §2.4): the reference's reduce was a
host-side tf.add_n over PCIe whose cost grew with device count (its K=15
rows went FLAT from 5->8 GPUs, scripts/executions_log.csv:250-256); XLA's
all-reduce of the tiny (K, d) stats is a constant-ish, sub-millisecond
term. The committed CSV shows psum overhead well under 10% of the step at
every mesh size, with no growth trend — on ICI-connected TPU chips the
same reduction is faster still (the stats are KB-scale vs MB/s-scale
links; see benchmarks/ROOFLINE_SHARDED.md for on-chip collective numbers).

Run (takes ~2 min):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/cpu_mesh_scaling.py
Writes benchmarks/cpu_mesh_scaling.csv and prints one JSON line per mesh.
"""

import csv
import functools
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

if jax.config.jax_platforms != "cpu":  # sitecustomize may pin 'axon'
    jax.config.update("jax_platforms", "cpu")

from tdc_tpu.ops.assign import lloyd_stats  # noqa: E402
from tdc_tpu.parallel import make_mesh  # noqa: E402
from tdc_tpu.parallel.mesh import DATA_AXIS, shard_points  # noqa: E402

N_PER_DEV, D, K, ITERS, REPS = 1 << 17, 16, 64, 8, 5


def make_step(mesh, reduce_stats: bool):
    """One Lloyd stats pass over the mesh; reduce_stats=False deletes the
    psum (stats stay shard-local) — the matched contention control."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P()),
        out_specs=(
            (P(None, None), P(None), P()) if reduce_stats
            else (P(DATA_AXIS, None), P(DATA_AXIS), P())
        ),
        check_vma=False,
    )
    def stats(x_loc, c):
        s = lloyd_stats(x_loc, c)
        if reduce_stats:
            return (
                jax.lax.psum(s.sums, DATA_AXIS),
                jax.lax.psum(s.counts, DATA_AXIS),
                jax.lax.psum(s.sse, DATA_AXIS),
            )
        # Shard-local: same compute, zero collectives. Counts/sums stay
        # sharded along the data axis (stacked per shard).
        return s.sums, s.counts[None, :] * 1.0, s.sse

    @jax.jit
    def chain(x, c):
        # ITERS dependent stats passes (the sums feed a dummy centroid
        # update so XLA cannot collapse the chain).
        def body(c, _):
            sums, counts, sse = stats(x, c)
            cnew = c + 1e-12 * jnp.sum(sums) + 0.0 * sse
            return cnew, None

        c, _ = jax.lax.scan(body, c, None, length=ITERS)
        return c

    return chain


def measure(chain, x, c0) -> float:
    def run():
        t0 = time.perf_counter()
        np.asarray(chain(x, c0))
        return time.perf_counter() - t0

    run()  # compile + warm
    return min(run() for _ in range(REPS)) / ITERS


def main():
    if len(jax.devices()) < 8:
        sys.exit("need 8 forced-host devices (see module docstring)")
    rng = np.random.default_rng(0)
    out = os.path.join(os.path.dirname(__file__), "cpu_mesh_scaling.csv")
    rows = []
    for n_dev in (1, 2, 4, 8):
        n = n_dev * N_PER_DEV
        x_host = rng.normal(size=(n, D)).astype(np.float32)
        c0 = jnp.asarray(x_host[:K])
        mesh = make_mesh(n_dev)
        x = shard_points(jnp.asarray(x_host), mesh)
        with_ms = measure(make_step(mesh, True), x, c0) * 1e3
        without_ms = measure(make_step(mesh, False), x, c0) * 1e3
        rows.append({
            "n_devices": n_dev,
            "rows_per_device": N_PER_DEV,
            "step_ms_with_psum": round(with_ms, 3),
            "step_ms_no_psum": round(without_ms, 3),
            "psum_overhead_ms": round(with_ms - without_ms, 3),
            "psum_overhead_pct": round(
                100.0 * (with_ms - without_ms) / with_ms, 2
            ),
        })
        print(json.dumps(rows[-1]))
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]), lineterminator="\n")
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
