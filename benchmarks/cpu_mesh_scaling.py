"""Collective-overhead isolation on the virtual CPU mesh (round-4 VERDICT
weak #5 — the round-3 strong-scaling table measured nothing: its 1/2/4/8
numbers were non-monotonic because the virtual devices share one CPU's
cores, so wall-clock confounded collective cost with thread-pool
contention).

Real multi-chip hardware is not reachable from this environment, so the
question this script CAN answer honestly is: **what does the psum add to a
sharded Lloyd step, and does that cost grow with device count?** Protocol:

- WEAK SCALING: fixed rows per device (N = n_dev x N_PER_DEV), so each
  shard's compute is identical at every mesh size; the full stats step
  (per-shard Lloyd stats + psum) is timed as context.
- DIRECT COLLECTIVE MEASUREMENT: the psum itself is timed in isolation —
  a chained shard_map loop whose body is nothing but the all-reduce of
  the stats-sized arrays ((K, d) f32 sums + (K,) counts + scalar sse,
  the exact payload the step reduces). No subtraction, no matched
  control: two earlier protocols (strong scaling round 3; with/without-
  psum differencing round 5a) both drowned in shared-core contention
  noise — deleting the psum changes how XLA compiles the control, so
  the "difference" measured compilation artifacts as often as the
  collective. A direct chain of 64 dependent psums is immune to both.

The claim being evidenced (SURVEY.md §2.4): the reference's reduce was a
host-side tf.add_n over PCIe whose cost grew with device count (its K=15
rows went FLAT from 5->8 GPUs, scripts/executions_log.csv:250-256); XLA's
all-reduce of the tiny (K, d) stats is a small term that does not blow up
with device count. The committed CSV shows the directly-measured psum at
single-digit milliseconds and far below the step time at every mesh size
— on ICI-connected TPU chips the same reduction is faster still (the
stats are KB-scale vs the links' GB/s).

Run (takes ~2 min):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/cpu_mesh_scaling.py
Writes benchmarks/cpu_mesh_scaling.csv and prints one JSON line per mesh.
"""

import csv
import functools
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import shard_map  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

if jax.config.jax_platforms != "cpu":  # sitecustomize may pin 'axon'
    jax.config.update("jax_platforms", "cpu")

from tdc_tpu.ops.assign import lloyd_stats  # noqa: E402
from tdc_tpu.parallel import make_mesh  # noqa: E402
from tdc_tpu.parallel.mesh import DATA_AXIS, shard_points  # noqa: E402

N_PER_DEV, D, K, ITERS, REPS = 1 << 17, 16, 64, 8, 5


def make_step(mesh):
    """One full Lloyd stats pass over the mesh (per-shard stats + psum) —
    the weak-scaling context measurement."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P()),
        out_specs=(P(None, None), P(None), P()),
        check_vma=False,
    )
    def stats(x_loc, c):
        s = lloyd_stats(x_loc, c)
        return (
            jax.lax.psum(s.sums, DATA_AXIS),
            jax.lax.psum(s.counts, DATA_AXIS),
            jax.lax.psum(s.sse, DATA_AXIS),
        )

    @jax.jit
    def chain(x, c):
        # ITERS dependent stats passes (the sums feed a dummy centroid
        # update so XLA cannot collapse the chain).
        def body(c, _):
            sums, counts, sse = stats(x, c)
            cnew = c + 1e-12 * jnp.sum(sums) + 0.0 * sse
            return cnew, None

        c, _ = jax.lax.scan(body, c, None, length=ITERS)
        return c

    return chain


PSUM_CHAIN = 64


def make_psum_chain(mesh):
    """PSUM_CHAIN dependent all-reduces of exactly the stats payload —
    the direct collective measurement (no compute, no control)."""
    n_dev = float(np.prod(mesh.devices.shape))

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, None), P(None), P()),
        out_specs=(P(None, None), P(None), P()),
        check_vma=False,
    )
    def body_once(sums, counts, sse):
        # /n_dev keeps values finite across the chain (psum of a
        # replicated operand multiplies by the axis size).
        return (
            jax.lax.psum(sums, DATA_AXIS) / n_dev,
            jax.lax.psum(counts, DATA_AXIS) / n_dev,
            jax.lax.psum(sse, DATA_AXIS) / n_dev,
        )

    @jax.jit
    def chain(sums, counts, sse):
        def body(carry, _):
            return body_once(*carry), None

        out, _ = jax.lax.scan(body, (sums, counts, sse), None,
                              length=PSUM_CHAIN)
        return out

    return chain


def measure(chain, x, c0) -> float:
    def run():
        t0 = time.perf_counter()
        np.asarray(chain(x, c0))
        return time.perf_counter() - t0

    run()  # compile + warm
    return min(run() for _ in range(REPS)) / ITERS


def measure_psum(chain, sums, counts, sse, reps=5):
    def run():
        t0 = time.perf_counter()
        out = chain(sums, counts, sse)
        np.asarray(out[2])
        return time.perf_counter() - t0

    run()  # compile + warm
    return min(run() for _ in range(reps)) / PSUM_CHAIN


def main():
    if len(jax.devices()) < 8:
        sys.exit("need 8 forced-host devices (see module docstring)")
    rng = np.random.default_rng(0)
    out = os.path.join(os.path.dirname(__file__), "cpu_mesh_scaling.csv")
    rows = []
    for n_dev in (1, 2, 4, 8):
        n = n_dev * N_PER_DEV
        x_host = rng.normal(size=(n, D)).astype(np.float32)
        c0 = jnp.asarray(x_host[:K])
        mesh = make_mesh(n_dev)
        x = shard_points(jnp.asarray(x_host), mesh)
        step_ms = measure(make_step(mesh), x, c0) * 1e3
        sums0 = jnp.zeros((K, D), jnp.float32)
        counts0 = jnp.zeros((K,), jnp.float32)
        sse0 = jnp.zeros((), jnp.float32)
        psum_ms = measure_psum(
            make_psum_chain(mesh), sums0, counts0, sse0
        ) * 1e3
        rows.append({
            "n_devices": n_dev,
            "rows_per_device": N_PER_DEV,
            "step_ms": round(step_ms, 3),
            "psum_ms": round(psum_ms, 3),
            "psum_pct_of_step": round(100.0 * psum_ms / step_ms, 2),
        })
        print(json.dumps(rows[-1]))
    with open(out, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=list(rows[0]), lineterminator="\n")
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
