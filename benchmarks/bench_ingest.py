"""Hardened-ingest overhead + resilience microbench (data/ingest.py).

Two questions an operator needs numbers for before leaving the guard on
in production (it IS on by default):

1. **Clean-path overhead** — what the per-batch integrity screen (one
   min/max pass over the host batch) and the guard plumbing cost on a
   healthy store: guarded vs PASSTHROUGH_POLICY wall time for the same
   streamed fit, plus the bit-exactness assertion.
2. **Flaky-store resilience** — with an emulated cold store failing ~30%
   of read attempts transiently (sleep-then-ConnectionError, the
   object-store-GET-timeout shape), how close the retrying guarded fit
   stays to the fault-free wall time when the retries overlap compute on
   the spill ring's producer threads, vs paying them inline.

Usage:
    JAX_PLATFORMS=cpu python benchmarks/bench_ingest.py [--smoke]

--smoke shrinks the config and asserts only the invariants (bit-exact
clean path, retries absorbed, result transparent) — suitable for ad-hoc
CI use; the chaos-smoke stage in scripts/ci_tier1.sh remains the gating
ingest proof.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--d", type=int, default=32)
    ap.add_argument("--k", type=int, default=64)
    ap.add_argument("--batch_rows", type=int, default=20_000)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--fail_every", type=int, default=3,
                    help="every Nth read attempt fails transiently")
    ap.add_argument("--read_ms", type=float, default=10.0,
                    help="emulated cold-store read latency per batch")
    args = ap.parse_args()
    if args.smoke:
        args.rows, args.d, args.k = 40_000, 16, 16
        args.batch_rows, args.iters = 8_000, 2

    from tdc_tpu.data.device_cache import SizedBatches
    from tdc_tpu.data.ingest import PASSTHROUGH_POLICY, IngestPolicy
    from tdc_tpu.data.loader import NpzStream
    from tdc_tpu.models.streaming import streamed_kmeans_fit

    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.rows, args.d)).astype(np.float32)
    init = x[: args.k]

    def fit(stream, residency="stream", **kw):
        t0 = time.perf_counter()
        res = streamed_kmeans_fit(
            stream, args.k, args.d, init=init, max_iters=args.iters,
            tol=-1.0, residency=residency, **kw,
        )
        np.asarray(res.centroids)
        return res, time.perf_counter() - t0

    # ---- 1. clean-path overhead -------------------------------------
    # Best-of-3 per side: the screen costs ~0.3 ms/batch (one min/max
    # pass over 2.5 MB), well inside single-run variance on a shared box.
    fit(NpzStream(x, args.batch_rows))  # compile warm-up (not timed)
    base = res = None
    t_off = t_on = float("inf")
    for _ in range(1 if args.smoke else 3):
        base, t = fit(NpzStream(x, args.batch_rows),
                      ingest=PASSTHROUGH_POLICY)
        t_off = min(t_off, t)
        res, t = fit(NpzStream(x, args.batch_rows))  # default: screen on
        t_on = min(t_on, t)
    np.testing.assert_array_equal(np.asarray(base.centroids),
                                  np.asarray(res.centroids))
    ovh = (t_on / t_off - 1.0) * 100.0
    print(f"clean path (best of 3): passthrough {t_off:.3f}s, guarded "
          f"{t_on:.3f}s ({ovh:+.1f}% — screen + guard plumbing), bit-exact")

    # ---- 2. flaky cold store ----------------------------------------
    class FlakyStore:
        """Ranged store: every read sleeps `read_ms` (cold GET); every
        `fail_every`-th attempt dies transiently AFTER the latency (the
        worst case: the timeout is paid before the error)."""

        def __init__(self):
            self._n = 0
            self._lock = threading.Lock()

        def read(self, i):
            time.sleep(args.read_ms / 1e3)
            with self._lock:
                self._n += 1
                n = self._n
            if n % args.fail_every == 0:
                raise ConnectionError(f"emulated store timeout (read {n})")
            return x[i * args.batch_rows:(i + 1) * args.batch_rows]

    def flaky_stream():
        store = FlakyStore()
        return SizedBatches(
            lambda: (store.read(i) for i in range(-(-args.rows
                                                    // args.batch_rows))),
            args.rows, args.batch_rows, read_batch=store.read,
        )

    policy = IngestPolicy(io_retries=4, io_backoff=0.005)
    flaky_inline, t_inline = fit(flaky_stream(), ingest=policy)
    flaky_ring, t_ring = fit(flaky_stream(), residency="spill",
                             ingest=policy)
    for r in (flaky_inline, flaky_ring):
        assert r.ingest.retries > 0, "flaky store produced no retries"
        assert r.ingest.read_failures == 0
        np.testing.assert_array_equal(np.asarray(base.centroids),
                                      np.asarray(r.centroids))
    print(f"flaky store (~1/{args.fail_every} reads fail, "
          f"{args.read_ms:.0f}ms cold reads): inline {t_inline:.3f}s "
          f"({flaky_inline.ingest.retries} retries), spill ring "
          f"{t_ring:.3f}s ({flaky_ring.ingest.retries} retries, "
          f"retry+read latency on producer threads); both bit-exact "
          f"with fault-free")
    print("PASS bench_ingest: retries transparent, clean path bit-exact")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
