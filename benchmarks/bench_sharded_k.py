"""K=16,384 d=768 regime benchmark (BASELINE.json config 5 shape).

Measures Lloyd-iteration throughput with the K-sharded machinery
(parallel/sharded_k): the Pallas blockwise online-argmin kernel inside an
N-blocked shard tower, one-hot-matmul stats, psum'd over the data axis.

On a TPU chip this runs the real shape (K=16,384, d=768) on a 1x1 mesh —
the single-chip blockwise configuration; the reference could not run
anything near this regime (its N x K x d tile OOM'd 271/320 runs at K<=15,
d=5 — scripts/distribuitedClustering.py:221-230). On CPU it shrinks shapes
and also validates the 2-D (data x model) layout on the virtual 8-device
mesh.

Prints one JSON line per configuration:
  {"metric", "value", "unit", "vs_baseline"}.
Baseline anchor as in bench.py: 22.2M pt*iter/s/GPU at K=3, d=5 scaled by
1/(K*d) -> 22.2e6 * 15 / (16384*768) ≈ 26.5 pt*iter/s at this shape.

Run:  python benchmarks/bench_sharded_k.py
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tdc_tpu.parallel.sharded_k import (
    make_mesh_2d,
    make_sharded_lloyd_step,
    sum_sq,
)

BASE_RATE = 22.2e6 * (3 * 5)  # reference best per-GPU rate x (K*d) it ran at


def measure(step, x, c, x2sum, iters_short=13, iters_long=43, repeats=3):
    """Per-iteration seconds from the slope between per-length MIN times
    (constant dispatch/fetch overhead cancels; see bench.py timing notes).
    Tunnel hiccups only ever ADD time, so min-per-length is the robust
    estimator; pairing chains into per-repeat slopes instead keeps exactly
    the pairs whose short chain was inflated and can report physically
    impossible rates (> chip peak FLOP/s — observed in round 2). BOTH
    chains must sit past the host-dispatch pipelining knee (~10 dispatches
    on the tunnel): chain(iters) is sublinear below it, so a short-chain
    baseline curves the slope and under- or over-reports by 2-3×
    (measured round 3: 1/3/9/17/33-iter chains gave asymptotic slope only
    from 17→33)."""

    def chain(iters):
        ci = c
        t0 = time.perf_counter()
        for _ in range(iters):
            ci, _, _ = step(x, ci, x.shape[0], x2sum)
        np.asarray(ci)  # true sync: D2H fetch
        return time.perf_counter() - t0

    t_short = min(chain(iters_short) for _ in range(repeats))
    t_long = min(chain(iters_long) for _ in range(repeats))
    return max((t_long - t_short) / (iters_long - iters_short), 1e-9)


def run(tag, mesh, n, k, d, kernel, block_rows):
    rng = np.random.default_rng(0)
    dtype = jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
    x = jnp.asarray(rng.normal(size=(n, d)), dtype)
    c = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    c = jax.device_put(c, NamedSharding(mesh, P("model", None)))
    step = make_sharded_lloyd_step(mesh, kernel=kernel, block_rows=block_rows)
    x2sum = sum_sq(x)  # once per fit, exactly as kmeans_fit_sharded does
    np.asarray(step(x, c, x.shape[0], x2sum)[0])  # compile + warm
    per_iter = measure(step, x, c, x2sum)
    value = n / per_iter
    base = BASE_RATE / (k * d)
    print(
        json.dumps(
            {
                "metric": f"sharded_lloyd_pt_iter_per_s_{tag}_K{k}_d{d}",
                "value": round(value, 1),
                "unit": "pt*iter/s",
                "vs_baseline": round(value / base, 2),
            }
        )
    )


def main():
    # A sitecustomize on some machines pins jax_platforms after env vars are
    # read; re-assert JAX_PLATFORMS so CPU-mesh validation runs actually land
    # on CPU (same dance as __graft_entry__.dryrun_multichip).
    import os

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        try:
            jax.config.update("jax_platforms", env_platforms)
        except Exception:
            pass

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        # The real regime, single chip: blockwise Pallas argmin + sorted
        # stats, K fully resident as one model shard. N = 2M (3 GB bf16)
        # amortizes the per-iteration fixed costs (sort prefix, dispatch)
        # that dominate at smaller N; block_rows is ignored by the pallas
        # tower (it has no (block, K) intermediates to bound).
        run("1chip", make_mesh_2d(1, 1), n=1 << 21, k=16384, d=768,
            kernel="pallas", block_rows=0)
    else:
        # CPU dev/CI: shrunken single-device shape (interpret-mode Pallas is
        # too slow; use the XLA tower) ...
        run("1dev_cpu", make_mesh_2d(1, 1), n=1 << 14, k=2048, d=128,
            kernel="xla", block_rows=1 << 12)
        # ... and the 2-D (data x model) layout on the virtual mesh.
        if len(jax.devices()) >= 8:
            run("2x4_cpu", make_mesh_2d(2, 4), n=1 << 14, k=2048, d=128,
                kernel="xla", block_rows=1 << 12)


if __name__ == "__main__":
    main()
