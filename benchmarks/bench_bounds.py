"""Bounded (zero-loss Elkan/Hamerly) assignment benchmark.

What this measures, per (K, bounds-kind, data-shape) config:

- **distance-eval reduction** — `result.bounds.skipped_fraction`: the
  fraction of the exact all-K path's point·centroid distance evaluations
  the triangle-inequality bounds skipped across the resident iterations
  (EXACT device-side accounting off the donated carry, not a model).
  Iteration 1 streams (and fills the HBM cache), iteration 2 is the
  bounds-initializing full re-scan, so the skip fraction climbs from ~0
  and the gate reads it AT iteration 5 — the "does it pay off within a
  realistic fit" bar.
- **bit-exactness** — centroids AND final SSE of the bounded fit must
  `assert_array_equal` the `assign="exact"` fit. This is the zero-loss
  contract: unlike the coarse path (bench_subk.py), there is no inertia
  loss column because there is no loss.
- **wall-clock speedup** — per-fit wall time vs exact (informational on
  CPU: the packed-block `lax.cond` skips real work, but the sort/pack
  overhead and the one-hot stats matmul — which bounds cannot prune —
  bound the CPU win well below the eval reduction; ROOFLINE methodology
  applies on TPU where the distance matmul dominates).

CI acceptance (--smoke, the ci_tier1.sh `bounds-smoke` stage): on the
blobs config at K=1024, >= 60% of distance evaluations skipped by
iteration 5 AND bounded centroids/SSE bit-exact vs assign="exact".

The full sweep adds K=4096, the elkan per-tile variant, and the
ADVERSARIAL no-structure case (uniform random points and centroids, no
cluster structure: every centroid moves every iteration, bounds stay
loose, pruning ~nothing — the documented worst case, committed so the
CSV states it instead of hiding it), and writes benchmarks/bounds_cpu.csv.

Run:
  JAX_PLATFORMS=cpu python benchmarks/bench_bounds.py           # sweep -> CSV
  JAX_PLATFORMS=cpu python benchmarks/bench_bounds.py --smoke   # CI gate
"""

import csv
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "bounds_cpu.csv")
FIELDS = [
    "K", "d", "n", "bounds", "data", "iters",
    "dist_evals", "dist_evals_exact", "skipped_fraction",
    "exact_fit_s", "bounded_fit_s", "speedup", "bitexact",
]


def blobs(k, d, n, seed=20260804, noise=0.25):
    """Separated blobs — the workload bounds exist for: assignments
    stabilize after a few iterations, so almost every point becomes
    provably unchanged."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10.0, 10.0, size=(k, d)).astype(np.float32)
    x = (np.repeat(centers, n // k, axis=0)
         + rng.normal(0, noise, size=(n // k * k, d)).astype(np.float32))
    rng.shuffle(x)
    init = centers + rng.normal(0, 0.3, size=(k, d)).astype(np.float32)
    return x.astype(np.float32), init.astype(np.float32)


def structureless(k, d, n, seed=20260804):
    """The adversarial case: uniform points, uniform centroids — no
    cluster structure, centroids keep moving, bounds prune ~nothing."""
    rng = np.random.default_rng(seed)
    x = rng.uniform(-1.0, 1.0, size=(n, d)).astype(np.float32)
    init = rng.uniform(-1.0, 1.0, size=(k, d)).astype(np.float32)
    return x, init


def run_one(k, d, n, bounds, data, *, iters=5, batch_rows=16384):
    from tdc_tpu.data.device_cache import SizedBatches
    from tdc_tpu.models.streaming import streamed_kmeans_fit

    x, init = (blobs if data == "blobs" else structureless)(k, d, n)

    def mk():
        return SizedBatches(
            lambda: (x[i: i + batch_rows]
                     for i in range(0, len(x), batch_rows)),
            len(x), batch_rows,
        )

    t0 = time.perf_counter()
    r_exact = streamed_kmeans_fit(mk(), k, d, init=init, max_iters=iters,
                                  tol=-1.0, residency="hbm")
    t_exact = time.perf_counter() - t0
    t0 = time.perf_counter()
    r_b = streamed_kmeans_fit(mk(), k, d, init=init, max_iters=iters,
                              tol=-1.0, residency="hbm",
                              assign="bounded", bounds=bounds)
    t_bounded = time.perf_counter() - t0
    bitexact = bool(
        np.array_equal(np.asarray(r_b.centroids),
                       np.asarray(r_exact.centroids))
        and np.array_equal(np.asarray(r_b.sse), np.asarray(r_exact.sse))
    )
    rep = r_b.bounds
    row = {
        "K": k, "d": d, "n": n, "bounds": bounds, "data": data,
        "iters": iters,
        "dist_evals": rep.dist_evals,
        "dist_evals_exact": rep.dist_evals_exact,
        "skipped_fraction": round(rep.skipped_fraction, 4),
        "exact_fit_s": round(t_exact, 3),
        "bounded_fit_s": round(t_bounded, 3),
        "speedup": round(t_exact / max(t_bounded, 1e-9), 3),
        "bitexact": bitexact,
    }
    print(json.dumps(row), flush=True)
    return row


def main(argv):
    smoke = "--smoke" in argv
    if smoke:
        # The issue-14 gate: K=1024 blobs, >= 60% of distance evals
        # skipped BY iteration 5, results bit-exact vs assign="exact".
        row = run_one(1024, 32, 65536, "hamerly", "blobs", iters=5)
        ok = row["skipped_fraction"] >= 0.60 and row["bitexact"]
        print(
            "BOUNDS-SMOKE "
            + ("PASS" if ok else "FAIL")
            + f": skipped={row['skipped_fraction']:.2%} of distance evals "
            f"by iteration {row['iters']} (floor 60%), "
            f"bitexact={row['bitexact']}, "
            f"exact={row['exact_fit_s']}s bounded={row['bounded_fit_s']}s"
        )
        return 0 if ok else 1

    rows = [
        run_one(1024, 32, 65536, "hamerly", "blobs", iters=5),
        run_one(1024, 32, 65536, "elkan", "blobs", iters=5),
        run_one(1024, 32, 65536, "hamerly", "blobs", iters=10),
        run_one(4096, 32, 65536, "hamerly", "blobs", iters=5),
        run_one(4096, 32, 65536, "elkan", "blobs", iters=5),
        # The documented adversarial worst case: prune ~nothing, still
        # bit-exact (zero-loss means the fallback cost is bounded by one
        # tighten pass per point, not a wrong answer).
        run_one(1024, 32, 65536, "hamerly", "structureless", iters=5),
    ]
    with open(OUT, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {OUT} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
