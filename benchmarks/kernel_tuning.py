"""Fused-Lloyd kernel tuning harness: (block_n, halves) sweep + trace capture.

Run on the bench chip to (a) re-tune bench.py's FUSED_BLOCK_N and the
sub-block split (`halves`, the MXU/VPU-overlap lever in
ops/pallas_kernels.py:_fused_lloyd_kernel), and (b) capture a profiler trace
of the winner for the roofline analysis (benchmarks/ROOFLINE.md).

Timing protocol matches bench.py: slope between a short and a long
data-dependent chain of Lloyd iterations, so constant dispatch/fetch/tunnel
overhead cancels; per chain length the MIN over repetitions is taken first —
tunnel hiccups only ever ADD time, so min-per-length is robust where a
min-over-paired-slopes keeps exactly the pairs whose short chain was
inflated (observed as negative slopes).

Usage: python benchmarks/kernel_tuning.py [--trace_dir DIR] [--iters 24]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tdc_tpu.ops.assign import apply_centroid_update
from tdc_tpu.ops.pallas_kernels import lloyd_stats_fused

K = 1024
D = 128

# (block_n, halves) grid: halves=1 is the strictly sequential kernel; the
# larger splits overflowed VMEM (JaxRuntimeError) in the round-3 sweep and
# stay here so regressions in the VMEM model are noticed.
CONFIGS = [
    (1024, 1), (1024, 2), (2048, 1), (2048, 2), (2048, 4), (2048, 8),
    (4096, 4), (4096, 8),
]


def chain_time(step, x, c, iters):
    ci = c
    t0 = time.perf_counter()
    for _ in range(iters):
        ci = step(x, ci.astype(jnp.bfloat16))
    np.asarray(ci)
    return time.perf_counter() - t0


def measure(step, x, c, iters_long, n, reps=3):
    np.asarray(step(x, c.astype(jnp.bfloat16)))  # compile + warm
    t_short = min(chain_time(step, x, c, 4) for _ in range(reps))
    t_long = min(chain_time(step, x, c, iters_long) for _ in range(reps))
    return n / ((t_long - t_short) / (iters_long - 4))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--trace_dir", default=None)
    p.add_argument("--iters", type=int, default=24)
    p.add_argument("--n", type=int, default=8 << 20)
    args = p.parse_args()

    key = jax.random.PRNGKey(0)
    kx, kc = jax.random.split(key)
    c = jax.random.normal(kc, (K, D), jnp.bfloat16)
    x = jax.random.normal(kx, (args.n, D), jnp.bfloat16)

    results = {}
    for bn, halves in CONFIGS:
        @jax.jit
        def step(x, c, bn=bn, halves=halves):
            return apply_centroid_update(
                lloyd_stats_fused(x, c, block_n=bn, halves=halves), c
            )

        try:
            rate = measure(step, x, c, args.iters, args.n)
        except Exception as e:  # VMEM overflow at large bn*halves
            print(f"bn={bn} halves={halves}: {type(e).__name__}")
            continue
        results[(bn, halves)] = rate
        print(f"bn={bn} halves={halves}: {rate / 1e6:.1f} M pt*iter/s")

    best = max(results, key=results.get)
    print(f"best: bn={best[0]} halves={best[1]} "
          f"at {results[best] / 1e6:.1f} M pt*iter/s")

    if args.trace_dir:
        bn, halves = best

        @jax.jit
        def step(x, c):
            return apply_centroid_update(
                lloyd_stats_fused(x, c, block_n=bn, halves=halves), c
            )

        np.asarray(step(x, c.astype(jnp.bfloat16)))
        with jax.profiler.trace(args.trace_dir):
            ci = c
            for _ in range(8):
                ci = step(x, ci.astype(jnp.bfloat16))
            np.asarray(ci)
        print(f"trace written to {args.trace_dir}")


if __name__ == "__main__":
    main()
