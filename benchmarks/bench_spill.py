"""Spill-tier benchmark: synchronous streaming vs the async H2D
double-buffered prefetch ring (data/spill.py), plus the measured overlap
fraction.

What this measures, per config (the bench_resident.py iteration-differencing
methodology):

- **streamed_iter_s / spill_iter_s** — the marginal cost of ONE more Lloyd
  iteration on each path: `(wall(I2) - wall(I1)) / (I2 - I1)` with tol=-1
  pinning the iteration counts. Everything iteration count does not scale
  (compile, init, the final reporting pass) cancels, isolating exactly what
  the spill tier claims to change: per-batch host staging + H2D copy paid
  serially in line with compute (streamed) vs hidden behind the previous
  batch's compute on the producer thread (spill).
- **overlap_fraction** — (copy time hidden) / (total copy time), by the
  same differencing: `(streamed_iter_s - spill_iter_s) / copy_s_per_pass`,
  where `copy_s_per_pass` is the fit result's measured producer pipeline
  time (`h2d.copy_s`: stream read + decode + pad + device_put + transfer
  completion). The wall-clock delta IS the copy time that left the
  critical path. The per-fit `h2d` report also carries the raw stall
  accounting (`stall_s`, exported as `tdc_h2d_copy_stall_seconds_total`
  on `/metrics`) — the conservative consumer-side view a dashboard can
  alarm on.
- **bitexact** — spill centroids vs plain-streamed centroids via
  `np.array_equal` (the PR-5 parity bar): the ring changes WHEN batches are
  staged, never WHAT the accumulate ops see.

The stream models the realistic over-HBM-budget source: an int8-quantized
host store decoded to f32 per batch (a dataset kept compressed in host RAM
precisely because it cannot live in HBM), with an optional per-batch
`read_latency_s` emulating a cold-store read (memmap page fault / NFS /
object-store GET — the latency component of a true out-of-core pass).

CAVEAT — what a 1-core CI box can and cannot show. The CI host exposes a
single core, so producer-side CPU work (the int8 decode, the memcpy)
cannot genuinely parallelize with XLA compute there — only LATENCY (the
emulated cold read; on real hardware also the DMA-driven H2D itself)
truly overlaps. The smoke therefore gates the latency-hiding claim
(read_latency_s > 0, the regime the spill tier exists for), and the
warm-store sweep rows document the CPU-work-bound behavior honestly
(speedup ≈ 1x, noise-dominated on one core). On a real TPU host the
decode rides a spare host core and the copy rides the DMA engine, so the
smoke's floor is conservative for both components.

Run:
  JAX_PLATFORMS=cpu python benchmarks/bench_spill.py           # sweep -> CSV
  python benchmarks/bench_spill.py --smoke                     # CI gate

Writes benchmarks/spill_cpu.csv; one JSON line per config on stdout.
"""

import csv
import json
import os
import sys
import time

# Runnable as a plain script from any cwd (the serve_latency.py pattern).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tdc_tpu.data.device_cache import SizedBatches  # noqa: E402
from tdc_tpu.models.streaming import streamed_kmeans_fit  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "spill_cpu.csv")
FIELDS = [
    "config", "K", "d", "n", "batch_rows", "n_batches", "i1", "i2",
    "read_latency_ms", "streamed_iter_s", "spill_iter_s", "speedup",
    "overlap_fraction", "copy_s_per_pass", "stall_s_per_pass",
    "h2d_mb_per_pass", "bitexact",
]


def _int8_store(n, d, k, seed=123129):
    """Clustered data quantized to an int8 host store + per-column scale —
    the compressed at-rest form an over-budget dataset streams from."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10.0, 10.0, size=(k, d)).astype(np.float32)
    x = np.repeat(centers, n // k, axis=0) + rng.normal(
        0, 0.5, size=(n // k * k, d)
    ).astype(np.float32)
    rng.shuffle(x)
    scale = (np.abs(x).max(axis=0) / 127.0).astype(np.float32)
    x8 = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
    return x8, scale, centers


def _stream(x8, scale, batch_rows, read_latency_s=0.0):
    """Decode int8 -> f32 per batch, after an optional emulated cold-store
    read wait. Exposes the spill ring's RANGED protocol (`read_batch`,
    thread-safe) so the ring can run `slots` reads concurrently — which is
    how the per-read latency actually hides (cold reads overlap each other
    AND compute), while the decode (astype + multiply) is plain CPU work."""

    def read(i):
        if read_latency_s > 0.0:
            time.sleep(read_latency_s)
        s = i * batch_rows
        return x8[s : s + batch_rows].astype(np.float32) * scale

    def gen():
        for i in range(-(-len(x8) // batch_rows)):
            yield read(i)

    return SizedBatches(gen, len(x8), batch_rows, itemsize=4,
                        read_batch=read)


def _fit(x8, scale, centers, k, d, batch_rows, iters, residency,
         read_latency_s=0.0):
    batches = _stream(x8, scale, batch_rows, read_latency_s)
    t0 = time.perf_counter()
    res = streamed_kmeans_fit(
        batches, k, d, init=centers, max_iters=iters, tol=-1.0,
        residency=residency,
    )
    jax.block_until_ready(res.centroids)
    return time.perf_counter() - t0, res


def run_one(config, k, d, n, batch_rows, i1, i2, repeats=3,
            read_latency_s=0.0):
    x8, scale, centers = _int8_store(n, d, k)

    # Warm the compile caches on both paths.
    _fit(x8, scale, centers, k, d, batch_rows, i1, "stream")
    _fit(x8, scale, centers, k, d, batch_rows, i1, "spill")

    def marginal(residency):
        samples, r2 = [], None
        for _ in range(repeats):
            w1, _ = _fit(x8, scale, centers, k, d, batch_rows, i1, residency,
                         read_latency_s)
            w2, r2 = _fit(x8, scale, centers, k, d, batch_rows, i2, residency,
                          read_latency_s)
            samples.append((w2 - w1) / (i2 - i1))
        # Median across repeats absorbs scheduler noise; clamp like
        # bench_resident.marginal so a loaded box cannot crash the smoke.
        return max(float(np.median(samples)), 1e-6), r2

    s_iter, rs = marginal("stream")
    p_iter, rp = marginal("spill")
    h = rp.h2d
    passes = i2 + 1  # iterations + the final reporting pass
    copy_per_iter = h.copy_s / passes
    # (copy time hidden) / (total copy time) by differencing: the
    # wall-clock per-iteration delta is exactly the staging-pipeline time
    # that left the critical path (everything else is identical between
    # the two paths — same ops, same order, bit-exact results).
    overlap = (
        max(0.0, min(1.0, (s_iter - p_iter) / copy_per_iter))
        if copy_per_iter > 0 else 0.0
    )
    row = {
        "config": config, "K": k, "d": d, "n": n,
        "batch_rows": batch_rows, "n_batches": -(-n // batch_rows),
        "i1": i1, "i2": i2,
        "read_latency_ms": round(read_latency_s * 1e3, 1),
        "streamed_iter_s": round(s_iter, 6),
        "spill_iter_s": round(p_iter, 6),
        "speedup": round(s_iter / p_iter, 3),
        "overlap_fraction": round(overlap, 3),
        "copy_s_per_pass": round(copy_per_iter, 6),
        "stall_s_per_pass": round(h.stall_s / passes, 6),
        "h2d_mb_per_pass": round(h.h2d_bytes / passes / 2**20, 2),
        "bitexact": bool(
            np.array_equal(np.asarray(rs.centroids), np.asarray(rp.centroids))
        ),
    }
    print(json.dumps(row), flush=True)
    return row


def main(argv):
    smoke = "--smoke" in argv

    if smoke:
        # Compute-heavy sizing: few large batches so per-batch Python
        # dispatch is amortized and the marginal streamed iteration is
        # cold read + decode + H2D copy + stats compute in series; the
        # ring's concurrent reads hide the latency behind compute. The
        # 25 ms/batch emulated cold read makes the gate load-robust on
        # the 1-core CI box (latency hiding survives contention;
        # CPU-work hiding does not — module docstring) while staying in
        # range of real NFS/object-store latencies for 8 MB reads.
        # 1.2x floor; measured headroom documented in spill_cpu.csv.
        row = run_one("smoke_cold", k=16, d=64, n=1 << 18,
                      batch_rows=1 << 15, i1=2, i2=5, repeats=3,
                      read_latency_s=0.025)
        ok = row["speedup"] >= 1.2 and row["bitexact"]
        print(
            "SPILL-SMOKE "
            + ("PASS" if ok else "FAIL")
            + f": streamed={row['streamed_iter_s'] * 1e3:.1f} ms/iter, "
            f"spill={row['spill_iter_s'] * 1e3:.1f} ms/iter, "
            f"speedup={row['speedup']}x (floor 1.2x), "
            f"overlap={row['overlap_fraction']}, "
            f"stall={row['stall_s_per_pass'] * 1e3:.1f} ms/pass of "
            f"copy={row['copy_s_per_pass'] * 1e3:.1f} ms/pass, "
            f"bitexact={row['bitexact']}"
        )
        return 0 if ok else 1

    rows = [
        # The smoke's cold-store config (emulated read latency: the
        # honestly-overlappable component on this 1-core box) ...
        run_one("smoke_cold", k=16, d=64, n=1 << 18, batch_rows=1 << 15,
                i1=2, i2=5, read_latency_s=0.025),
        # ... deeper cold read: more to hide — the win grows with the
        # latency until the concurrent readers saturate ...
        run_one("colder", k=16, d=64, n=1 << 18, batch_rows=1 << 15,
                i1=2, i2=5, read_latency_s=0.050),
        # ... warm store: decode + memcpy only — pure CPU work the 1-core
        # CAVEAT says cannot genuinely parallelize with compute; any
        # measured win here is scheduling slack, treat as noise-prone and
        # ungated (real hosts hide this for real on spare cores).
        run_one("warm_cpu_bound", k=16, d=64, n=1 << 18, batch_rows=1 << 15,
                i1=2, i2=5),
        # ... compute-dominated (large K): copies are a small fraction,
        # speedup honestly shrinks toward 1x while overlap stays high —
        # the copies still hide, there is just less of them to hide.
        run_one("compute_heavy", k=128, d=64, n=1 << 18,
                batch_rows=1 << 15, i1=2, i2=5, read_latency_s=0.025),
    ]
    with open(OUT, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {OUT} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
