"""SLO observatory harness (ROADMAP 3c): drive the serving stack to
MEASURED saturation with the open-loop generator and publish the
tail-latency-vs-offered-load curve off the /metrics scrape.

    JAX_PLATFORMS=cpu python benchmarks/bench_load.py \
        --csv benchmarks/load_cpu.csv --out benchmarks/LOAD.md

    python benchmarks/bench_load.py --smoke     # the load-smoke tier-1 gate

Everything this harness reports is scrape-derived: percentiles come from
`tdc_serve_latency_ms` bucket deltas between two /metrics scrapes
(obs/metrics.quantile_from_buckets), sheds from `tdc_serve_shed_total`,
state from `tdc_serve_admission_state` — the client-side stopwatch
window is carried only as a cross-check column. If the committed curve
is wrong, the production dashboards are wrong the same way, which is the
point: the harness certifies the scrape as an SLO instrument.

Saturation is MEASURED, not assumed: a calibration ramp doubles a
constant offered rate until goodput stops following it; the sweep and
the 2x-overload spike are expressed as multiples of that measurement, so
the harness lands at the knee on any box.

Service time is emulated (`--service_ms`, default 20): each coalesced
device batch holds its executor slot for a fixed extra sleep, exactly
like bench_spill emulates cold-store latency — the CPU CI's tiny-model
predict is so fast that saturation would otherwise sit at the Python-
overhead floor, measuring the harness instead of the serving stack.
`--service_ms 0` on real silicon measures the hardware.

The `--smoke` contract (gated in scripts/ci_tier1.sh):
  - at >= 2x measured saturation, accepted-request p999 (scrape-derived)
    stays under --p999_bound_ms;
  - the governor sheds: nonzero `tdc_serve_shed_total` on the scrape,
    and the scrape's shed count equals the client's 503-shed count
    (every rejected request is accounted);
  - sheds stay FAIR: the background tenant's goodput survives the hot
    tenant's flood;
  - zero requests hang; after the spike the governor exits shedding,
    /readyz returns 200, and a post-spike window sheds nothing.
"""

from __future__ import annotations

import argparse
import csv
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tdc_tpu.obs.loadgen import (  # noqa: E402
    InprocTarget,
    make_shape,
    run_open_loop,
)
from tdc_tpu.obs.metrics import (  # noqa: E402
    scrape_counter,
    scrape_quantile,
)

D = 16  # request feature width
MODELS = ("hot", "bg")
MIX = {"hot": 0.85, "bg": 0.15}  # the tenancy story: one tenant dominates


def build_app(*, service_ms: float, max_queue_rows: int = 1024,
              max_batch_rows: int = 128, max_wait_ms: float = 4.0,
              p99_wait_high_ms: float = 250.0, min_shed_s: float = 0.4):
    """ServeApp with two tiny kmeans tenants and an emulated per-batch
    service time (documented above); governor tuned so the smoke's
    overload/recovery cycle fits in seconds, not minutes."""
    import jax

    from tdc_tpu.models.kmeans import kmeans_fit
    from tdc_tpu.models.persist import save_fitted
    from tdc_tpu.serve import GovernorConfig, PredictEngine, ServeApp

    class _SlowEngine(PredictEngine):
        """PredictEngine plus a fixed post-batch sleep emulating device
        service time: the executor slot (and therefore the dispatcher's
        one-batch-at-a-time pipeline) is held exactly as a slower real
        device would hold it."""

        service_ms = 0.0

        def run(self, entry, method, x):
            out = super().run(entry, method, x)
            if self.service_ms > 0:
                time.sleep(self.service_ms / 1e3)
            return out

    import tempfile

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, D)).astype(np.float32)
    root = tempfile.mkdtemp(prefix="tdc_bench_load_")
    for i, mid in enumerate(MODELS):
        km = kmeans_fit(x, 16, key=jax.random.PRNGKey(i), max_iters=4)
        save_fitted(os.path.join(root, mid), km)

    engine = _SlowEngine()
    engine.service_ms = float(service_ms)
    app = ServeApp(
        engine=engine,
        poll_interval=0,
        max_batch_rows=max_batch_rows,
        max_wait_ms=max_wait_ms,
        max_queue_rows=max_queue_rows,
        request_timeout=30.0,
        governor_config=GovernorConfig(
            p99_wait_high_ms=p99_wait_high_ms,
            min_shed_s=min_shed_s,
            eval_interval_s=0.1,
            retry_after_s=0.5,
        ),
    )
    for mid in MODELS:
        app.registry.add(mid, os.path.join(root, mid))
    app.start()
    for mid in MODELS:
        app.engine.warmup(app.registry.get(mid), methods=("predict",),
                          buckets=[8, 16, 32, 64, 128])
    return app


def settle(app, timeout_s: float = 10.0) -> bool:
    """Between cells: wait for the queue to drain and the governor to
    exit shedding (probe-driven, like an LB would see it)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status, _, _ = app.handle_get("/readyz")
        if status == 200 and app.batcher.queued_rows == 0:
            return True
        time.sleep(0.1)
    return False


def run_cell(app, *, shape: str, base_rps: float, peak_rps: float | None,
             duration_s: float, seed: int, mix=MIX,
             max_workers: int = 256) -> dict:
    """One open-loop cell: scrape, fire the schedule, scrape again, and
    report everything from the two scrapes' deltas."""
    target = InprocTarget(app)
    before = target.scrape()
    rep = run_open_loop(
        target,
        make_shape(shape, base_rps=base_rps, peak_rps=peak_rps,
                   duration_s=duration_s),
        duration_s, d=D, model_mix=mix, seed=seed,
        max_workers=max_workers, hang_timeout_s=45.0,
    )
    after = target.scrape()

    def q(quant, match=None):
        ms = scrape_quantile(after, "tdc_serve_latency_ms", quant,
                             match or {"endpoint": "predict"},
                             baseline=before)
        return round(ms, 2) if ms == ms else float("nan")

    sheds = scrape_counter(after, "tdc_serve_shed_total") - \
        scrape_counter(before, "tdc_serve_shed_total")
    qp99 = scrape_quantile(after, "tdc_serve_queue_wait_ms", 0.99,
                           baseline=before)
    readyz_status, _, _ = app.handle_get("/readyz")
    return {
        "shape": shape,
        "offered_rps": round(rep.offered_rps, 1),
        "goodput_rps": round(rep.goodput_rps, 1),
        "ok": rep.counts["ok"],
        "shed": rep.counts["shed"],
        "backpressure": rep.counts["backpressure"],
        "drain": rep.counts["drain"],
        "error": rep.counts["error"],
        "hung": rep.hung,
        "late": rep.late_fires,
        "p50_ms": q(0.50),
        "p99_ms": q(0.99),
        "p999_ms": q(0.999),
        "queue_p99_ms": round(qp99, 2) if qp99 == qp99 else float("nan"),
        "client_p50_ms": round(rep.client_percentile(0.50), 2),
        "client_p99_ms": round(rep.client_percentile(0.99), 2),
        "shed_scrape": int(sheds),
        "admission_state": int(scrape_counter(
            after, "tdc_serve_admission_state")),
        "readyz": readyz_status,
        "by_model": rep.by_model,
    }


def measure_saturation(app, *, seed: int = 11, start_rps: float = 30.0,
                       cell_s: float = 2.0) -> float:
    """Calibration ramp: double a constant offered rate until goodput
    stops following it (goodput < 80% of offered). Returns the highest
    goodput observed — the measured capacity every other cell is
    expressed against."""
    best, rps = 0.0, start_rps
    for i in range(8):
        cell = run_cell(app, shape="constant", base_rps=rps, peak_rps=None,
                        duration_s=cell_s, seed=seed + i)
        best = max(best, cell["goodput_rps"])
        print(f"calibrate: offered={cell['offered_rps']} "
              f"goodput={cell['goodput_rps']} shed={cell['shed']}",
              flush=True)
        settle(app)
        if cell["goodput_rps"] < 0.8 * cell["offered_rps"]:
            break
        rps *= 2.0
    return best


# ---------------------------------------------------------------------------
# The committed sweep (load_cpu.csv + LOAD.md)
# ---------------------------------------------------------------------------

SWEEP_FRACTIONS = (0.25, 0.5, 0.75, 0.9, 1.0, 1.1, 1.25, 1.5, 2.0, 2.5)

CSV_COLUMNS = (
    "shape", "offered_rps", "goodput_rps", "ok", "shed", "backpressure",
    "drain", "error", "hung", "late", "p50_ms", "p99_ms", "p999_ms",
    "queue_p99_ms", "client_p50_ms", "client_p99_ms", "shed_scrape",
    "admission_state", "readyz",
)


def run_sweep(app, sat: float, *, cell_s: float, seed: int) -> list[dict]:
    cells = []
    for i, frac in enumerate(SWEEP_FRACTIONS):
        cell = run_cell(app, shape="constant", base_rps=frac * sat,
                        peak_rps=None, duration_s=cell_s, seed=seed + i)
        cell["load_frac"] = frac
        cells.append(cell)
        print(f"sweep {frac:>4}x: offered={cell['offered_rps']} "
              f"goodput={cell['goodput_rps']} p50={cell['p50_ms']} "
              f"p99={cell['p99_ms']} p999={cell['p999_ms']} "
              f"shed={cell['shed_scrape']}", flush=True)
        settle(app)
    # Two shaped programs on top of the constant sweep: the 2x spike
    # (the overload contract's shape) and a diurnal day.
    for shape, base, peak in (("spike", 0.4 * sat, 2.0 * sat),
                              ("diurnal", 0.3 * sat, 1.3 * sat)):
        cell = run_cell(app, shape=shape, base_rps=base, peak_rps=peak,
                        duration_s=3 * cell_s, seed=seed + 50)
        cell["load_frac"] = round(peak / sat, 2)
        cells.append(cell)
        print(f"sweep {shape}: offered={cell['offered_rps']} "
              f"goodput={cell['goodput_rps']} p999={cell['p999_ms']} "
              f"shed={cell['shed_scrape']}", flush=True)
        settle(app)
    return cells


def render_md(cells: list[dict], sat: float, args) -> str:
    knee = next((c for c in cells if c["shape"] == "constant"
                 and c["goodput_rps"] < 0.9 * c["offered_rps"]), None)
    onset = next((c for c in cells if c["shape"] == "constant"
                  and c["shed_scrape"] > 0), None)
    lines = [
        "# Serving under offered load (SLO observatory, "
        "benchmarks/bench_load.py)",
        "",
        f"Open-loop Poisson traffic against the in-process serving stack "
        f"(2 kmeans tenants K=16 d={D}, mix hot:bg = "
        f"{MIX['hot']}:{MIX['bg']}), emulated per-batch service time "
        f"{args.service_ms} ms, micro-batch max_wait "
        f"{args.max_wait_ms} ms, queue bound {args.max_queue_rows} rows, "
        f"governor p99-wait target {args.p99_wait_high_ms} ms. "
        f"**Measured saturation: {sat:.0f} req/s** (calibration ramp); "
        "offered load below is expressed against it.",
        "",
        "All percentiles are **scrape-derived**: "
        "`tdc_serve_latency_ms` bucket deltas between the cell's two "
        "`/metrics` scrapes through "
        "`obs.metrics.quantile_from_buckets` — the same numbers a "
        "Prometheus stack computes. `client p50/p99` is the client-side "
        "stopwatch kept only as a cross-check; `shed` (client-counted "
        "503s with `reason: shed`) must equal `shed_scrape` "
        "(`tdc_serve_shed_total` delta): every rejected request is "
        "accounted on the scrape.",
        "",
        "| load | shape | offered rps | goodput rps | p50 ms | p99 ms "
        "| p999 ms | queue p99 ms | shed | backpr | hung | client "
        "p50/p99 | state |",
        "|---|---|---|---|---|---|---|---|---|---|---|---|---|",
    ]
    for c in cells:
        lines.append(
            f"| {c['load_frac']}x | {c['shape']} | {c['offered_rps']} "
            f"| {c['goodput_rps']} | {c['p50_ms']} | {c['p99_ms']} "
            f"| {c['p999_ms']} | {c['queue_p99_ms']} "
            f"| {c['shed_scrape']} | {c['backpressure']} | {c['hung']} "
            f"| {c['client_p50_ms']}/{c['client_p99_ms']} "
            f"| {'shed' if c['admission_state'] == 1 else 'ok'} |"
        )
    lines.append("")
    if knee is not None:
        lines.append(
            f"**Knee:** goodput first falls behind offered load at "
            f"{knee['load_frac']}x saturation "
            f"({knee['offered_rps']} req/s offered, "
            f"{knee['goodput_rps']} req/s served)."
        )
    if onset is not None:
        lines.append(
            f"**Shed onset:** the admission governor first sheds at "
            f"{onset['load_frac']}x "
            f"({onset['shed_scrape']} sheds in {onset['ok']}+"
            f"{onset['shed_scrape']} offered)."
        )
    over = [c for c in cells if c["shape"] == "constant"
            and c["load_frac"] >= 2.0]
    if over:
        worst = max(c["p999_ms"] for c in over)
        lines.append(
            f"**Overload bound:** at >= 2x saturation, accepted-request "
            f"p999 stays at {worst} ms (stated bound: "
            f"{args.p999_bound_ms} ms) while the governor sheds the "
            "excess — open-loop offered load does NOT collapse the "
            "accepted tail, it is converted into counted 503s with "
            "`Retry-After`. Zero hung requests in every cell."
        )
    lines += [
        "",
        "CPU-CI proof of the overload contract (`load-smoke` gates it "
        "in tier-1); re-run with `--service_ms 0` on real silicon for "
        "production capacity numbers.",
        "",
    ]
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The tier-1 smoke
# ---------------------------------------------------------------------------


def run_smoke(args) -> int:
    app = build_app(service_ms=args.service_ms,
                    max_queue_rows=args.max_queue_rows,
                    max_wait_ms=args.max_wait_ms,
                    p99_wait_high_ms=args.p99_wait_high_ms)
    try:
        sat = measure_saturation(app)
        if sat <= 0:
            print("LOAD-SMOKE FAIL: calibration measured zero goodput")
            return 1
        settle(app)
        # The overload cell: a spike to 2x measured saturation for the
        # middle third, base load 0.4x on either side (the recovery
        # window is inside the same open-loop program).
        spike = run_cell(app, shape="spike", base_rps=0.4 * sat,
                         peak_rps=2.0 * sat, duration_s=args.smoke_cell_s,
                         seed=101, max_workers=args.max_workers)
        recovered = settle(app, timeout_s=10.0)
        post = run_cell(app, shape="constant", base_rps=0.3 * sat,
                        peak_rps=None, duration_s=args.smoke_cell_s / 3,
                        seed=202, max_workers=args.max_workers)

        hot = spike["by_model"].get("hot", {})
        bg = spike["by_model"].get("bg", {})

        def frac_ok(c):
            total = sum(c.get(k, 0) for k in
                        ("ok", "shed", "backpressure", "drain", "error"))
            return c.get("ok", 0) / total if total else 0.0

        checks = {
            "sheds_nonzero": spike["shed_scrape"] > 0,
            "sheds_accounted":
                spike["shed_scrape"] == spike["shed"],
            "p999_bounded":
                spike["p999_ms"] == spike["p999_ms"]
                and spike["p999_ms"] <= args.p999_bound_ms,
            "zero_hung": spike["hung"] == 0 and post["hung"] == 0,
            "fair_to_bg": frac_ok(bg) >= frac_ok(hot),
            "recovered": recovered and post["readyz"] == 200,
            "post_spike_clean":
                post["shed_scrape"] == 0 and post["admission_state"] == 0,
        }
        ok = all(checks.values())
        failed = [k for k, v in checks.items() if not v]
        print(
            "LOAD-SMOKE " + ("PASS" if ok else "FAIL")
            + f": sat={sat:.0f} rps, spike offered="
            f"{spike['offered_rps']} rps (2x), accepted p999="
            f"{spike['p999_ms']} ms (bound {args.p999_bound_ms}), "
            f"sheds={spike['shed_scrape']} (client {spike['shed']}), "
            f"hung={spike['hung']}, late={spike['late']}, "
            f"bg_ok={frac_ok(bg):.2f} vs hot_ok="
            f"{frac_ok(hot):.2f}, post: shed={post['shed_scrape']} "
            f"p99={post['p99_ms']} ms readyz={post['readyz']}"
            + (f" FAILED={failed}" if failed else "")
        )
        return 0 if ok else 1
    finally:
        app.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--smoke", action="store_true",
                   help="tier-1 overload-contract gate (PASS/FAIL line)")
    p.add_argument("--out", default=None, help="LOAD.md output path")
    p.add_argument("--csv", default=None, help="per-cell CSV output path")
    p.add_argument("--service_ms", type=float, default=20.0,
                   help="emulated per-batch device service time "
                        "(0 on real silicon)")
    p.add_argument("--cell_s", type=float, default=4.0,
                   help="sweep cell duration")
    p.add_argument("--smoke_cell_s", type=float, default=9.0,
                   help="smoke spike-cell duration (spike = middle third)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_queue_rows", type=int, default=1024)
    p.add_argument("--max_wait_ms", type=float, default=4.0)
    p.add_argument("--p99_wait_high_ms", type=float, default=250.0)
    p.add_argument("--p999_bound_ms", type=float, default=2000.0,
                   help="stated accepted-request p999 bound under "
                        "2x overload (the smoke contract)")
    p.add_argument("--max_workers", type=int, default=256)
    args = p.parse_args(argv)

    if args.smoke:
        return run_smoke(args)

    app = build_app(service_ms=args.service_ms,
                    max_queue_rows=args.max_queue_rows,
                    max_wait_ms=args.max_wait_ms,
                    p99_wait_high_ms=args.p99_wait_high_ms)
    try:
        sat = measure_saturation(app)
        settle(app)
        cells = run_sweep(app, sat, cell_s=args.cell_s, seed=args.seed)
    finally:
        app.stop()

    if args.csv:
        with open(args.csv, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=("load_frac",) + CSV_COLUMNS,
                               extrasaction="ignore")
            w.writeheader()
            for c in cells:
                w.writerow(c)
        print(f"wrote {args.csv}")
    text = render_md(cells, sat, args)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
