"""Communication-strategy sweep for the streamed distributed fits
(parallel/reduce): strategy × K on the 8-device mesh.

What this measures, per (mesh, strategy, K):

- **reduces_per_pass / bytes_per_pass** — the comms accounting from the
  fit result's `CommsReport` (parallel/reduce.py): cross-device
  sufficient-stat reduces issued and the logical payload bytes they moved.
  The acceptance invariant under test: per-pass reduction issues EXACTLY
  one cross-device reduce per Lloyd iteration, vs num_batches for the
  per-batch default; the quantized encodings shrink bytes_per_pass by
  ~2x (bf16) / ~3.7x (int8 + scales) at K=1024, d=64.
- **max_centroid_delta / rel_inertia_delta** — numerics vs the per-batch
  f32 baseline on the same mesh: per-pass reorders f32 summation
  (tolerance-level, ~1e-6 on this data), and the quantized modes carry
  error feedback (documented bound: inertia within 1e-3 relative).
- **wall_s** — whole-fit wall clock. CAVEAT (the cpu_mesh_scaling.py
  lesson): the 8 virtual CPU devices share one CPU's cores, so wall-clock
  differences here mostly measure dispatch/thread contention, NOT link
  time — on real multi-chip hardware the collective count and DCN bytes
  are the quantities that dominate, which is exactly what the counters
  report. Treat wall_s as context, the counters as the result.

Mesh column: `flat8` = 1-D 8-device data mesh; `hier2x4` = hierarchical
(dcn=2, ici=4) mesh (mesh.make_hierarchical_mesh) — two staged reduces
whose DCN stage moves the payload once per host group; `mesh2x4` = the
2-D (data=2, model=4) K-sharded mesh for the gather= sweep.

PR 17 adds the MODEL axis to the accounting (`CommsReport.data_bytes /
model_bytes / gathers`): the K-sharded champion all_gathers and the
centroid-finalize exchange, priced per `gather=` compression mode
(fp32 | fp32_sharded | bf16 | int8, parallel/gather.py). The gather
sweep's model-axis columns are the acceptance quantity: at K>=4096 the
int8 finalize moves >=3.5x fewer bytes per centroid update than the
fp32_sharded full-precision wire baseline (3.88x measured; fp32 proper
books ZERO finalize bytes — its finalize is replicated compute, so
fp32_sharded is the apples-to-apples baseline; the whole-axis per-pass
ratio is lower because the champion argmin column is int32 and the
report pass runs fp32 champions). `hier2x4-staged` rows price the
staged (dcn=2, ici=4) finalize gather from the same cost function the
drivers book (gather.finalize_gather_cost) — the ICI stage stays fp32,
only the DCN hop is compressed, so the byte ratio there is the
DCN-link ratio.

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/bench_comms.py            # full sweep -> CSV
  python benchmarks/bench_comms.py --smoke        # CI one-liner (~20 s)

Writes benchmarks/comms_8dev_cpu.csv; analysis note in
benchmarks/COMMS.md. One JSON line per configuration on stdout.
"""

import csv
import json
import os
import sys
import time

# Script invocation puts benchmarks/ (not the repo root) on sys.path;
# mirror bench_spill.py so the smoke runs without an external PYTHONPATH.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tdc_tpu.models.streaming import streamed_kmeans_fit  # noqa: E402
from tdc_tpu.parallel.mesh import (  # noqa: E402
    make_hierarchical_mesh,
    make_mesh,
)

OUT = os.path.join(os.path.dirname(__file__), "comms_8dev_cpu.csv")
STRATEGIES = ("per_batch", "per_pass", "per_pass:bf16", "per_pass:int8")
GATHER_MODES = ("fp32", "fp32_sharded", "bf16", "int8")
FIELDS = [
    "mesh", "strategy", "gather", "K", "d", "n", "batch_rows", "n_batches",
    "iters", "passes", "reduces_per_pass", "bytes_per_pass",
    "data_bytes_per_pass", "model_bytes_per_pass", "gathers_per_pass",
    "total_reduces", "total_bytes", "max_centroid_delta",
    "rel_inertia_delta", "wall_s",
]


def _data(n, d, k, seed=123128):
    """k well-separated gaussian blobs in d dims (the reference sweep's
    seed); init = the true centers so every strategy follows the same
    short, well-conditioned trajectory."""
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10.0, 10.0, size=(k, d)).astype(np.float32)
    x = np.repeat(centers, n // k, axis=0) + rng.normal(
        0, 0.5, size=(n // k * k, d)
    ).astype(np.float32)
    rng.shuffle(x)
    return x, centers


def run_one(mesh_name, mesh, strategy, k, d, n, batch_rows, iters):
    x, centers = _data(n, d, k)
    batches = lambda: (
        x[i: i + batch_rows] for i in range(0, len(x), batch_rows)
    )
    t0 = time.perf_counter()
    res = streamed_kmeans_fit(
        batches, k, d, init=centers, max_iters=iters, tol=-1.0, mesh=mesh,
        reduce=strategy,
    )
    jax.block_until_ready(res.centroids)
    wall = time.perf_counter() - t0
    row = _row(mesh_name, strategy, "", k, d, len(x), batch_rows, iters,
               res.comms, wall)
    return row, res


def _row(mesh_name, strategy, gather, k, d, n, batch_rows, iters, c, wall):
    return {
        "mesh": mesh_name, "strategy": strategy, "gather": gather,
        "K": k, "d": d, "n": n, "batch_rows": batch_rows,
        "n_batches": -(-n // batch_rows), "iters": iters,
        "passes": c.passes,
        "reduces_per_pass": round(c.reduces / c.passes, 3),
        "bytes_per_pass": c.logical_bytes // c.passes,
        "data_bytes_per_pass": c.data_bytes // c.passes,
        "model_bytes_per_pass": c.model_bytes // c.passes,
        "gathers_per_pass": round(c.gathers / c.passes, 3),
        "total_reduces": c.reduces, "total_bytes": c.logical_bytes,
        "wall_s": round(wall, 3),
    }


def run_gather_one(mesh2d, gather, k, d, n, batch_rows, iters):
    """One K-sharded streamed fit on the (data=2, model=4) mesh with the
    given gather= compression mode; the CommsReport's model-axis columns
    are the result."""
    from tdc_tpu.parallel.sharded_k import streamed_kmeans_fit_sharded

    x, centers = _data(n, d, k)
    batches = lambda: (
        x[i: i + batch_rows] for i in range(0, len(x), batch_rows)
    )
    t0 = time.perf_counter()
    res = streamed_kmeans_fit_sharded(
        batches, k, d, mesh2d, init=centers, max_iters=iters, tol=-1.0,
        gather=gather,
    )
    jax.block_until_ready(res.centroids)
    wall = time.perf_counter() - t0
    row = _row("mesh2x4", "per_batch", gather, k, d, len(x), batch_rows,
               iters, res.comms, wall)
    return row, res


def sweep_gather(ks, d, n, batch_rows, iters, mesh2d):
    """gather= mode x K on the 2-D mesh; numerics columns are vs the
    gather='fp32' (fully replicated finalize, pre-PR schedule) baseline."""
    rows = []
    for k in ks:
        baseline = None
        for gather in GATHER_MODES:
            row, res = run_gather_one(mesh2d, gather, k, d, n, batch_rows,
                                      iters)
            if baseline is None:  # fp32 runs first
                baseline = res
            bc = np.asarray(baseline.centroids)
            row["max_centroid_delta"] = float(
                np.max(np.abs(np.asarray(res.centroids) - bc))
            )
            row["rel_inertia_delta"] = float(
                abs(float(res.sse) - float(baseline.sse))
                / max(float(baseline.sse), 1e-12)
            )
            rows.append(row)
            print(json.dumps(row))
    return rows


def hier_staged_rows(ks, d, groups=(2, 4)):
    """Cost-model rows for the staged hierarchical finalize gather: each
    device's (K, d)/8 centroid slice gathered ICI-first at fp32, with
    only the DCN stage compressed — priced by the SAME
    gather.finalize_gather_cost the drivers book, so these rows are the
    schedule's bytes, not a fit's. wall_s is blank on purpose (nothing
    ran)."""
    from tdc_tpu.parallel import gather as gather_lib

    rows = []
    for k in ks:
        for mode in GATHER_MODES:
            if mode == "fp32_sharded":
                continue  # staging is about compression; fp32 is the ref
            gathers, nbytes = gather_lib.finalize_gather_cost(
                k, d, groups, mode
            )
            rows.append({
                "mesh": "hier2x4-staged", "strategy": "finalize",
                "gather": mode, "K": k, "d": d, "n": "", "batch_rows": "",
                "n_batches": "", "iters": "", "passes": 1,
                "reduces_per_pass": 0, "bytes_per_pass": nbytes,
                "data_bytes_per_pass": 0, "model_bytes_per_pass": nbytes,
                "gathers_per_pass": gathers, "total_reduces": 0,
                "total_bytes": nbytes, "max_centroid_delta": "",
                "rel_inertia_delta": "", "wall_s": "",
            })
            print(json.dumps(rows[-1]))
    return rows


def sweep(ks, d, n, batch_rows, iters, meshes):
    rows = []
    for mesh_name, mesh, strategies in meshes:
        for k in ks:
            baseline = None
            for strategy in strategies:
                row, res = run_one(
                    mesh_name, mesh, strategy, k, d, n, batch_rows, iters
                )
                if baseline is None:  # per_batch runs first
                    baseline = res
                bc = np.asarray(baseline.centroids)
                row["max_centroid_delta"] = float(
                    np.max(np.abs(np.asarray(res.centroids) - bc))
                )
                row["rel_inertia_delta"] = float(
                    abs(float(res.sse) - float(baseline.sse))
                    / max(float(baseline.sse), 1e-12)
                )
                rows.append(row)
                print(json.dumps(row))
    return rows


def main(argv):
    smoke = "--smoke" in argv
    n_dev = len(jax.devices())
    if n_dev < 8:
        print(f"note: only {n_dev} devices visible; expected the 8-device "
              "mesh", file=sys.stderr)
    flat = make_mesh(min(8, n_dev))
    meshes = [("flat8", flat, STRATEGIES)]
    if min(8, n_dev) % 2 == 0:
        meshes.append(
            ("hier2x4", make_hierarchical_mesh(2, n_devices=min(8, n_dev)),
             ("per_batch", "per_pass"))
        )

    mesh2d = None
    if n_dev >= 8:
        from tdc_tpu.parallel.sharded_k import make_mesh_2d

        mesh2d = make_mesh_2d(2, 4)

    if smoke:
        rows = sweep([16], d=16, n=2048, batch_rows=256, iters=2,
                     meshes=meshes[:1])
        by = {r["strategy"]: r for r in rows}
        ok = (
            by["per_pass"]["reduces_per_pass"] == 1.0
            and by["per_batch"]["reduces_per_pass"]
            == by["per_batch"]["n_batches"]
            and all(r["rel_inertia_delta"] < 1e-3 for r in rows)
        )
        # Quantized-gather config (PR 17): the K-sharded tower with the
        # bf16 compressed gather — sharded finalize must stay bit-exact
        # at fp32 wire precision, bf16 must cut model-axis bytes below
        # the full-precision sharded baseline while staying within the
        # quantized inertia envelope.
        gok = True
        if mesh2d is not None:
            grows = sweep_gather([64], d=16, n=2048, batch_rows=256,
                                 iters=2, mesh2d=mesh2d)
            gby = {r["gather"]: r for r in grows}
            gok = (
                gby["fp32_sharded"]["max_centroid_delta"] == 0.0
                and gby["bf16"]["rel_inertia_delta"] < 1e-2
                and gby["bf16"]["model_bytes_per_pass"]
                < gby["fp32_sharded"]["model_bytes_per_pass"]
                and gby["int8"]["model_bytes_per_pass"]
                < gby["bf16"]["model_bytes_per_pass"]
            )
        ok = ok and gok
        print(
            "COMMS-SMOKE "
            + ("PASS" if ok else "FAIL")
            + f": per_pass={by['per_pass']['reduces_per_pass']}/pass, "
            f"per_batch={by['per_batch']['reduces_per_pass']}/pass "
            f"(n_batches={by['per_batch']['n_batches']}), "
            f"worst rel_inertia_delta="
            f"{max(r['rel_inertia_delta'] for r in rows):.2e}, "
            f"gather={'ok' if gok else 'FAIL'}"
        )
        return 0 if ok else 1

    rows = sweep([16, 256, 1024], d=64, n=8192, batch_rows=1024, iters=5,
                 meshes=meshes)
    if mesh2d is not None:
        # n >= K so every blob gets rows (_data repeats n//k per center).
        rows += sweep_gather([1024], d=128, n=8192, batch_rows=1024,
                             iters=3, mesh2d=mesh2d)
        rows += sweep_gather([4096], d=128, n=8192, batch_rows=2048,
                             iters=3, mesh2d=mesh2d)
    rows += hier_staged_rows([1024, 4096], d=128)
    with open(OUT, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {OUT} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
