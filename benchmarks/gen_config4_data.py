"""Generate the config-4 dataset to disk: 100M × 256 bf16 blobs → /tmp/c4.npy
(51.2 GB), consumed by benchmarks/config4_100m.json via --data_file.

Why a disk file instead of the CLI's in-process synthetic path (round 5,
measured the hard way): a 100M×256 run needs the dataset OUT of anonymous
host memory. The tunneled device client pins a host-side staging copy per
uploaded batch for longer than the batch's Python lifetime, so a streamed
pass leaks ~dataset-size of anon RSS per pass; with the dataset ALSO
resident (in-process generation), the second pass OOM-killed the run at
130 GB RSS on a 125 GB host — twice. A memory-mapped npy moves the dataset
into reclaimable page cache, which the kernel evicts under that pressure,
and lets a checkpoint-resumed retry skip the ~50-minute regeneration
(device→host through the tunnel runs at ~1 GB/min — the generation, not
the fit, is the expensive part).

bf16 on disk halves both the file and every pass's H2D (the npy format
stores it as unstructured |V2; data/loader.load_points reinterprets).

Run:  python benchmarks/gen_config4_data.py   (~50 min through the tunnel)
Then: python -m tdc_tpu.cli.sweep benchmarks/config4_100m.json
      (re-run the sweep to resume from /tmp/ckpt_c4 if an attempt dies).
"""

import time

import jax.numpy as jnp
import ml_dtypes
import numpy as np

from tdc_tpu.data.synthetic import make_blobs

N, D, K, SEED = 100_000_000, 256, 4096, 123128
CHUNK = 4_000_000


def main():
    out = np.lib.format.open_memmap(
        "/tmp/c4.npy", mode="w+", dtype=ml_dtypes.bfloat16, shape=(N, D)
    )
    t0 = time.time()
    done = 0
    while done < N:
        n = min(CHUNK, N - done)
        # Per-chunk seeds keep chunks independent draws of the same blob
        # family (this is a data FILE, not seed-parity data — the fit's
        # own seed governs everything downstream).
        x, _ = make_blobs(SEED + 1 + done, n, D, K, to_host=True,
                          dtype=jnp.bfloat16)
        out[done:done + n] = x
        done += n
        print(f"{done / 1e6:.0f}M rows, {time.time() - t0:.0f}s", flush=True)
    out.flush()
    print("done", round(time.time() - t0, 1))


if __name__ == "__main__":
    main()
