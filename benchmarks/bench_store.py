"""Object-store data-plane benchmark: manifest streams (data/store.py)
vs the in-memory streamed baseline, over file:// and live flaky HTTP.

What this measures, per config (bench_spill.py's iteration-differencing
methodology — `(wall(I2) - wall(I1)) / (I2 - I1)` with tol=-1 pinning
iteration counts, so compile/init/reporting cancel):

- **mem_iter_s / file_iter_s / http_iter_s** — the marginal cost of one
  more Lloyd iteration when every batch is (a) an in-memory slice, (b) a
  pread-ranged read through `FileStore` + CRC32 verify, (c) a real
  HTTP Range request against a localhost server (stdlib http.client,
  keep-alive, one socket per producer thread). The deltas are the data
  plane's whole toll: syscall/socket + copy + CRC per batch.
- **http_flaky_iter_s / flaky_retries** — the same HTTP fit through a
  deterministic ~33% 5xx storm (`testing/flaky_http.py`, Retry-After
  honored): what a production-grade bad day costs, and proof the retry
  ladder absorbed it (`retries > 0`, result still bit-exact).
- **reads_per_pass / mb_per_pass** — `StoreCounter` truth (the
  `tdc_store_*` `/metrics` families): one ranged read per batch, bytes
  = the batch slice, no amplification.
- **spill_cross_pass** — the pass-persistent spill ring over the
  manifest stream: batches staged ACROSS iteration boundaries (> 0 is
  the PR-18 acceptance evidence) while staying bit-exact.
- **bitexact_*** — every store path vs the in-memory baseline via
  `np.array_equal`: the data plane changes WHERE bytes come from, never
  what the accumulate ops see.

The smoke gates correctness and robustness, not speed — on a loaded
1-core CI box wall-clock ratios are noise, but bit-exactness, absorbed
retries, zero quarantines, and cross-pass staging are invariant:

  STORE-SMOKE PASS requires file://, HTTP, and flaky-HTTP fits bit-exact
  with the in-memory baseline; flaky retries > 0 with 0 quarantined;
  spill-over-manifest bit-exact with cross_pass > 0.

Run:
  JAX_PLATFORMS=cpu python benchmarks/bench_store.py        # sweep -> CSV
  python benchmarks/bench_store.py --smoke                  # CI gate

Writes benchmarks/store_cpu.csv; one JSON line per config on stdout.
"""

import csv
import json
import os
import sys
import tempfile
import time

# Runnable as a plain script from any cwd (the serve_latency.py pattern).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tdc_tpu.data.device_cache import SizedBatches  # noqa: E402
from tdc_tpu.data.ingest import IngestPolicy  # noqa: E402
from tdc_tpu.data.manifest import build_manifest  # noqa: E402
from tdc_tpu.data.store import StoreCounter, open_manifest_stream  # noqa: E402
from tdc_tpu.models.streaming import streamed_kmeans_fit  # noqa: E402
from tdc_tpu.testing.flaky_http import FlakyHTTPServer  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "store_cpu.csv")
FIELDS = [
    "config", "K", "d", "n", "batch_rows", "n_shards", "i1", "i2",
    "mem_iter_s", "file_iter_s", "http_iter_s", "http_flaky_iter_s",
    "file_overhead", "http_overhead", "flaky_retries", "flaky_quarantined",
    "reads_per_pass", "mb_per_pass", "spill_cross_pass",
    "bitexact_file", "bitexact_http", "bitexact_flaky", "bitexact_spill",
]


def _blobs(n, d, k, seed=20250418):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-8.0, 8.0, size=(k, d)).astype(np.float32)
    x = np.repeat(centers, n // k, axis=0) + rng.normal(
        0, 0.4, size=(n // k * k, d)
    ).astype(np.float32)
    rng.shuffle(x)
    return x, centers


def _mem_stream(x, batch_rows):
    def read(i):
        return x[i * batch_rows: (i + 1) * batch_rows]

    def gen():
        for i in range(-(-len(x) // batch_rows)):
            yield read(i)

    return SizedBatches(gen, len(x), batch_rows, itemsize=4,
                        read_batch=read)


def _fit(make_stream, k, d, init, iters, residency="stream", ingest=None):
    batches = make_stream()
    t0 = time.perf_counter()
    res = streamed_kmeans_fit(
        batches, k, d, init=init, max_iters=iters, tol=-1.0,
        residency=residency,
        **({} if ingest is None else {"ingest": ingest}),
    )
    jax.block_until_ready(res.centroids)
    return time.perf_counter() - t0, res


def _marginal(make_stream, k, d, init, i1, i2, repeats, **kw):
    samples, r2 = [], None
    for _ in range(repeats):
        w1, _ = _fit(make_stream, k, d, init, i1, **kw)
        w2, r2 = _fit(make_stream, k, d, init, i2, **kw)
        samples.append((w2 - w1) / (i2 - i1))
    return max(float(np.median(samples)), 1e-6), r2


def run_one(config, k, d, n, batch_rows, n_shards, i1, i2, repeats=3,
            fail_every=3):
    x, centers = _blobs(n, d, k)
    init = centers
    tmp = tempfile.mkdtemp(prefix="tdc_bench_store_")
    manifest_path = build_manifest(x, batch_rows, tmp, n_shards=n_shards)

    def mem():
        return _mem_stream(x, batch_rows)

    counter = StoreCounter()

    def file_stream():
        return open_manifest_stream(manifest_path, counter=counter)

    # Warm the compile caches once (identical geometry on every path).
    _fit(mem, k, d, init, i1)
    _fit(file_stream, k, d, init, i1)

    mem_iter, rm = _marginal(mem, k, d, init, i1, i2, repeats)
    file_iter, rf = _marginal(file_stream, k, d, init, i1, i2, repeats)

    with FlakyHTTPServer(tmp) as url:
        def http_stream():
            return open_manifest_stream(f"{url}/manifest.json", timeout=10.0)

        http_iter, rh = _marginal(http_stream, k, d, init, i1, i2, repeats)

    storm = FlakyHTTPServer(tmp, fail_every=fail_every, fail_status=503,
                            retry_after=0.001)
    with storm as url:
        def flaky_stream():
            return open_manifest_stream(f"{url}/manifest.json", timeout=10.0)

        flaky_iter, rfl = _marginal(
            flaky_stream, k, d, init, i1, i2, 1,
            ingest=IngestPolicy(io_retries=6, io_backoff=0.001),
        )

    _, rsp = _fit(file_stream, k, d, init, i2, residency="spill")

    n_batches = -(-n // batch_rows)
    c0 = np.asarray(rm.centroids)
    row = {
        "config": config, "K": k, "d": d, "n": n,
        "batch_rows": batch_rows, "n_shards": n_shards, "i1": i1, "i2": i2,
        "mem_iter_s": round(mem_iter, 6),
        "file_iter_s": round(file_iter, 6),
        "http_iter_s": round(http_iter, 6),
        "http_flaky_iter_s": round(flaky_iter, 6),
        "file_overhead": round(file_iter / mem_iter, 3),
        "http_overhead": round(http_iter / mem_iter, 3),
        "flaky_retries": rfl.ingest.retries if rfl.ingest else 0,
        "flaky_quarantined": (rfl.ingest.quarantined_batches
                              if rfl.ingest else 0),
        "reads_per_pass": n_batches,
        "mb_per_pass": round(x.nbytes / 2**20, 2),
        "spill_cross_pass": rsp.h2d.cross_pass if rsp.h2d else 0,
        "bitexact_file": bool(np.array_equal(c0, np.asarray(rf.centroids))),
        "bitexact_http": bool(np.array_equal(c0, np.asarray(rh.centroids))),
        "bitexact_flaky": bool(np.array_equal(c0, np.asarray(rfl.centroids))),
        "bitexact_spill": bool(np.array_equal(c0, np.asarray(rsp.centroids))),
    }
    print(json.dumps(row), flush=True)
    return row


def main(argv):
    smoke = "--smoke" in argv

    if smoke:
        # One config, correctness-gated (module docstring): 8 batches x
        # 2 shards covers multi-shard locate arithmetic, the storm fires
        # on every 3rd request, the spill fit must stage across a pass
        # boundary. ~30 s on the CI box.
        row = run_one("smoke", k=16, d=32, n=1 << 16, batch_rows=1 << 13,
                      n_shards=2, i1=2, i2=4, repeats=1)
        ok = (
            row["bitexact_file"] and row["bitexact_http"]
            and row["bitexact_flaky"] and row["bitexact_spill"]
            and row["flaky_retries"] > 0
            and row["flaky_quarantined"] == 0
            and row["spill_cross_pass"] > 0
        )
        print(
            "STORE-SMOKE "
            + ("PASS" if ok else "FAIL")
            + f": mem={row['mem_iter_s'] * 1e3:.1f} file="
            f"{row['file_iter_s'] * 1e3:.1f} http="
            f"{row['http_iter_s'] * 1e3:.1f} flaky="
            f"{row['http_flaky_iter_s'] * 1e3:.1f} ms/iter, "
            f"retries={row['flaky_retries']} (floor >0), "
            f"quarantined={row['flaky_quarantined']} (==0), "
            f"cross_pass={row['spill_cross_pass']} (floor >0), "
            f"bitexact={row['bitexact_file'] and row['bitexact_http'] and row['bitexact_flaky'] and row['bitexact_spill']}",
            flush=True,
        )
        return 0 if ok else 1

    rows = [
        run_one("small_8x2", k=16, d=32, n=1 << 16, batch_rows=1 << 13,
                n_shards=2, i1=2, i2=5),
        run_one("wide_d128", k=32, d=128, n=1 << 16, batch_rows=1 << 13,
                n_shards=4, i1=2, i2=5),
        run_one("many_batches", k=16, d=32, n=1 << 17, batch_rows=1 << 12,
                n_shards=4, i1=2, i2=5),
    ]
    with open(OUT, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {OUT}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
