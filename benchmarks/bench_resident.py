"""Residency benchmark: streamed vs HBM-resident per-iteration wall clock.

What this measures, per (mesh, config):

- **streamed_iter_s / resident_iter_s** — the marginal cost of ONE more
  Lloyd iteration on each path, measured by DIFFERENCING two whole-fit
  wall clocks at different iteration counts (tol=-1 pins the counts):
  `(wall(I2) - wall(I1)) / (I2 - I1)`. Everything the iteration count
  does not scale — compile (warmed first), init, the cache-fill pass,
  the final reporting pass — cancels, so the quotient isolates exactly
  what the residency subsystem claims to change: per-iteration dispatch +
  H2D round trips (streamed: one Python dispatch and one host->device
  upload per batch per iteration) vs the compiled on-device chunk loop
  (resident: 1/chunk_iters of a dispatch and ZERO transfers per
  iteration; the chunk-boundary fetch is included in its quotient, so
  the comparison is honest about the boundary cost).
- **speedup** — streamed_iter_s / resident_iter_s. The CI acceptance
  floor is >= 1.5x on the smoke config, which is sized to be
  dispatch/H2D-dominated (many small batches, tiny stats compute) — the
  regime the measured ~10x round-trip penalty on remote links
  (models/streaming.py) makes ubiquitous off-box.

CAVEAT (the cpu_mesh_scaling.py lesson): on the 8 virtual CPU devices the
"H2D" is a memcpy, so the streamed path is charged far LESS here than on
real TPU links — the CPU speedup is a conservative floor for hardware,
where per-iteration H2D of the whole dataset rides a ~GB/s PCIe/ICI path.
The v5e methodology for the full-size measurement is documented in
benchmarks/ROOFLINE.md (residency addendum).

Run:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python benchmarks/bench_resident.py           # sweep -> CSV
  python benchmarks/bench_resident.py --smoke       # CI one-liner (~60 s)

Writes benchmarks/resident_cpu.csv; one JSON line per config on stdout.
"""

import csv
import json
import os
import sys
import time

# Script invocation puts benchmarks/ (not the repo root) on sys.path;
# mirror bench_spill.py so the smoke runs without an external PYTHONPATH.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from tdc_tpu.data.device_cache import SizedBatches  # noqa: E402
from tdc_tpu.models.streaming import streamed_kmeans_fit  # noqa: E402
from tdc_tpu.parallel.mesh import make_mesh  # noqa: E402

OUT = os.path.join(os.path.dirname(__file__), "resident_cpu.csv")
FIELDS = [
    "mesh", "K", "d", "n", "batch_rows", "n_batches", "i1", "i2",
    "streamed_iter_s", "resident_iter_s", "speedup",
    "dispatch_overhead_per_iter_s", "bitexact",
]


def _data(n, d, k, seed=123128):
    rng = np.random.default_rng(seed)
    centers = rng.uniform(-10.0, 10.0, size=(k, d)).astype(np.float32)
    x = np.repeat(centers, n // k, axis=0) + rng.normal(
        0, 0.5, size=(n // k * k, d)
    ).astype(np.float32)
    rng.shuffle(x)
    return x, centers


def _fit(x, centers, k, d, batch_rows, iters, mesh, residency):
    batches = SizedBatches(
        lambda: (x[i: i + batch_rows] for i in range(0, len(x), batch_rows)),
        len(x), batch_rows,
    )
    t0 = time.perf_counter()
    res = streamed_kmeans_fit(
        batches, k, d, init=centers, max_iters=iters, tol=-1.0, mesh=mesh,
        residency=residency,
    )
    jax.block_until_ready(res.centroids)
    return time.perf_counter() - t0, res


def run_one(mesh_name, mesh, k, d, n, batch_rows, i1, i2, repeats=3):
    x, centers = _data(n, d, k)

    # Warm every compile cache (streamed accumulate, fill, chunk loop).
    _fit(x, centers, k, d, batch_rows, i1, mesh, "stream")
    _fit(x, centers, k, d, batch_rows, i1, mesh, "hbm")

    def marginal(residency):
        samples, r2 = [], None
        for _ in range(repeats):
            w1, _ = _fit(x, centers, k, d, batch_rows, i1, mesh, residency)
            w2, r2 = _fit(x, centers, k, d, batch_rows, i2, mesh, residency)
            samples.append((w2 - w1) / (i2 - i1))
        # Median across repeats absorbs scheduler noise on a loaded box; a
        # non-positive median means the marginal iteration cost is below
        # the differencing noise floor — clamp to 1 µs instead of crashing
        # (the smoke then reports the honest "unmeasurably small" side).
        return max(float(np.median(samples)), 1e-6), r2

    s_iter, rs = marginal("stream")
    r_iter, rh = marginal("hbm")
    row = {
        "mesh": mesh_name, "K": k, "d": d, "n": n,
        "batch_rows": batch_rows, "n_batches": -(-n // batch_rows),
        "i1": i1, "i2": i2,
        "streamed_iter_s": round(s_iter, 6),
        "resident_iter_s": round(r_iter, 6),
        "speedup": round(s_iter / r_iter, 3),
        "dispatch_overhead_per_iter_s": round(s_iter - r_iter, 6),
        "bitexact": bool(
            np.array_equal(np.asarray(rs.centroids), np.asarray(rh.centroids))
        ),
    }
    print(json.dumps(row), flush=True)
    return row


def main(argv):
    smoke = "--smoke" in argv
    n_dev = len(jax.devices())
    mesh = make_mesh(min(8, n_dev))

    if "--trace" in argv:
        # Phase attribution for the iteration-differencing numbers: run
        # the dispatch-dominated config ONCE traced and print the
        # per-pass timeline (read/stage/compute/reduce self-times from
        # the same spans the Chrome export carries) — where the
        # marginal streamed iteration actually goes.
        from tdc_tpu.obs import trace

        at = argv.index("--trace")
        if at + 1 >= len(argv) or argv[at + 1].startswith("-"):
            print("usage: bench_resident.py --trace <dir>", file=sys.stderr)
            return 2
        trace.configure(argv[at + 1])
        x, centers = _data(16384, 16, 16)
        _, res = _fit(x, centers, 16, 16, 128, 4, None, "stream")
        print(trace.format_timeline(res.timeline, label="stream k16 d16"))
        print(f"trace written: {trace.flush()}", flush=True)
        return 0

    if smoke:
        # Dispatch-dominated sizing: 128 small batches per pass, trivial
        # stats compute — the marginal streamed iteration is almost pure
        # per-batch dispatch + upload, which is the cost residency
        # removes (measured here: ~60 ms/iter streamed vs <1 ms resident,
        # ~100x; the 1.5x floor leaves wide margin for a loaded CI box).
        # Single-device (mesh dispatch contention on the shared CPU cores
        # is bench_comms territory, not this claim).
        row = run_one("cpu1", None, k=16, d=16, n=16384, batch_rows=128,
                      i1=3, i2=9)
        ok = row["speedup"] >= 1.5 and row["bitexact"]
        print(
            "RESIDENT-SMOKE "
            + ("PASS" if ok else "FAIL")
            + f": streamed={row['streamed_iter_s'] * 1e3:.1f} ms/iter, "
            f"resident={row['resident_iter_s'] * 1e3:.1f} ms/iter, "
            f"speedup={row['speedup']}x (floor 1.5x), "
            f"bitexact={row['bitexact']}"
        )
        return 0 if ok else 1

    rows = [
        # dispatch-dominated (many small batches) ...
        run_one("cpu1", None, k=16, d=16, n=16384, batch_rows=128,
                i1=3, i2=9),
        # ... through compute-heavier (few large batches): the speedup
        # shrinks toward 1x as per-batch compute amortizes the dispatch —
        # the honest shape of the win.
        run_one("cpu1", None, k=16, d=16, n=16384, batch_rows=2048,
                i1=3, i2=9),
        run_one("cpu1", None, k=64, d=64, n=32768, batch_rows=2048,
                i1=3, i2=9),
        run_one("flat8", mesh, k=16, d=16, n=16384, batch_rows=128,
                i1=3, i2=9),
        run_one("flat8", mesh, k=64, d=64, n=32768, batch_rows=2048,
                i1=3, i2=9),
    ]
    with open(OUT, "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=FIELDS)
        w.writeheader()
        w.writerows(rows)
    print(f"wrote {OUT} ({len(rows)} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
