"""K=16,384 · d=768 sharded FUZZY C-Means benchmark (round-4 VERDICT #1:
fuzzy — the reference's fastest algorithm, 326 M pt·iter/s at K=3 in its
log — deserved the Lloyd tower's large-K treatment).

Measures one fuzzy step of the K-sharded tower (parallel/sharded_k.
make_sharded_fuzzy_stats + the M-step ratio) with the two-pass Pallas
kernels inside the shard: pass 1 streams K-tiles to build the per-point
membership normalizer, a psum over the model axis globalizes it, pass 2
re-streams the K-tiles accumulating the u^m-weighted moments. No (N, K)
or (N, K/Pm) tile exists anywhere; the only N-sized arrays are the (N, 1)
normalizer columns.

Roofline note (distance-only convention, 2·K·d = 25.17 MFLOP/pt·iter,
v5e bf16 peak 197 TFLOP/s ⇒ 7.83 M pt·iter/s): the two-pass design pays
the distance FLOPs TWICE (the normalizer pass and the accumulate pass
recompute the same d² tiles — the price of never materializing (N, K)),
plus the accumulate pass's second MXU contraction (u^m @ x, another
2·K·d). So the fuzzy step's hard ceiling is 197/(6·K·d) = **2.61 M
pt·iter/s** — committed numbers should be read against that, not the
Lloyd tower's 7.83 M. The reference's own fuzzy/Lloyd ratio at K=15 was
similar (59 M vs 31 M — its fuzzy did ~2× the work per point too, with
the full membership matrix materialized per GPU).

Run:  python benchmarks/bench_sharded_fuzzy.py
Prints one JSON line per configuration (bench.py conventions: robust
slope timing, min-of-repeats, D2H sync).
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from tdc_tpu.parallel.sharded_k import make_mesh_2d, make_sharded_fuzzy_stats

BASE_RATE = 40.7e6 * (3 * 5)  # reference best fuzzy per-GPU rate x (K*d)


def measure(step_fn, x, c, iters_short=7, iters_long=21, repeats=3):
    """Per-iteration seconds from the slope between per-length MIN times
    (bench.py timing notes: constant dispatch/fetch overhead cancels;
    min-per-length is robust against tunnel hiccups, which only ADD)."""

    def chain(iters):
        ci = c
        t0 = time.perf_counter()
        for _ in range(iters):
            wsums, weights, _ = step_fn(x, ci)
            ci = wsums / jnp.maximum(weights[:, None], 1e-12)
        np.asarray(ci)  # true sync: D2H fetch
        return time.perf_counter() - t0

    t_short = min(chain(iters_short) for _ in range(repeats))
    t_long = min(chain(iters_long) for _ in range(repeats))
    return max((t_long - t_short) / (iters_long - iters_short), 1e-9)


def run(tag, mesh, n, k, d, kernel, block_rows):
    rng = np.random.default_rng(0)
    dtype = jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
    x = jnp.asarray(rng.normal(size=(n, d)), dtype)
    c = jnp.asarray(rng.normal(size=(k, d)), jnp.float32)
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    c = jax.device_put(c, NamedSharding(mesh, P("model", None)))
    stats = jax.jit(
        make_sharded_fuzzy_stats(mesh, 2.0, 1e-9, block_rows=block_rows,
                                 kernel=kernel)
    )
    np.asarray(stats(x, c)[0])  # compile + warm
    per_iter = measure(stats, x, c)
    value = n / per_iter
    base = BASE_RATE / (k * d)
    # Fuzzy two-pass ceiling: 6*K*d FLOPs/pt (see module docstring).
    ceiling = 197e12 / (6.0 * k * d)
    print(
        json.dumps(
            {
                "metric": f"sharded_fuzzy_pt_iter_per_s_{tag}_K{k}_d{d}",
                "value": round(value, 1),
                "unit": "pt*iter/s",
                "vs_baseline": round(value / base, 2),
                "pct_of_twopass_ceiling": round(100.0 * value / ceiling, 1),
            }
        )
    )


def main():
    import os

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        try:
            jax.config.update("jax_platforms", env_platforms)
        except Exception:
            pass

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        # The real regime, single chip (one model shard holds all of K):
        # N = 1M bf16 (1.5 GB) — the two-pass kernel re-reads x per K-tile
        # pair, so N is HBM-bound lower than the Lloyd bench's 2M.
        run("1chip", make_mesh_2d(1, 1), n=1 << 20, k=16384, d=768,
            kernel="pallas", block_rows=0)
    else:
        run("1dev_cpu", make_mesh_2d(1, 1), n=1 << 14, k=2048, d=128,
            kernel="xla", block_rows=1 << 12)
        if len(jax.devices()) >= 8:
            run("2x4_cpu", make_mesh_2d(2, 4), n=1 << 14, k=2048, d=128,
                kernel="xla", block_rows=1 << 12)


if __name__ == "__main__":
    main()
