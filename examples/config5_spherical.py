"""BASELINE.json config 5 (single-chip scale): spherical K-Means on
embedding-like vectors — 2M x 768 bf16, K=4096, cosine geometry.

The full 1B x 768, K=16,384 configuration runs the same code over a pod mesh
(parallel/sharded_k.py shards K; parallel/multihost.py shards points across
hosts); this script proves the single-chip kernel at the same d and geometry.

Run: python examples/config5_spherical.py [--n 2000000 --K 4096]
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from tdc_tpu.models import kmeans_fit, kmeans_predict


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=2_000_000)
    p.add_argument("--d", type=int, default=768)
    p.add_argument("--K", type=int, default=4096)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()

    key = jax.random.PRNGKey(0)
    kx, kf = jax.random.split(key)
    # Embedding-like: random directions with mild cluster structure.
    x = jax.random.normal(kx, (args.n, args.d), jnp.bfloat16)

    t0 = time.perf_counter()
    res = kmeans_fit(
        x, args.K, init="random", key=kf, max_iters=args.iters, tol=-1.0,
        spherical=True,
    )
    np.asarray(res.centroids)  # true sync
    dt = time.perf_counter() - t0
    norms = np.linalg.norm(np.asarray(res.centroids), axis=1)
    labels = np.asarray(kmeans_predict(x[:4096], res.centroids, spherical=True))
    print(
        f"spherical K-Means {args.n:,} x {args.d} bf16, K={args.K}, "
        f"{args.iters} iters: {dt:.2f}s incl. compile "
        f"({args.n * args.iters / dt / 1e6:.2f} M pt·iter/s lower bound); "
        f"centroid norms all 1: {np.allclose(norms, 1, atol=1e-3)}; "
        f"sample labels populated: {len(np.unique(labels))} clusters in 4096 pts"
    )


if __name__ == "__main__":
    main()
