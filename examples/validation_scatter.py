"""Visual validation — the reference's notebook workflow as a script.

Mirrors New-Distributed-KMeans.ipynb end-to-end: synthetic blobs → distributed
K-Means → before/after scatter plots with centers overlaid (#cell22-25), plus
the convergence curve the reference commented out "for performance"
(visualization.ipynb#cell5:66-68).

Run: python examples/validation_scatter.py --out_dir /tmp/plots
"""

import argparse
import os

import numpy as np
import jax

from tdc_tpu.analysis.plots import convergence_curve, scatter_clusters
from tdc_tpu.data import make_blobs
from tdc_tpu.data.loader import NpzStream
from tdc_tpu.models import kmeans_predict, streamed_kmeans_fit


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n_obs", type=int, default=500_000)
    p.add_argument("--K", type=int, default=15)
    p.add_argument("--out_dir", default="plots")
    p.add_argument("--seed", type=int, default=123128)
    args = p.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    # visualization.ipynb shape: 500k x 3, K=15 (we plot the first 2 dims).
    x, y = make_blobs(args.seed, args.n_obs, 3, args.K, class_sep=3.0)
    res = streamed_kmeans_fit(
        NpzStream(x, args.n_obs // 4), args.K, 3, init="kmeans++",
        key=jax.random.PRNGKey(args.seed), max_iters=50, tol=1e-4,
    )
    labels = np.asarray(kmeans_predict(x, res.centroids))

    before = scatter_clusters(
        x, y, None, os.path.join(args.out_dir, "before.png"),
        title="true labels",
    )
    after = scatter_clusters(
        x, labels, np.asarray(res.centroids),
        os.path.join(args.out_dir, "after.png"),
        title=f"k-means labels (n_iter={int(res.n_iter)}, "
              f"sse={float(res.sse):.3g})",
    )
    curve = convergence_curve(
        res.history[:, 0], os.path.join(args.out_dir, "sse.png"),
    )
    print(f"converged={bool(res.converged)} n_iter={int(res.n_iter)}")
    for f in (before, after, curve):
        print("wrote", f)


if __name__ == "__main__":
    main()
