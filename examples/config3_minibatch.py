"""BASELINE.json config 3: Mini-batch K-Means, 10M x 128, K=1024.

TPU-native demonstration: batches are generated *on device* (seeded, chunked —
no host staging at all, unlike the reference which fed its whole dataset
through one feed_dict), and each mini-batch updates the centers with the
per-center learning-rate rule (models/minibatch.py — the principled version of
the reference's mean-of-batch-centers approximation, defect 8).

Run: python examples/config3_minibatch.py [--n_total 10000000]
"""

import argparse
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from tdc_tpu.models.minibatch import MiniBatchState, minibatch_step
from tdc_tpu.ops.init import init_kmeans_pp


@functools.partial(jax.jit, static_argnames=("n", "d", "k_true"))
def make_batch(key, centers_key, n, d, k_true=64):
    """On-device synthetic blob batch (same generator family as data/synthetic)."""
    centers = jax.random.uniform(centers_key, (k_true, d), minval=-3.0, maxval=3.0)
    kl, kn = jax.random.split(key)
    labels = jax.random.randint(kl, (n,), 0, k_true)
    return centers[labels] + jax.random.normal(kn, (n, d))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n_total", type=int, default=10_000_000)
    p.add_argument("--d", type=int, default=128)
    p.add_argument("--K", type=int, default=1024)
    p.add_argument("--batch_rows", type=int, default=1 << 19)  # 512K
    args = p.parse_args()

    key = jax.random.PRNGKey(123128)
    centers_key, key = jax.random.split(key)
    n_batches = args.n_total // args.batch_rows

    key, k0, k_step = jax.random.split(key, 3)
    first = make_batch(k0, centers_key, args.batch_rows, args.d)
    c0 = init_kmeans_pp(key, first, args.K)
    state = MiniBatchState(
        centroids=c0,
        counts=jnp.zeros((args.K,), jnp.float32),
        step=jnp.asarray(0, jnp.int32),
        last_sse=jnp.asarray(jnp.inf, jnp.float32),
        key=k_step,  # drives the sklearn-style low-count reassignment
    )

    t0 = time.perf_counter()
    for i in range(n_batches):
        key, kb = jax.random.split(key)
        batch = make_batch(kb, centers_key, args.batch_rows, args.d)
        state = minibatch_step(state, batch, reassignment_ratio=0.01)
    np.asarray(state.centroids)  # true sync (tunnel-safe)
    dt = time.perf_counter() - t0
    seen = n_batches * args.batch_rows
    print(
        f"mini-batch K-Means: {seen:,} pts x {args.d}d, K={args.K}: "
        f"{dt:.2f}s = {seen / dt / 1e6:.1f} M pts/s; "
        f"last batch SSE {float(state.last_sse):.4g}; "
        f"centers populated: {int((np.asarray(state.counts) > 0).sum())}/{args.K}"
    )


if __name__ == "__main__":
    main()
