"""Real-data application evidence — the committed-artifact run.

The reference's application evidence is Testing Images.ipynb#cell12-13: a
loop over real video frames (409,600×3 px each), per-frame segmentation
timing, a NaN sentinel, and a cv2.kmeans center/timing crosscheck, with the
outputs published in the notebook. This script reproduces that evidence on
data that ships with the image (zero network egress):

- **Frames loop**: sklearn's bundled real photographs (china.jpg /
  flower.jpg, 427×640×3 RGB — load_sample_images) turned into a camera-pan
  sequence of sliding 400×560 crops (224,000 real pixels per frame), run
  through apps.segmentation.segment_frames with the cv2.kmeans oracle —
  the reference's exact oracle — every other frame.
  → benchmarks/segmentation_real.csv + examples/china_frame0{,_seg}.png
- **Single image**: flower.jpg, K=3, with oracle crosscheck.
  → rows appended to the same CSV (frame = -1) + flower_seg.png
- **Digits**: the real UCI handwritten-digits dataset bundled with sklearn
  (1797×64, the MNIST-shaped config at the scale available offline; real
  MNIST requires a download), K=10, cluster purity vs true labels.
  → benchmarks/digits_real.csv

Run: python examples/real_data_evidence.py  (writes the committed artifacts)
"""

from __future__ import annotations

import csv
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_FRAMES = 10
CROP_H, CROP_W = 400, 560


def pan_frames(image: np.ndarray, n_frames: int = N_FRAMES):
    """Sliding-window crops of a real photo — a synthetic camera pan over
    real pixels, standing in for the reference's video file (which is not
    redistributable and not downloadable from this image)."""
    h, w = image.shape[:2]
    max_dx = w - CROP_W
    max_dy = h - CROP_H
    for i in range(n_frames):
        dx = round(i * max_dx / max(n_frames - 1, 1))
        dy = round(i * max_dy / max(n_frames - 1, 1))
        yield image[dy:dy + CROP_H, dx:dx + CROP_W]


def main() -> int:
    from PIL import Image
    from sklearn.datasets import load_digits, load_sample_images

    from tdc_tpu.apps.digits import run as digits_run
    from tdc_tpu.apps.segmentation import crosscheck_oracle, segment_frames, \
        segment_image

    images = load_sample_images().images  # [china (427,640,3), flower]
    china, flower = (np.asarray(im, np.float32) for im in images)

    rows = []
    # Frames loop over real pixels (reference: Testing Images.ipynb#cell12).
    for (recolored, _, _, row), frame in zip(
        segment_frames(pan_frames(china), 3, crosscheck_every=2),
        pan_frames(china),
    ):
        row["source"] = "china.jpg pan"
        row["n_pixels"] = CROP_H * CROP_W
        rows.append(row)
        print(row, flush=True)
        if row["frame"] == 0:
            Image.fromarray(frame.astype(np.uint8)).save(
                os.path.join(REPO, "examples", "china_frame0.png")
            )
            Image.fromarray(recolored).save(
                os.path.join(REPO, "examples", "china_frame0_seg.png")
            )

    # Single full image + oracle (reference: #cell13's per-frame table).
    recolored, _, _ = segment_image(flower, 3)
    Image.fromarray(recolored).save(
        os.path.join(REPO, "examples", "flower_seg.png")
    )
    name, _, _, t_ours, t_orc, worst = crosscheck_oracle(
        flower.reshape(-1, 3), 3
    )
    row = {
        "frame": -1, "seconds": round(t_ours, 4), "K": 3, "method": "kmeans",
        "oracle": name, "oracle_seconds": round(t_orc, 4),
        "refit_seconds": round(t_ours, 4), "max_center_dist": round(worst, 4),
        "source": "flower.jpg full", "n_pixels": flower.shape[0] * flower.shape[1],
    }
    rows.append(row)
    print(row, flush=True)

    fields = ["source", "frame", "n_pixels", "K", "method", "seconds",
              "oracle", "oracle_seconds", "refit_seconds", "max_center_dist"]
    with open(os.path.join(REPO, "benchmarks", "segmentation_real.csv"),
              "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=fields)
        w.writeheader()
        for row in rows:
            w.writerow({k: row.get(k, "") for k in fields})

    # Real digits (the offline stand-in for the MNIST 60k×784 config).
    t0 = time.perf_counter()
    res, _, purity, shape = digits_run(None, 10, 0, 50)
    dt = time.perf_counter() - t0
    n_digits = load_digits().data.shape[0]
    assert shape[0] == n_digits
    with open(os.path.join(REPO, "benchmarks", "digits_real.csv"),
              "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["dataset", "n", "d", "K", "n_iter", "sse", "purity",
                    "seconds"])
        w.writerow(["sklearn digits (UCI, real)", shape[0], shape[1], 10,
                    int(res.n_iter), f"{float(res.sse):.6g}",
                    f"{purity:.4f}", f"{dt:.3f}"])
    print(f"digits: purity={purity:.3f} n_iter={int(res.n_iter)} "
          f"({dt:.2f}s incl. compile)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
