"""Elastic multi-process worker — template for supervised gang runs.

Launch under the gang supervisor (any number of processes; CPU devices shown
so the demo runs anywhere):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 \\
    python -m tdc_tpu.cli.supervise --num_processes=2 --max_restarts=2 \\
        --heartbeat_timeout=300 --ckpt_root=/tmp/elastic_ck \\
        --log_dir=/tmp/elastic_logs -- python examples/elastic_worker.py

Kill any worker mid-run (kill -9 <pid>): the supervisor detects the loss,
kills the survivors, trims the shared checkpoint to the last complete step,
and relaunches; the fit resumes where it left off. SIGTERM a worker (or the
supervisor) instead and the gang drains GRACEFULLY: checkpoint at the next
safe boundary, exit with the preemption code, relaunch without charging the
restart budget. On a TPU pod, drop the JAX_PLATFORMS/XLA_FLAGS overrides
and run one process per host.

The structure to copy:
  1. install_preemption_handler() + initialize_from_env() first — the
     handler turns preemption SIGTERM into checkpoint-and-exit-75 (install
     on EVERY worker or none: gangs agree on the stop point collectively),
     and initialize_from_env joins the gang from $TDC_* variables (works
     unchanged standalone; it also re-asserts the handler over jax's own
     C-level SIGTERM notifier).
  2. Each host streams ONLY its own rows of every global batch
     (host_shard_bounds), same local count on every host.
  3. ckpt_dir comes from $TDC_CKPT_DIR — one SHARED directory for the gang
     (process 0 is the single writer, atomic state.npz per step with
     per-array CRCs; all hosts restore the same step).
"""

import os
import sys

import numpy as np

import jax

# Honor $JAX_PLATFORMS even when a site hook pre-imported jax and pinned a
# platform (the env var is only read at first import) — must run before any
# device use.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from tdc_tpu.models.streaming import streamed_kmeans_fit
from tdc_tpu.parallel.multihost import (
    barrier,
    global_mesh,
    host_shard_bounds,
    initialize_from_env,
)
from tdc_tpu.utils.preempt import install_preemption_handler


def main() -> int:
    install_preemption_handler()
    pid, nproc = initialize_from_env()

    # Demo data: derivable on every host so no distribution step is needed.
    # Real workers load their own slice of a dataset here instead.
    n_obs, n_dim, k, n_batches = 200_000, 16, 32, 8
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_obs, n_dim)).astype(np.float32)
    x[: n_obs // 4] += 5.0

    per_batch = n_obs // n_batches

    def batches():
        for b in range(n_batches):
            lo = b * per_batch
            start, end = host_shard_bounds(per_batch)
            yield x[lo + start : lo + end]

    res = streamed_kmeans_fit(
        batches, k, n_dim,
        init=x[:k],
        max_iters=30, tol=1e-4,
        mesh=global_mesh(),
        ckpt_dir=os.environ.get("TDC_CKPT_DIR"),
        ckpt_every=1,
        ckpt_every_batches=2,
        ckpt_keep_last_n=3,  # retention: crash fallback needs >= 2
    )
    print(
        f"worker {pid}/{nproc}: n_iter={int(res.n_iter)} "
        f"sse={float(res.sse):.6g} converged={bool(res.converged)} "
        f"(ran {res.n_iter_run} iterations this attempt)"
    )
    # 4. Synchronize before exit: the first process to tear down its
    #    distributed runtime cancels its peers mid-shutdown otherwise.
    barrier()
    return 0


if __name__ == "__main__":
    sys.exit(main())
