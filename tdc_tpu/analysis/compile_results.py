"""Profile-trace and results compiler — `compileResults.py` parity for TPU.

Reference: scripts/compileResults.py parses nvprof text logs (regex-split on
'==N== Profiling result:' / 'API calls:'; unit-normalized per-kernel rows) into
profling_result_*.csv / API_calls_*.csv. The TPU equivalent consumes the
perfetto trace.json.gz files emitted by jax.profiler (tdc_tpu.cli.main
--profile_dir) and produces the same table shape: one row per op/kernel with
time %, total time, call count, avg/min/max, name.

Also compiles executions_log.csv into per-method throughput pivot tables
(n_obs x K x n_devices), the reference's visualization-notebook analysis step.

Run: python -m tdc_tpu.analysis.compile_results --input_dir traces/ --output_dir out/
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os

import pandas as pd


_COLUMNS = ["time_pct", "total_s", "calls", "avg_s", "min_s", "max_s", "name"]


def _aggregate(events) -> pd.DataFrame:
    if not events:
        return pd.DataFrame(columns=_COLUMNS)
    df = pd.DataFrame(
        {"name": [e["name"] for e in events], "dur_s": [e["dur"] / 1e6 for e in events]}
    )
    g = df.groupby("name")["dur_s"]
    out = pd.DataFrame(
        {
            "total_s": g.sum(),
            "calls": g.count(),
            "avg_s": g.mean(),
            "min_s": g.min(),
            "max_s": g.max(),
        }
    )
    out["time_pct"] = 100.0 * out["total_s"] / out["total_s"].sum()
    return out.sort_values("total_s", ascending=False).reset_index()[_COLUMNS]


def parse_trace_file(path: str) -> tuple[pd.DataFrame, pd.DataFrame]:
    """Aggregate a perfetto trace into (device-op stats, host/runtime stats).

    The reference's nvprof parser emits two tables per log — per-kernel
    ('Profiling result:') and per-API-call — scripts/compileResults.py:103-105
    and :133-136. The TPU analog splits trace events by their process-name
    metadata: processes named for an accelerator ('/device:TPU:...', 'TPU
    core', 'GPU') hold device ops; everything else (Python host threads, the
    PJRT runtime) is the API-call analog. Columns in both mirror the
    reference parser: time %, total seconds, calls, avg/min/max, name.
    Durations in the trace are microseconds.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        trace = json.load(f)
    all_events = trace.get("traceEvents", [])
    pid_names = {
        e.get("pid"): str(e.get("args", {}).get("name", ""))
        for e in all_events
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }

    def is_device(pid) -> bool:
        name = pid_names.get(pid, "").lower()
        return any(t in name for t in ("tpu", "gpu", "/device:", "xla"))

    events = [
        e for e in all_events
        if e.get("ph") == "X" and "dur" in e and e.get("name")
    ]
    if not pid_names:
        # No process metadata (older traces): everything in the device table,
        # matching the round-1 single-table behavior.
        return _aggregate(events), pd.DataFrame(columns=_COLUMNS)
    device = [e for e in events if is_device(e.get("pid"))]
    host = [e for e in events if not is_device(e.get("pid"))]
    return _aggregate(device), _aggregate(host)


def compile_traces(input_dir: str, output_dir: str) -> list[str]:
    """Parse every trace under input_dir → profiling_result_<name>.csv
    (reference emitted profling_result_* — typo not reproduced)."""
    os.makedirs(output_dir, exist_ok=True)
    written = []
    pattern = os.path.join(input_dir, "**", "*.trace.json*")
    for path in sorted(glob.glob(pattern, recursive=True)):
        device_df, host_df = parse_trace_file(path)
        # Tag from the input-relative path, not the basename: jax.profiler
        # emits identically-named traces in per-run subdirectories.
        rel = os.path.relpath(path, input_dir)
        tag = rel.split(".")[0].replace(os.sep, "_") or "trace"
        out_path = os.path.join(output_dir, f"profiling_result_{tag}.csv")
        device_df.to_csv(out_path, index=False)
        written.append(out_path)
        if len(host_df):
            # The reference's second table (API_calls_*.csv,
            # scripts/compileResults.py:133-136): host/runtime-side calls.
            api_path = os.path.join(output_dir, f"API_calls_{tag}.csv")
            host_df.to_csv(api_path, index=False)
            written.append(api_path)
    return written


def compile_log(log_csv: str, output_dir: str) -> list[str]:
    """Pivot the experiment CSV into per-method throughput tables."""
    os.makedirs(output_dir, exist_ok=True)
    df = pd.read_csv(log_csv)
    written = []
    num = pd.to_numeric(df["computation_time"], errors="coerce")
    ok = df[num.notna()].copy()
    ok["computation_time"] = num[num.notna()]
    # Iterations the run actually executed: n_iter_run when logged (differs
    # from the cumulative n_iter on checkpoint resume — using n_iter there
    # would inflate throughput for resumed rows), else n_iter.
    iters = pd.to_numeric(ok["n_iter"], errors="coerce")
    if "n_iter_run" in ok.columns:
        run = pd.to_numeric(ok["n_iter_run"], errors="coerce")
        iters = run.where(run.notna(), iters)
    ok["pt_iter_per_s"] = (
        pd.to_numeric(ok["n_obs"]) * iters / ok["computation_time"]
    )
    for method, sub in ok.groupby("method_name"):
        pivot = sub.pivot_table(
            index=["n_obs", "K"], columns="num_GPUs", values="pt_iter_per_s",
            aggfunc="max",
        )
        out_path = os.path.join(output_dir, f"throughput_{method}.csv")
        pivot.to_csv(out_path)
        written.append(out_path)
    # Failure matrix: the reference's CSV doubles as a pass/fail grid (§4).
    fail = df[num.isna()]
    if len(fail):
        out_path = os.path.join(output_dir, "failures.csv")
        fail.to_csv(out_path, index=False)
        written.append(out_path)
    return written


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tdc_tpu.analysis.compile_results")
    p.add_argument("--input_dir", help="directory of jax profiler traces")
    p.add_argument("--log_csv", help="experiment results CSV to pivot")
    p.add_argument("--output_dir", required=True)
    args = p.parse_args(argv)
    written = []
    if args.input_dir:
        written += compile_traces(args.input_dir, args.output_dir)
    if args.log_csv:
        written += compile_log(args.log_csv, args.output_dir)
    for w in written:
        print(w)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
