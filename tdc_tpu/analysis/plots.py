"""Validation plots — the reference's notebook scatter-plot checks as a module.

Reference: New-Distributed-KMeans.ipynb#cell22-25 and visualization.ipynb
#cell4/#cell6: scatter of (subsampled) points colored by label with centers
overlaid, before/after. Headless here (Agg backend), writes PNGs.
"""

from __future__ import annotations

import numpy as np


def scatter_clusters(
    x: np.ndarray,
    labels: np.ndarray | None,
    centers: np.ndarray | None,
    out_path: str,
    *,
    max_points: int = 20000,
    title: str = "",
    seed: int = 0,
):
    """2-D scatter (first two dims) colored by label, centers as X markers."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    x = np.asarray(x)
    if x.shape[0] > max_points:
        idx = np.random.default_rng(seed).choice(x.shape[0], max_points, replace=False)
        x = x[idx]
        labels = labels[idx] if labels is not None else None
    fig, ax = plt.subplots(figsize=(6, 6))
    ax.scatter(x[:, 0], x[:, 1], c=labels, s=2, cmap="tab20", alpha=0.5)
    if centers is not None:
        centers = np.asarray(centers)
        ax.scatter(centers[:, 0], centers[:, 1], c="black", s=120, marker="x",
                   linewidths=2, label="centers")
        ax.legend()
    ax.set_title(title)
    fig.tight_layout()
    fig.savefig(out_path, dpi=100)
    plt.close(fig)
    return out_path


def convergence_curve(sse_per_iter, out_path: str, *, title: str = "SSE per iteration"):
    """Cost-vs-iteration plot (the metric the reference commented out 'for
    performance', visualization.ipynb#cell5:66-68 — cheap on TPU)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(6, 4))
    ax.plot(np.arange(1, len(sse_per_iter) + 1), sse_per_iter, marker="o")
    ax.set_xlabel("iteration")
    ax.set_ylabel("SSE")
    ax.set_title(title)
    ax.set_yscale("log")
    fig.tight_layout()
    fig.savefig(out_path, dpi=100)
    plt.close(fig)
    return out_path
