"""Clustering quality metrics, device-resident and blockwise.

The reference validated clusterings by eyeballing scatter plots and an
external-oracle center comparison (SURVEY.md §4) — it computed no quality
metric at all beyond the SSE it commented out "for performance". This module
provides the standard internal metrics, shaped for TPU:

- silhouette_score: the O(N²) pairwise work is done in N-blocks, and the
  per-cluster mean distances come from a (B, N) × (N, K) one-hot matmul per
  block — the MXU does the reduction, and no N×N matrix ever exists.
- davies_bouldin_score / calinski_harabasz_score: O(N·K) from one pass of
  per-cluster sufficient statistics.

All match sklearn.metrics (tests/test_metrics.py) to f32 tolerance.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tdc_tpu.ops.distance import pairwise_sq_dist


@partial(jax.jit, static_argnames=("k", "block_rows"))
def _silhouette_device(x, labels, k: int, block_rows: int):
    from tdc_tpu.ops.assign import _pad_rows

    n, d = x.shape
    one_hot = jax.nn.one_hot(labels, k, dtype=jnp.float32)  # (N, K)
    counts = jnp.sum(one_hot, axis=0)  # (K,)

    xp, _ = _pad_rows(x, block_rows)
    lp, _ = _pad_rows(labels, block_rows)
    xb = xp.reshape(-1, block_rows, d)
    lb = lp.reshape(-1, block_rows)

    def block_sums(args):
        blk, blab = args
        # (B, N) true distances to every point, then per-cluster sums on the
        # MXU; the (B, N) tile is the only large intermediate.
        dist = jnp.sqrt(jnp.maximum(pairwise_sq_dist(blk, x), 0.0))
        s = dist @ one_hot  # (B, K) sum of distances to each cluster
        own = jnp.take_along_axis(s, blab[:, None], axis=1)[:, 0]
        own_count = counts[blab]
        # a(i): mean distance to OWN cluster, excluding self (dist 0).
        a = own / jnp.maximum(own_count - 1.0, 1.0)
        # b(i): min over OTHER clusters of mean distance.
        mean_other = s / jnp.maximum(counts[None, :], 1.0)
        mean_other = jnp.where(
            jax.nn.one_hot(blab, k, dtype=bool), jnp.inf, mean_other
        )
        mean_other = jnp.where(counts[None, :] > 0, mean_other, jnp.inf)
        b = jnp.min(mean_other, axis=1)
        s_i = jnp.where(
            own_count > 1.0,
            (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30),
            0.0,  # sklearn: singleton clusters contribute 0
        )
        return s_i

    s_blocks = jax.lax.map(block_sums, (xb, lb))  # (n_blocks, B)
    s_flat = s_blocks.reshape(-1)[:n]
    return jnp.mean(s_flat)


def _encode_labels(labels) -> tuple[jax.Array, int]:
    """Contiguous 0..k-1 label encoding (sklearn does the same before
    scoring): un-used label ids must not create phantom empty clusters."""
    uniq, enc = np.unique(np.asarray(labels), return_inverse=True)
    return jnp.asarray(enc, jnp.int32), len(uniq)


def silhouette_score(x, labels, *, block_rows: int = 4096) -> float:
    """Mean silhouette coefficient (sklearn.metrics.silhouette_score parity,
    Euclidean). Blockwise: peak memory is (block_rows, N) f32."""
    x = jnp.asarray(x, jnp.float32)
    labels, k = _encode_labels(labels)
    if k < 2:
        raise ValueError("silhouette requires at least 2 clusters")
    block_rows = min(block_rows, x.shape[0])
    return float(_silhouette_device(x, labels, k, block_rows))


@partial(jax.jit, static_argnames=("k",))
def _cluster_moments(x, labels, k: int):
    """(counts, centroids, within-dispersion per cluster Σ‖x−c‖²,
    mean-dist-to-centroid per cluster)."""
    one_hot = jax.nn.one_hot(labels, k, dtype=jnp.float32)
    counts = jnp.sum(one_hot, axis=0)
    sums = one_hot.T @ x.astype(jnp.float32)
    centroids = sums / jnp.maximum(counts[:, None], 1.0)
    d2 = pairwise_sq_dist(x, centroids)  # (N, K)
    own_d2 = jnp.take_along_axis(d2, labels[:, None], axis=1)[:, 0]
    within = jnp.zeros((k,), jnp.float32).at[labels].add(own_d2)
    mean_dist = (
        jnp.zeros((k,), jnp.float32).at[labels].add(jnp.sqrt(
            jnp.maximum(own_d2, 0.0)))
        / jnp.maximum(counts, 1.0)
    )
    return counts, centroids, within, mean_dist


def davies_bouldin_score(x, labels) -> float:
    """sklearn.metrics.davies_bouldin_score parity: mean over clusters of the
    worst (S_i + S_j) / ‖c_i − c_j‖ ratio."""
    x = jnp.asarray(x, jnp.float32)
    labels, k = _encode_labels(labels)
    if k < 2:
        raise ValueError("davies_bouldin requires at least 2 clusters")
    counts, centroids, _, s = _cluster_moments(x, labels, k)
    m = jnp.sqrt(jnp.maximum(pairwise_sq_dist(centroids, centroids), 0.0))
    ratio = (s[:, None] + s[None, :]) / jnp.where(m > 0, m, jnp.inf)
    ratio = jnp.where(jnp.eye(k, dtype=bool), -jnp.inf, ratio)
    return float(jnp.mean(jnp.max(ratio, axis=1)))


def calinski_harabasz_score(x, labels) -> float:
    """sklearn.metrics.calinski_harabasz_score parity:
    (between / (k−1)) / (within / (n−k))."""
    x = jnp.asarray(x, jnp.float32)
    labels, k = _encode_labels(labels)
    n = x.shape[0]
    if k < 2:
        raise ValueError("calinski_harabasz requires at least 2 clusters")
    counts, centroids, within, _ = _cluster_moments(x, labels, k)
    grand = jnp.mean(x.astype(jnp.float32), axis=0)
    between = jnp.sum(
        counts * jnp.sum((centroids - grand[None, :]) ** 2, axis=1)
    )
    w = float(jnp.sum(within))
    if w == 0.0:
        return 1.0  # sklearn sentinel: every point on its cluster mean
    return float(between) * (n - k) / (w * max(k - 1, 1))


__all__ = [
    "silhouette_score",
    "davies_bouldin_score",
    "calinski_harabasz_score",
]
