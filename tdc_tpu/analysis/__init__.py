"""Results compilation and profile-trace parsing (reference L6)."""
