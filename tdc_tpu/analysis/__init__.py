"""Results compilation, profile-trace parsing, and clustering quality
metrics (reference L6)."""

from tdc_tpu.analysis.metrics import (
    calinski_harabasz_score,
    davies_bouldin_score,
    silhouette_score,
)

__all__ = [
    "calinski_harabasz_score",
    "davies_bouldin_score",
    "silhouette_score",
]
