"""Centroid (K-axis) sharding over a 2-D (data × model) mesh — the
tensor-parallel analog for clustering (SURVEY.md §2.3: "optional K-axis
sharding of centroids for the K = 16,384 regime", BASELINE.json config 5).

Layout: points sharded over the 'data' axis, centroids sharded over the
'model' axis. Each device computes distances only against its K/Pm local
centroids (the N×K work and memory split Pm ways), the global argmin is a
small (Pm, n_local) all-gather of per-shard (min, argmin) pairs over ICI, and
the sufficient statistics stay *sharded over K* — only a psum over the data
axis touches them, so centroid state never needs to fit on one device.

The reference has no counterpart: its centroid state was a single /cpu:0
variable broadcast to every tower (scripts/distribuitedClustering.py:199).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdc_tpu.ops.distance import pairwise_sq_dist
from tdc_tpu.models.kmeans import KMeansResult

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh_2d(n_data: int, n_model: int) -> Mesh:
    """(data, model) mesh over the first n_data*n_model devices."""
    devs = jax.devices()
    need = n_data * n_model
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(
        np.asarray(devs[:need]).reshape(n_data, n_model), (DATA_AXIS, MODEL_AXIS)
    )


class ShardedStats(NamedTuple):
    sums: jax.Array  # (K, d) — sharded over K (model axis)
    counts: jax.Array  # (K,) — sharded over K
    sse: jax.Array  # () — replicated


def _local_stats(x_loc, c_loc):
    """Per-(data, model) shard body; returns K-sharded stats."""
    k_per = c_loc.shape[0]
    m_idx = jax.lax.axis_index(MODEL_AXIS)
    d2 = pairwise_sq_dist(x_loc, c_loc)  # (n_loc, K/Pm)
    lmin = jnp.min(d2, axis=1)  # (n_loc,)
    larg = jnp.argmin(d2, axis=1).astype(jnp.int32) + m_idx * k_per
    # Global argmin across the model axis: all_gather the per-shard champions
    # (2 small (Pm, n_loc) arrays over ICI — not the distances).
    mins = jax.lax.all_gather(lmin, MODEL_AXIS)  # (Pm, n_loc)
    args = jax.lax.all_gather(larg, MODEL_AXIS)  # (Pm, n_loc)
    w = jnp.argmin(mins, axis=0)  # (n_loc,) winning shard per point
    gmin = jnp.take_along_axis(mins, w[None, :], 0)[0]
    garg = jnp.take_along_axis(args, w[None, :], 0)[0]
    # Stats for MY K-shard only: one_hot maps out-of-shard assignments to 0.
    rel = garg - m_idx * k_per
    one_hot = jax.nn.one_hot(rel, k_per, dtype=jnp.float32)  # (n_loc, K/Pm)
    sums = jax.lax.dot_general(
        one_hot,
        x_loc.astype(jnp.float32),
        (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )
    counts = jnp.sum(one_hot, axis=0)
    # Reduce over the data axis only; K stays sharded. SSE is identical on
    # every model shard, so the data-psum leaves it replicated.
    sums = jax.lax.psum(sums, DATA_AXIS)
    counts = jax.lax.psum(counts, DATA_AXIS)
    sse = jax.lax.psum(jnp.sum(gmin), DATA_AXIS)
    return sums, counts, sse, garg


def sharded_lloyd_step(mesh: Mesh):
    """Returns a jit-able step: (x sharded (data,), c sharded (model,)) →
    (new_c sharded (model,), shift, sse)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=(P(MODEL_AXIS, None), P(), P()),
        check_vma=False,
    )
    def step(x_loc, c_loc):
        sums, counts, sse, _ = _local_stats(x_loc, c_loc)
        new_c = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts[:, None], 1.0),
            c_loc.astype(jnp.float32),
        )
        # Shift must be the global max over all K shards.
        shift_local = jnp.max(jnp.linalg.norm(new_c - c_loc, axis=-1))
        shift = jax.lax.pmax(shift_local, MODEL_AXIS)
        return new_c, shift, sse

    return step


def sharded_assign(mesh: Mesh):
    """Jit-able global assignment under the 2-D layout: labels sharded (data,)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    def assign(x_loc, c_loc):
        _, _, _, garg = _local_stats(x_loc, c_loc)
        return garg

    return assign


def kmeans_fit_sharded(
    x,
    k: int,
    mesh: Mesh,
    *,
    init,
    max_iters: int = 20,
    tol: float = 1e-4,
) -> KMeansResult:
    """Lloyd K-Means with points sharded over 'data' and centroids over
    'model'. init must be an explicit (K, d) array (seed at smaller scale or
    with ops.init / ops.kmeans_parallel first)."""
    n_data = mesh.devices.shape[0]
    n_model = mesh.devices.shape[1]
    x = jnp.asarray(x)
    if x.shape[0] % n_data != 0:
        raise ValueError(f"N={x.shape[0]} not divisible by data axis {n_data}")
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    c = jnp.asarray(init, jnp.float32)
    if c.shape[0] != k:
        raise ValueError(f"init has {c.shape[0]} rows, expected {k}")
    x = jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS, None)))
    c = jax.device_put(c, NamedSharding(mesh, P(MODEL_AXIS, None)))
    step = jax.jit(sharded_lloyd_step(mesh))

    shift = float("inf")
    sse = float("inf")
    n_iter = 0
    converged = False
    for n_iter in range(1, max_iters + 1):
        c, shift_dev, sse_dev = step(x, c)
        shift = float(shift_dev)
        sse = float(sse_dev)
        if tol >= 0 and shift <= tol:
            converged = True
            break
    return KMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        sse=jnp.asarray(sse, jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
    )
