"""Centroid (K-axis) sharding over a 2-D (data × model) mesh — the
tensor-parallel analog for clustering (SURVEY.md §2.3: "optional K-axis
sharding of centroids for the K = 16,384 regime", BASELINE.json config 5).

Layout: points sharded over the 'data' axis, centroids sharded over the
'model' axis. Each device computes distances only against its K/Pm local
centroids (the N×K work and memory split Pm ways), the global argmin is a
small (Pm, n_local) all-gather of per-shard (min, argmin) pairs over ICI, and
the sufficient statistics stay *sharded over K* — only a psum over the data
axis touches them, so centroid state never needs to fit on one device.

The per-shard tower is N-blocked (lax.scan) so the (block, K/Pm) distance /
one-hot intermediates stay bounded at any N, and can run either the XLA
matmul-form distance or the Pallas blockwise online-argmin kernel
(ops/pallas_kernels.distance_argmin — no (n, K/Pm) buffer at all).

The reference has no counterpart: its centroid state was a single /cpu:0
variable broadcast to every tower (scripts/distribuitedClustering.py:199),
and its N×K work could not exceed one device's memory (the root cause of its
271/320 InternalError rows).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Iterable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdc_tpu.ops.distance import pairwise_sq_dist
from tdc_tpu.models.kmeans import KMeansResult, _normalize, resolve_init
from tdc_tpu.utils.heartbeat import maybe_beat

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh_2d(n_data: int, n_model: int) -> Mesh:
    """(data, model) mesh over the first n_data*n_model devices."""
    devs = jax.devices()
    need = n_data * n_model
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(
        np.asarray(devs[:need]).reshape(n_data, n_model), (DATA_AXIS, MODEL_AXIS)
    )


def _block_champions(x_blk, c_loc, kernel: str):
    """Per-block global (min d², argmin) across all K shards.

    Each model shard scores the block against its local centroids, then the
    per-shard champions — two (Pm, block) arrays, not distances — cross ICI
    via all_gather for the global argmin.
    """
    k_per = c_loc.shape[0]
    m_idx = jax.lax.axis_index(MODEL_AXIS)
    if kernel == "pallas":
        from tdc_tpu.ops.pallas_kernels import distance_argmin

        # 1024-wide K-tiles measured 7% faster than the 512 default at the
        # K=16,384·d=768 regime (80% vs 74% MFU) and stay within VMEM.
        blk_k = 1024 if k_per >= 1024 else 512
        arg, lmin = distance_argmin(
            x_blk, c_loc, block_k=blk_k, return_dist=True
        )
    else:
        d2 = pairwise_sq_dist(x_blk, c_loc)  # (block, K/Pm)
        lmin = jnp.min(d2, axis=1)
        arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
    larg = arg + m_idx * k_per
    mins = jax.lax.all_gather(lmin, MODEL_AXIS)  # (Pm, block)
    args = jax.lax.all_gather(larg, MODEL_AXIS)  # (Pm, block)
    # Champion selection as pure reductions: per-column take_along_axis
    # gathers on (Pm, N) measured 3.75 ms each at N=524k (scalar-gather
    # layout); min + masked-min is VPU-cheap and deterministic (distance
    # ties across shards resolve to the lowest centroid index).
    gmin = jnp.min(mins, axis=0)
    garg = jnp.min(jnp.where(mins == gmin[None, :], args, 2**30), axis=0)
    return gmin, garg


def _block_stats(x_blk, c_loc, kernel: str):
    """(sums (K/Pm, d), counts (K/Pm,), sse ()) for one N-block — local to
    this (data, model) shard pair; data-psum'd by the caller.

    Stats for MY K-shard only, via the sort-based segment sum
    (ops/sorted_stats): out-of-shard assignments map to the sentinel label
    K/Pm and drop out. The round-3 dense one-hot contraction here cost a
    full second distance pass (2·K·d MXU FLOPs per point at HIGHEST
    precision) plus an HBM-materialized (block, K/Pm) one-hot — it capped
    the K=16,384 regime at ~40% of the distance-only roofline
    (benchmarks/ROOFLINE_SHARDED.md)."""
    from tdc_tpu.ops.sorted_stats import sorted_cluster_stats

    k_per = c_loc.shape[0]
    m_idx = jax.lax.axis_index(MODEL_AXIS)
    gmin, garg = _block_champions(x_blk, c_loc, kernel)
    rel = garg - m_idx * k_per
    sums, counts = sorted_cluster_stats(x_blk, rel, k_per)
    return sums, counts, jnp.sum(gmin)


def make_sharded_stats(mesh: Mesh, kernel: str = "xla", block_rows: int = 0):
    """Returns a jit-able fn(x, c) → (sums, counts, sse): x sharded (data,),
    c sharded (model,); sums/counts stay K-sharded, sse replicated.

    block_rows > 0 scans the local points in (block_rows, d) tiles so the
    per-shard intermediates never exceed O(block_rows · K/Pm) regardless of N
    (requires the local shard size to be a block_rows multiple — pad upstream
    with zero rows and correct via `padding_correction`).
    """

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS), P()),
        check_vma=False,
    )
    def stats(x_loc, c_loc):
        n_loc, d = x_loc.shape
        k_per = c_loc.shape[0]
        # The N-block scan exists to bound the XLA path's (block, K/Pm)
        # distance intermediates. The pallas path has none — its only
        # N-sized arrays are the (N,) champion columns — and profiling showed
        # the per-block sorts inside the scan cost ~25 ms/step at N=524k
        # (8 sorts of 64k vs one of 512k): one-shot is strictly better.
        if block_rows and n_loc > block_rows and kernel != "pallas":
            if n_loc % block_rows != 0:
                raise ValueError(
                    f"local shard rows {n_loc} not divisible by "
                    f"block_rows={block_rows}"
                )
            xb = x_loc.reshape(n_loc // block_rows, block_rows, d)

            def body(acc, blk):
                s, ct, e = _block_stats(blk, c_loc, kernel)
                return (acc[0] + s, acc[1] + ct, acc[2] + e), None

            zero = (
                jnp.zeros((k_per, d), jnp.float32),
                jnp.zeros((k_per,), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            (sums, counts, sse), _ = jax.lax.scan(body, zero, xb)
        else:
            sums, counts, sse = _block_stats(x_loc, c_loc, kernel)
        # Reduce over the data axis only; K stays sharded. The champions are
        # identical on every model shard, so sse comes out replicated.
        sums = jax.lax.psum(sums, DATA_AXIS)
        counts = jax.lax.psum(counts, DATA_AXIS)
        sse = jax.lax.psum(sse, DATA_AXIS)
        return sums, counts, sse

    return stats


def padding_correction(counts, sse, centroids, n_pad):
    """Remove the exact contribution of `n_pad` zero-padding rows: each lands
    on the global argmin-‖c‖² cluster with zero Σx, one count, ‖c_j‖² sse
    (same correction as models/streaming and the fused Pallas kernel)."""
    c2 = jnp.sum(centroids.astype(jnp.float32) ** 2, axis=-1)
    j = jnp.argmin(c2)
    n_pad = jnp.asarray(n_pad, jnp.float32)
    return counts.at[j].add(-n_pad), sse - n_pad * c2[j]


def make_sharded_lloyd_step(
    mesh: Mesh,
    kernel: str = "xla",
    block_rows: int = 0,
    spherical: bool = False,
):
    """Returns a jit'd step: (x (data,)-sharded, c (model,)-sharded, n_valid)
    → (new_c (model,)-sharded, shift, sse). Zero-padding rows beyond n_valid
    are corrected exactly."""
    stats_fn = make_sharded_stats(mesh, kernel, block_rows)

    @jax.jit
    def step(x, c, n_valid):
        sums, counts, sse = stats_fn(x, c)
        n_pad = x.shape[0] - n_valid
        counts, sse = padding_correction(counts, sse, c, n_pad)
        cf = c.astype(jnp.float32)
        new_c = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts[:, None], 1.0),
            cf,
        )
        if spherical:
            new_c = _normalize(new_c)
        shift = jnp.max(jnp.linalg.norm(new_c - cf, axis=-1))
        return new_c, shift, sse

    return step


def sharded_lloyd_step(mesh: Mesh):
    """Back-compat wrapper: (x, c) → (new_c, shift, sse), no padding."""
    step = make_sharded_lloyd_step(mesh)

    def run(x, c):
        return step(x, c, x.shape[0])

    return run


def sharded_assign(mesh: Mesh, kernel: str = "xla", block_rows: int = 0):
    """Jit-able global assignment under the 2-D layout: labels sharded
    (data,). Blocked the same way as the stats tower."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    def assign(x_loc, c_loc):
        n_loc, d = x_loc.shape
        if block_rows and n_loc > block_rows and kernel != "pallas":
            if n_loc % block_rows != 0:
                raise ValueError(
                    f"local shard rows {n_loc} not divisible by "
                    f"block_rows={block_rows}"
                )
            xb = x_loc.reshape(n_loc // block_rows, block_rows, d)
            _, garg = jax.lax.scan(
                lambda _, blk: (None, _block_champions(blk, c_loc, kernel)[1]),
                None,
                xb,
            )
            return garg.reshape(-1)
        return _block_champions(x_loc, c_loc, kernel)[1]

    return assign


def _resolve_init_sharded(x, k: int, init, key, *, sample_rows: int = 65536):
    """Init for the K-sharded fit. Arrays pass through; names resolve on a
    deterministic host-side subsample (the seeding problem is tiny next to
    the fit — k-means++ on ≤64k rows — and must not require the full dataset
    on one device)."""
    if hasattr(init, "shape"):
        c = jnp.asarray(init, jnp.float32)
        if c.shape[0] != k:
            raise ValueError(f"init has {c.shape[0]} rows, expected {k}")
        return c
    sample = jnp.asarray(np.asarray(x[: min(x.shape[0], sample_rows)]))
    return resolve_init(sample, k, init, key)


def kmeans_fit_sharded(
    x,
    k: int,
    mesh: Mesh,
    *,
    init,
    key=None,
    max_iters: int = 20,
    tol: float = 1e-4,
    spherical: bool = False,
    kernel: str = "xla",
    block_rows: int = 0,
) -> KMeansResult:
    """Lloyd K-Means with points sharded over 'data' and centroids over
    'model' (the K=16,384 regime). init may be a (K, d) array or an init name
    ('kmeans++'/'random'/'first_k'/'kmeans||'), resolved on a host subsample.

    Multi-process meshes (SURVEY §7 step 7: sharded centroid tiles at pod
    scale) are supported by passing `x` as the full NUMPY array, identical on
    every process: numpy stays host-side until the global device_put, which
    places only this process's addressable shards. (A jnp input would commit
    to one local device first and cannot be resharded across processes.)
    """
    n_data = mesh.devices.shape[0]
    n_model = mesh.devices.shape[1]
    if not isinstance(x, np.ndarray):
        x = jnp.asarray(x)
    if x.shape[0] % n_data != 0:
        raise ValueError(f"N={x.shape[0]} not divisible by data axis {n_data}")
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    if spherical:
        if isinstance(x, np.ndarray):
            norms = np.linalg.norm(x, axis=-1, keepdims=True)
            x = (x / np.maximum(norms, 1e-12)).astype(np.float32)
        else:
            x = _normalize(x.astype(jnp.float32))
    c = _resolve_init_sharded(x, k, init, key)
    if spherical:
        c = _normalize(c)
    x = jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS, None)))
    c = jax.device_put(c, NamedSharding(mesh, P(MODEL_AXIS, None)))
    step = make_sharded_lloyd_step(mesh, kernel, block_rows, spherical)

    shift = float("inf")
    n_iter = 0
    converged = False
    for n_iter in range(1, max_iters + 1):
        c, shift_dev, _ = step(x, c, x.shape[0])
        shift = float(shift_dev)
        if tol >= 0 and shift <= tol:
            converged = True
            break
    # One extra step so the reported SSE matches the *returned* centroids
    # (every other fit path does the same; the in-loop SSE is measured
    # against the pre-update centroids). step's SSE is computed against its
    # INPUT centroids, so re-invoking the already-compiled step and
    # discarding its update gives exactly that with no extra compile.
    _, _, sse = step(x, c, x.shape[0])
    return KMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        sse=jnp.asarray(float(sse), jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
    )


class _ShardedAcc(NamedTuple):
    sums: jax.Array  # (K, d) — K-sharded
    counts: jax.Array  # (K,) — K-sharded
    sse: jax.Array  # () — replicated


def streamed_kmeans_fit_sharded(
    batches: Callable[[], Iterable],
    k: int,
    d: int,
    mesh: Mesh,
    *,
    init,
    key=None,
    max_iters: int = 20,
    tol: float = 1e-4,
    spherical: bool = False,
    kernel: str = "xla",
    block_rows: int = 0,
    dtype=None,
    prefetch: int = 0,
    ckpt_dir: str | None = None,
    ckpt_every: int = 1,
    ckpt_every_batches: int | None = None,
) -> KMeansResult:
    """Exact out-of-core Lloyd under the 2-D (data × model) layout — the
    1B×768, K=16,384 configuration: batches stream host→device, each batch's
    K-sharded sufficient stats accumulate on-device across the pass, and the
    centroid state never exists unsharded.

    `batches` follows the models/streaming contract: a zero-arg callable
    returning a fresh iterator of (rows, d) arrays per Lloyd iteration.
    `dtype` (e.g. jnp.bfloat16) converts batches host-side before transfer —
    the MXU fast path for the bf16 K=16,384 regime; stats stay f32.

    ckpt_dir enables checkpoint/resume with the models/streaming contract
    (per-iteration saves every `ckpt_every` iterations; mid-pass accumulator
    + batch-cursor saves every `ckpt_every_batches` batches; resume is
    bit-identical to the uninterrupted fit). Checkpoint I/O gathers the
    (K, d) centroids/accumulator to THIS host, so it is single-process-mesh
    only — the multi-hour 1B-row single-host regime this driver targets.
    """
    from tdc_tpu.models.streaming import (
        _StreamCheckpointer,
        _mesh_layout,
        _run_pass,
    )

    n_data = int(mesh.devices.shape[0])
    n_model = int(mesh.devices.shape[1])
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    if ckpt_dir is not None and _mesh_layout(mesh)[0] > 1:
        raise ValueError(
            "K-sharded checkpointing gathers state to one host and supports "
            "single-process meshes only (multi-process gang checkpointing "
            "of K-sharded state is not implemented)"
        )
    pad_multiple = n_data * max(block_rows, 1)

    ckpt = _StreamCheckpointer(
        ckpt_dir, k, d,
        params={"spherical": bool(spherical), "shard_model": float(n_model)},
        acc_map={"acc_sums": "sums", "acc_counts": "counts",
                 "acc_sse": "sse"},
        key=key,
    )
    # Restore FIRST (models/streaming convention): a resume must not re-pay
    # init resolution, and must report the checkpointed state faithfully.
    state = ckpt.restore(_ShardedAcc, None)
    shift = state.shift
    history = state.history
    start_iter = state.start_iter
    resume_cursor, resume_rows = state.cursor, state.rows_seen
    resume_acc = state.acc
    if state.centroids is not None:
        c = jnp.asarray(state.centroids, jnp.float32)
    else:
        first = None
        if not hasattr(init, "shape"):
            first = np.asarray(next(iter(batches())))
            if spherical:
                first = np.asarray(
                    _normalize(jnp.asarray(first, jnp.float32))
                )
            init = _resolve_init_sharded(first, k, init, key)
        c = jnp.asarray(init, jnp.float32)
        if c.shape != (k, d):
            raise ValueError(f"init shape {c.shape} != {(k, d)}")
        if spherical:
            c = _normalize(c)
    c = jax.device_put(c, NamedSharding(mesh, P(MODEL_AXIS, None)))
    if resume_acc is not None:
        resume_acc = _ShardedAcc(
            sums=jax.device_put(
                resume_acc.sums, NamedSharding(mesh, P(MODEL_AXIS, None))
            ),
            counts=jax.device_put(
                resume_acc.counts, NamedSharding(mesh, P(MODEL_AXIS))
            ),
            sse=resume_acc.sse,
        )

    stats_fn = make_sharded_stats(mesh, kernel, block_rows)

    @jax.jit
    def accumulate(acc: _ShardedAcc, x, c, n_valid) -> _ShardedAcc:
        sums, counts, sse = stats_fn(x, c)
        n_pad = x.shape[0] - n_valid
        counts, sse = padding_correction(counts, sse, c, n_pad)
        return _ShardedAcc(acc.sums + sums, acc.counts + counts, acc.sse + sse)

    @jax.jit
    def update(acc: _ShardedAcc, c):
        cf = c.astype(jnp.float32)
        new_c = jnp.where(
            acc.counts[:, None] > 0,
            acc.sums / jnp.maximum(acc.counts[:, None], 1.0),
            cf,
        )
        if spherical:
            new_c = _normalize(new_c)
        shift = jnp.max(jnp.linalg.norm(new_c - cf, axis=-1))
        return new_c, shift

    def zero_acc() -> _ShardedAcc:
        return _ShardedAcc(
            sums=jax.device_put(
                jnp.zeros((k, d), jnp.float32),
                NamedSharding(mesh, P(MODEL_AXIS, None)),
            ),
            counts=jax.device_put(
                jnp.zeros((k,), jnp.float32), NamedSharding(mesh, P(MODEL_AXIS))
            ),
            sse=jnp.zeros((), jnp.float32),
        )

    def put_batch(batch):
        batch = np.asarray(batch)
        n_valid = batch.shape[0]
        rem = (-n_valid) % pad_multiple
        if rem:
            batch = np.pad(batch, ((0, rem), (0, 0)))
        if dtype is not None:
            import ml_dtypes  # noqa: F401 — registers bfloat16 with numpy

            batch = batch.astype(np.dtype(dtype))  # host-side: halves transfer
        xb = jax.device_put(batch, NamedSharding(mesh, P(DATA_AXIS, None)))
        if spherical:
            xb = _spherical_rows(xb)
        return xb, n_valid

    @jax.jit
    def _spherical_rows(xb):
        # Normalize real rows; zero padding rows stay zero (norm 0 guard).
        norms = jnp.linalg.norm(xb, axis=-1, keepdims=True)
        return jnp.where(norms > 0, xb / jnp.maximum(norms, 1e-12), xb)

    def full_pass(c, n_iter=0, skip=0, acc0=None, rows0=0):
        def step(acc, batch):
            maybe_beat()  # supervised-gang liveness
            xb, n_valid = put_batch(batch)
            return accumulate(acc, xb, c, n_valid), n_valid

        return _run_pass(
            batches, prefetch, zero_acc, step,
            ckpt=ckpt, ckpt_every_batches=ckpt_every_batches, n_iter=n_iter,
            skip=skip, acc0=acc0, rows0=rows0,
            save_args=(c, shift, history),
        )

    n_iter = start_iter
    resume_converged = tol >= 0 and shift <= tol
    converged = resume_converged
    iters = (
        () if resume_converged else range(start_iter + 1, max_iters + 1)
    )
    for n_iter in iters:
        acc = full_pass(c, n_iter, skip=resume_cursor, acc0=resume_acc,
                        rows0=resume_rows)
        resume_cursor, resume_acc, resume_rows = 0, None, 0
        c, shift_dev = update(acc, c)
        shift = float(shift_dev)
        history.append((float(acc.sse), shift))
        done = tol >= 0 and shift <= tol
        if ckpt_dir is not None and (done or n_iter % ckpt_every == 0
                                     or n_iter == max_iters):
            ckpt.save(n_iter, c, shift, history)
        if done:
            converged = True
            break
    # Extra stats pass: report the SSE of the returned centroids, not the
    # pre-update ones (parity with streamed_kmeans_fit).
    sse = float(full_pass(c).sse)
    return KMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        sse=jnp.asarray(sse, jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
        history=np.asarray(history, np.float32),
        n_iter_run=n_iter - start_iter,
    )
