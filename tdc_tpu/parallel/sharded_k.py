"""Centroid (K-axis) sharding over a 2-D (data × model) mesh — the
tensor-parallel analog for clustering (SURVEY.md §2.3: "optional K-axis
sharding of centroids for the K = 16,384 regime", BASELINE.json config 5).

Layout: points sharded over the 'data' axis, centroids sharded over the
'model' axis. Each device computes distances only against its K/Pm local
centroids (the N×K work and memory split Pm ways), the global argmin is a
small (Pm, n_local) all-gather of per-shard (min, argmin) pairs over ICI, and
the sufficient statistics stay *sharded over K* — only a psum over the data
axis touches them, so centroid state never needs to fit on one device.

The per-shard tower is N-blocked (lax.scan) so the (block, K/Pm) distance /
one-hot intermediates stay bounded at any N, and can run either the XLA
matmul-form distance or the Pallas blockwise online-argmin kernel
(ops/pallas_kernels.distance_argmin — no (n, K/Pm) buffer at all).

The reference has no counterpart: its centroid state was a single /cpu:0
variable broadcast to every tower (scripts/distribuitedClustering.py:199),
and its N×K work could not exceed one device's memory (the root cause of its
271/320 InternalError rows).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Callable, Iterable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdc_tpu.data import ingest as ingest_lib
from tdc_tpu.data import spill as spill_lib
from tdc_tpu.obs import trace
from tdc_tpu.parallel import gather as gather_lib
from tdc_tpu.parallel.compat import shard_map
from tdc_tpu.parallel.meshspec import MeshSpec
from tdc_tpu.parallel import reshard as reshard_lib

from tdc_tpu.ops.distance import pairwise_sq_dist
from tdc_tpu.models.kmeans import KMeansResult, _normalize, resolve_init
from tdc_tpu.models.resident import chunk_iters_for as _chunk_iters_for
from tdc_tpu.utils.heartbeat import maybe_beat

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh_2d(n_data: int, n_model: int) -> Mesh:
    """(data, model) mesh over the first n_data*n_model devices."""
    devs = jax.devices()
    need = n_data * n_model
    if need > len(devs):
        raise ValueError(f"need {need} devices, have {len(devs)}")
    return Mesh(
        np.asarray(devs[:need]).reshape(n_data, n_model), (DATA_AXIS, MODEL_AXIS)
    )


def _block_champions(x_blk, c_loc, kernel: str, shifted: bool = False,
                     gather: str = "fp32"):
    """Per-block global (min d², argmin) across all K shards.

    Each model shard scores the block against its local centroids, then the
    per-shard champions — two (Pm, block) arrays, not distances — cross ICI
    via all_gather for the global argmin.

    gather='bf16'/'int8' compresses the min-distance column of that pair
    (parallel/gather.py: packed codes + per-block scales, still ONE
    all_gather); the int32 argmin column always travels exact. Champion
    comparisons then happen on the decoded values — identical on every
    shard, so sse stays replicated — and ties still resolve to the lowest
    centroid index. No error feedback: mins are per-batch data with no
    next-pass residual slot to fold into.

    shifted=True drops the row-constant ‖x‖² term from the reported min
    distances — every shard shifts a given point by the same amount, so
    cross-shard champion comparisons are unchanged. The caller adds the
    iteration-invariant Σ‖x‖² back to the summed SSE once per fit instead of
    re-reading all of x every iteration (4.3 ms/step at N=2M·d=768 on v5e).
    Tie-break caveat: the exact XLA path clamps distances at 0, which can
    collapse near-duplicate centroids' fp-noise-level distances into an
    index-order tie; the shifted form compares the unclamped values instead
    — the same semantics the Pallas `distance_argmin` kernel always had —
    so assignments may differ on such degenerate pairs (either index is a
    valid argmin).
    """
    k_per = c_loc.shape[0]
    m_idx = jax.lax.axis_index(MODEL_AXIS)
    if kernel == "pallas":
        from tdc_tpu.ops.pallas_kernels import argmin_block_k, distance_argmin

        # 1024-wide K-tiles measured 7% faster than the 512 default at the
        # K=16,384·d=768 regime (80% vs 74% MFU); VMEM-gated per dtype/d.
        blk_k = argmin_block_k(k_per, x_blk.shape[1], x_blk.dtype.itemsize)
        arg, lmin = distance_argmin(
            x_blk, c_loc, block_k=blk_k, return_dist=not shifted
        )
    else:
        # shifted=True drops the ‖x‖² term and the 0-clamp inside the shared
        # helper (same dtype/precision policy either way).
        d2 = pairwise_sq_dist(x_blk, c_loc, shifted=shifted)  # (block, K/Pm)
        lmin = jnp.min(d2, axis=1)
        arg = jnp.argmin(d2, axis=1).astype(jnp.int32)
    larg = arg + m_idx * k_per
    mins, _ = gather_lib.compressed_all_gather(
        lmin, MODEL_AXIS, gather
    )  # (Pm, block)
    args = jax.lax.all_gather(larg, MODEL_AXIS)  # (Pm, block)
    # Champion selection as pure reductions: per-column take_along_axis
    # gathers on (Pm, N) measured 3.75 ms each at N=524k (scalar-gather
    # layout); min + masked-min is VPU-cheap and deterministic (distance
    # ties across shards resolve to the lowest centroid index).
    gmin = jnp.min(mins, axis=0)
    garg = jnp.min(jnp.where(mins == gmin[None, :], args, 2**30), axis=0)
    return gmin, garg


def _block_stats(x_blk, c_loc, kernel: str, shifted: bool = False,
                 gather: str = "fp32"):
    """(sums (K/Pm, d), counts (K/Pm,), sse ()) for one N-block — local to
    this (data, model) shard pair; data-psum'd by the caller.

    Stats for MY K-shard only, via the sort-based segment sum
    (ops/sorted_stats): out-of-shard assignments map to the sentinel label
    K/Pm and drop out. The round-3 dense one-hot contraction here cost a
    full second distance pass (2·K·d MXU FLOPs per point at HIGHEST
    precision) plus an HBM-materialized (block, K/Pm) one-hot — it capped
    the K=16,384 regime at ~40% of the distance-only roofline
    (benchmarks/ROOFLINE_SHARDED.md)."""
    from tdc_tpu.ops.sorted_stats import sorted_cluster_stats

    k_per = c_loc.shape[0]
    m_idx = jax.lax.axis_index(MODEL_AXIS)
    gmin, garg = _block_champions(x_blk, c_loc, kernel, shifted, gather)
    rel = garg - m_idx * k_per
    # On the pallas route the windowed-accumulate runs as a Pallas kernel
    # too (accumulator tiles stay VMEM-resident instead of DUS round-trips
    # — benchmarks/ROOFLINE_SHARDED.md round-4 update).
    sums, counts = sorted_cluster_stats(
        x_blk, rel, k_per, pallas=(kernel == "pallas")
    )
    return sums, counts, jnp.sum(gmin)


def make_sharded_stats(
    mesh: Mesh, kernel: str = "xla", block_rows: int = 0,
    shifted: bool = False, reduce_data: bool = True,
    assign_spec=None, gather: str = "fp32",
):
    """Returns a jit-able fn(x, c) → (sums, counts, sse): x sharded (data,),
    c sharded (model,); sums/counts stay K-sharded, sse replicated.

    assign_spec (ops/subk.CoarseSpec, coarse mode) swaps the all-K
    champion pass for the coarse→refine tile-pruned assignment: each model
    shard clusters its OWN K/Pm local centroids into tiles and refines
    only the top-`probe` tiles per point block (the plan build is
    shard-local — zero collectives — and the champion all_gather is
    unchanged, so the collective schedule is assignment-mode-independent).
    The returned fn then takes (x, c, n_valid): zero-padding rows are
    masked INSIDE (sentinel champions, zero sse on every shard), so
    callers must skip the exact path's padding_correction.

    block_rows > 0 scans the local points in (block_rows, d) tiles so the
    per-shard intermediates never exceed O(block_rows · K/Pm) regardless of N
    (requires the local shard size to be a block_rows multiple — pad upstream
    with zero rows and correct via `padding_correction`).

    shifted=True returns sse WITHOUT the Σ‖x‖² term (see _block_champions);
    the caller must add it back.

    reduce_data=False defers the data-axis psum (parallel/reduce per-pass
    strategy): the outputs keep a leading data-shard axis — sums
    (n_data, K, d), counts (n_data, K), sse (n_data,) — and stay UNREDUCED
    over the data axis so a streamed driver can accumulate batches
    shard-locally and issue `make_sharded_deferred_reduce` once per pass.
    The champion all_gather over the model axis still runs per batch (it is
    N-proportional assignment traffic and cannot be deferred).

    gather='bf16'/'int8' compresses the champion min column's model-axis
    all_gather (parallel/gather.py); 'fp32'/'fp32_sharded' keep the exact
    fp32 pair (the finalize-side difference between those two lives in
    make_sharded_finalize, not here). The collective count/order is
    mode-independent — only operand dtypes change (tdcverify pins this
    via same_schedule_as).
    """
    out_specs = (
        (P(MODEL_AXIS, None), P(MODEL_AXIS), P()) if reduce_data
        else (P(DATA_AXIS, MODEL_AXIS, None), P(DATA_AXIS, MODEL_AXIS),
              P(DATA_AXIS))
    )

    if assign_spec is not None and assign_spec.coarse:
        from tdc_tpu.ops import subk as subk_lib

        aspec = assign_spec

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None), P()),
            out_specs=out_specs,
            check_vma=False,
        )
        def stats_coarse(x_loc, c_loc, n_valid):
            from tdc_tpu.ops.sorted_stats import sorted_cluster_stats

            n_loc = x_loc.shape[0]
            k_per = c_loc.shape[0]
            m_idx = jax.lax.axis_index(MODEL_AXIS)
            d_idx = jax.lax.axis_index(DATA_AXIS)
            # Zero-padding rows sit at the END of the global batch, so
            # each data shard's valid prefix is a clipped remainder.
            nv_loc = jnp.clip(n_valid - d_idx * n_loc, 0, n_loc)
            # The per-shard plan rebuilds per stats call (= per batch on
            # the streamed drivers): hoisting it per pass would thread
            # model-sharded plan operands through every accumulate
            # signature. O(K/Pm·(T + log K)·d) vs the refine's
            # O(rows·(T + probe·S)·d) — amortized by the large batches
            # the huge-K regime runs anyway (ARCHITECTURE §"Sub-linear
            # assignment"; the 1-D driver hoists via subk.plan_for).
            plan = subk_lib.build_plan(c_loc, aspec)
            labels, lmin = subk_lib.coarse_champions(
                x_loc, plan, nv_loc, aspec
            )
            # Local → global champion ids; pad rows stay sentinel on every
            # shard and report min 0.0, so the cross-shard reduction keeps
            # them sentinel/zero (no padding correction anywhere).
            larg = jnp.where(labels < subk_lib.ARG_SENTINEL,
                             labels + m_idx * k_per, subk_lib.ARG_SENTINEL)
            # Pad rows report min 0.0 on every shard, and 0.0 survives the
            # quantized gather exactly (code 0 decodes to 0.0 under any
            # positive scale — parallel/gather.py), so the sentinel/zero
            # masking below is gather-mode-independent.
            mins, _ = gather_lib.compressed_all_gather(
                lmin, MODEL_AXIS, gather
            )  # (Pm, n_loc)
            args = jax.lax.all_gather(larg, MODEL_AXIS)
            gmin = jnp.min(mins, axis=0)
            garg = jnp.min(
                jnp.where(mins == gmin[None, :], args, 2**30), axis=0
            )
            rel = garg - m_idx * k_per  # sentinel stays >= k_per → dropped
            sums, counts = sorted_cluster_stats(
                x_loc, rel, k_per, pallas=(kernel == "pallas")
            )
            valid = jnp.arange(n_loc) < nv_loc
            if shifted:
                sse = jnp.sum(jnp.where(valid, gmin, 0.0))
            else:
                xf = x_loc.astype(jnp.float32)
                x2 = jnp.sum(xf * xf, axis=1)
                sse = jnp.sum(
                    jnp.where(valid, jnp.maximum(gmin + x2, 0.0), 0.0)
                )
            if not reduce_data:
                return sums[None], counts[None], sse[None]
            return (
                jax.lax.psum(sums, DATA_AXIS),
                jax.lax.psum(counts, DATA_AXIS),
                jax.lax.psum(sse, DATA_AXIS),
            )

        return stats_coarse

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=out_specs,
        check_vma=False,
    )
    def stats(x_loc, c_loc):
        n_loc, d = x_loc.shape
        k_per = c_loc.shape[0]
        # The N-block scan exists to bound the XLA path's (block, K/Pm)
        # distance intermediates. The pallas path has none — its only
        # N-sized arrays are the (N,) champion columns — and profiling showed
        # the per-block sorts inside the scan cost ~25 ms/step at N=524k
        # (8 sorts of 64k vs one of 512k): one-shot is strictly better.
        if block_rows and n_loc > block_rows and kernel != "pallas":
            if n_loc % block_rows != 0:
                raise ValueError(
                    f"local shard rows {n_loc} not divisible by "
                    f"block_rows={block_rows}"
                )
            xb = x_loc.reshape(n_loc // block_rows, block_rows, d)

            def body(acc, blk):
                s, ct, e = _block_stats(blk, c_loc, kernel, shifted, gather)
                return (acc[0] + s, acc[1] + ct, acc[2] + e), None

            zero = (
                jnp.zeros((k_per, d), jnp.float32),
                jnp.zeros((k_per,), jnp.float32),
                jnp.zeros((), jnp.float32),
            )
            (sums, counts, sse), _ = jax.lax.scan(body, zero, xb)
        else:
            sums, counts, sse = _block_stats(x_loc, c_loc, kernel, shifted,
                                             gather)
        if not reduce_data:
            # Deferred mode: keep the data-shard partials local (leading
            # device axis); the sse is identical on every model shard (the
            # champions are globally reduced), so its unmentioned model
            # axis takes any copy.
            return sums[None], counts[None], sse[None]
        # Reduce over the data axis only; K stays sharded. The champions are
        # identical on every model shard, so sse comes out replicated.
        sums = jax.lax.psum(sums, DATA_AXIS)
        counts = jax.lax.psum(counts, DATA_AXIS)
        sse = jax.lax.psum(sse, DATA_AXIS)
        return sums, counts, sse

    return stats


class ShardedBoundsState(NamedTuple):
    """Per-shard Hamerly bounds for the K-sharded towers: each (data,
    model) shard pair keeps, for every local point row, the champion
    index WITHIN ITS OWN K/Pm centroid slice plus a lower bound on the
    local runner-up distance (no upper-bound leaf — the tower always
    tightens, see ops/bounds.BoundsState). Everything — the per-centroid
    drift, the bound update, the skip test, the packed re-scan — is
    shard-local, so bounded assignment adds ZERO collectives: each shard
    reports its (possibly bound-certified) local champion into the very
    same two all_gathers the exact tower issues.

    lab/lb are (rows, Pm) sharded P(data, model) — one column per model
    shard; ev is the (n_data·n_model,) per-shard distance-eval tally
    (P((data, model)) — stacked locals, no reduce)."""

    prev_c: jax.Array  # (K, d) f32, model-sharded
    lab: jax.Array  # (rows, Pm) int32
    lb: jax.Array  # (rows, Pm) f32 — lower bound on local runner-up
    ev: jax.Array  # (n_data*n_model,) f32 — evals performed per shard


def init_sharded_bounds(mesh: Mesh, rows: int, c) -> ShardedBoundsState:
    """−inf bounds (first pass = full local re-scan on every shard, i.e.
    one exact iteration that doubles as initialization). prev_c is an
    explicit copy — the resident chunk donates the carry alongside the
    centroids, and an aliased buffer would be donated twice."""
    import numpy as _np

    n_data = int(mesh.devices.shape[0])
    n_model = int(mesh.devices.shape[1])
    put = lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec))
    return ShardedBoundsState(
        prev_c=put(_np.asarray(c, _np.float32), P(MODEL_AXIS, None)),
        lab=put(_np.zeros((rows, n_model), _np.int32),
                P(DATA_AXIS, MODEL_AXIS)),
        lb=put(_np.full((rows, n_model), -_np.inf, _np.float32),
               P(DATA_AXIS, MODEL_AXIS)),
        ev=put(_np.zeros((n_data * n_model,), _np.float32),
               P((DATA_AXIS, MODEL_AXIS))),
    )


class ShardedResidentBounds(NamedTuple):
    """The K-sharded resident chunk's bounds aux carry: per-batch
    ShardedBoundsState slices aligned with the DeviceCache geometry
    (stacked full batches + tail), donated alongside the centroids."""

    prev_c: jax.Array  # (K, d) f32, model-sharded
    lab_s: jax.Array | None  # (n_full, B, Pm) int32
    lb_s: jax.Array | None
    lab_t: jax.Array  # (B_tail, Pm)
    lb_t: jax.Array
    ev: jax.Array  # (n_data*n_model,) f32


def init_resident_sharded_bounds(mesh: Mesh, cache, c) -> ShardedResidentBounds:
    """±inf per-batch bounds for a filled DeviceCache (the sharded analog
    of ops/bounds.init_state; prev_c copied for the donation contract)."""
    import numpy as _np

    n_data = int(mesh.devices.shape[0])
    n_model = int(mesh.devices.shape[1])
    put = lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec))

    def duo(shape):
        return (
            put(_np.zeros(shape + (n_model,), _np.int32),
                P(*((None,) * (len(shape) - 1)), DATA_AXIS, MODEL_AXIS)),
            put(_np.full(shape + (n_model,), -_np.inf, _np.float32),
                P(*((None,) * (len(shape) - 1)), DATA_AXIS, MODEL_AXIS)),
        )

    if cache.stacked is not None:
        lab_s, lb_s = duo(tuple(cache.stacked.shape[:2]))
    else:
        lab_s = lb_s = None
    lab_t, lb_t = duo((cache.tail.shape[0],))
    return ShardedResidentBounds(
        prev_c=put(_np.asarray(c, _np.float32), P(MODEL_AXIS, None)),
        lab_s=lab_s, lb_s=lb_s,
        lab_t=lab_t, lb_t=lb_t,
        ev=put(_np.zeros((n_data * n_model,), _np.float32),
               P((DATA_AXIS, MODEL_AXIS))),
    )


def make_sharded_bounded_stats(mesh: Mesh, block_rows_pack: int = 512):
    """The bounded (zero-loss) counterpart of make_sharded_stats: jit-able
    fn(x, c, prev_c, lab, lb) → (sums, counts, sse, lab', lb', evals)
    with the EXACT tower's collective schedule — the per-shard bound
    maintenance prunes only local FLOPs (rows whose local champion is
    bound-certified skip the (rows, K/Pm) scan via the packed-block
    `lax.cond`), and the champion all_gathers + data-axis stat psums run
    identically (the PR-13 `same_schedule_as` invariant pins this).

    Zero-padding rows are ordinary zero points (the exact tower's rule);
    callers apply the same padding_correction. SSE is the full (clamped,
    ‖x‖²-included) form — bounded fits don't use the x2sum shift."""
    from tdc_tpu.ops.bounds import _second_min
    from tdc_tpu.ops.pallas_kernels import champion_tile
    from tdc_tpu.ops.sorted_stats import sorted_cluster_stats

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None),
                  P(MODEL_AXIS, None), P(DATA_AXIS, MODEL_AXIS),
                  P(DATA_AXIS, MODEL_AXIS)),
        out_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS), P(),
                   P(DATA_AXIS, MODEL_AXIS), P(DATA_AXIS, MODEL_AXIS),
                   P((DATA_AXIS, MODEL_AXIS))),
        check_vma=False,
    )
    def stats(x_loc, c_loc, prev_loc, lab, lb):
        n_loc, d = x_loc.shape
        k_per = c_loc.shape[0]
        m_idx = jax.lax.axis_index(MODEL_AXIS)
        lab, lb = lab[:, 0], lb[:, 0]
        cf = c_loc.astype(jnp.float32)
        # Shard-LOCAL drift: this shard's centroids moved by delta; the
        # local bounds only ever referenced local centroids, so no
        # cross-shard drift exchange is needed (the collective-free
        # property the schedule golden pins). The tighten below
        # re-establishes the upper bound exactly, so only the lower
        # bound drifts (ops/bounds.BoundsState's no-upper-leaf rule).
        delta = jnp.linalg.norm(cf - prev_loc.astype(jnp.float32), axis=1)
        dmax = jnp.max(delta)
        xf = x_loc.astype(jnp.float32)
        x2 = jnp.sum(xf * xf, axis=1)
        lb = lb - dmax
        ca = cf[lab]
        d2a = jnp.maximum(
            x2 + jnp.sum(ca * ca, axis=1) - 2.0 * jnp.sum(xf * ca, axis=1),
            0.0,
        )
        ta = jnp.sqrt(d2a)
        need = jnp.logical_not(ta < lb)
        block = min(block_rows_pack, max(n_loc, 1))
        order = jnp.argsort(
            jnp.logical_not(need).astype(jnp.int32)
        ).astype(jnp.int32)
        pad = (-n_loc) % block
        if pad:
            order = jnp.concatenate([order, jnp.zeros((pad,), jnp.int32)])
        npad = n_loc + pad
        real = jnp.arange(npad) < n_loc
        needs = jnp.where(real, need[order], False)
        nb = npad // block

        def one_block(args):
            xs_b, lab_b, d2a_b, lb_b, need_b = args

            def rescan(_):
                d2 = pairwise_sq_dist(xs_b, cf)
                tmin, targ = champion_tile(d2)
                d1 = tmin[:, 0]
                return (targ[:, 0], d1,
                        jnp.sqrt(jnp.maximum(_second_min(d2, targ), 0.0)),
                        jnp.full((), float(block * k_per), jnp.float32))

            def skip(_):
                return (lab_b, d2a_b, lb_b,
                        jnp.zeros((), jnp.float32))

            return jax.lax.cond(jnp.any(need_b), rescan, skip, None)

        lab2, champ, lb2, ev_b = jax.lax.map(
            one_block,
            (xf[order].reshape(nb, block, d),
             lab[order].reshape(nb, block),
             d2a[order].reshape(nb, block),
             lb[order].reshape(nb, block),
             needs.reshape(nb, block)),
        )

        def unsort(v, fill):
            dest = jnp.where(real, order, n_loc)
            out = jnp.full((n_loc + 1,), fill, v.dtype)
            return out.at[dest].set(v.reshape(-1))[:n_loc]

        lab_n = unsort(lab2, 0)
        lmin = unsort(champ, 0.0)
        lb_n = unsort(lb2, 0.0)
        evals = jnp.sum(ev_b) + float(n_loc)  # + the tighten pass
        # From here the EXACT tower, op for op: global champion fold over
        # the model axis, shard-local stats, data-axis psums.
        larg = lab_n + m_idx * k_per
        mins = jax.lax.all_gather(lmin, MODEL_AXIS)  # (Pm, n_loc)
        args = jax.lax.all_gather(larg, MODEL_AXIS)
        gmin = jnp.min(mins, axis=0)
        garg = jnp.min(jnp.where(mins == gmin[None, :], args, 2**30),
                       axis=0)
        rel = garg - m_idx * k_per
        sums, counts = sorted_cluster_stats(x_loc, rel, k_per)
        sse = jnp.sum(gmin)
        return (
            jax.lax.psum(sums, DATA_AXIS),
            jax.lax.psum(counts, DATA_AXIS),
            jax.lax.psum(sse, DATA_AXIS),
            lab_n[:, None],
            lb_n[:, None],
            (evals)[None],
        )

    return stats


def make_sharded_deferred_reduce(mesh: Mesh):
    """The per-pass counterpart of make_sharded_stats(reduce_data=False):
    ONE data-axis psum of the deferred (n_data-leading) accumulator —
    returns jit-able fn(sums, counts, sse) → K-sharded reduced stats
    (sums (K, d) / counts (K,) model-sharded, sse replicated)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS, None), P(DATA_AXIS, MODEL_AXIS),
                  P(DATA_AXIS)),
        out_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS), P()),
        check_vma=False,
    )
    def red(sums, counts, sse):
        return (
            jax.lax.psum(sums[0], DATA_AXIS),
            jax.lax.psum(counts[0], DATA_AXIS),
            jax.lax.psum(sse[0], DATA_AXIS),
        )

    return red


def make_sharded_deferred_accumulate(stats_fn, acc_cls, coarse: bool = False):
    """The donated per-batch add of the K-sharded per-pass paths: one
    jitted fn(acc, x, c[, n_valid]) adding `stats_fn`'s shard-local
    partials into the deferred accumulator (an `acc_cls` NamedTuple of
    leading-data-axis leaves), with the accumulator DONATED so XLA
    updates the n_data×-larger buffer in place instead of keeping two
    generations live per batch (reduce.make_deferred_fns' rationale).

    Module-level (rather than a driver closure) so tdcverify's donation
    audit can lower the exact artifact the streamed drivers dispatch —
    the donate_argnums contract here is CI-verified against the compiled
    StableHLO (docs/VERIFICATION.md), not just declared. `coarse` adds
    the n_valid operand the tile-pruned stats mask padding with."""
    if coarse:

        @partial(jax.jit, donate_argnums=(0,))
        def accumulate(acc, x, c, n_valid):
            parts = stats_fn(x, c, n_valid)
            return acc_cls(*(a + p for a, p in zip(acc, parts)))

    else:

        @partial(jax.jit, donate_argnums=(0,))
        def accumulate(acc, x, c):
            parts = stats_fn(x, c)
            return acc_cls(*(a + p for a, p in zip(acc, parts)))

    return accumulate


@jax.jit
def sum_sq(x) -> jax.Array:
    """Σ‖x‖² as an f32 scalar — the iteration-invariant SSE term, computed
    once per fit and passed to the sharded step as `x2sum` (auto-sharded
    reduce; zero-padding rows contribute zero)."""
    return jnp.sum(jnp.square(x.astype(jnp.float32)))


def padding_correction(counts, sse, centroids, n_pad):
    """Remove the exact contribution of `n_pad` zero-padding rows: each lands
    on the global argmin-‖c‖² cluster with zero Σx, one count, ‖c_j‖² sse
    (same correction as models/streaming and the fused Pallas kernel)."""
    c2 = jnp.sum(centroids.astype(jnp.float32) ** 2, axis=-1)
    j = jnp.argmin(c2)
    n_pad = jnp.asarray(n_pad, jnp.float32)
    return counts.at[j].add(-n_pad), sse - n_pad * c2[j]


def zero_finalize_err(mesh: Mesh, k: int, d: int):
    """Fresh error-feedback state for the sharded finalize's quantized
    slice gather: ONE persistent residual slot per gathered leaf, in the
    deferred (n_data, K, d) leading-slot layout — slot i carries the
    residual of slice i's rows, zeros elsewhere, so Σ_slots is the full
    (K, d) residual map and `reshard.redistribute_gather_err` can fold
    it across a mesh resize (Σ-preserving, like the deferred stats
    accumulators). Device-placed sharding-first, like zero_deferred."""
    n_data = mesh.devices.shape[0]
    sharding = NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None))
    return jax.jit(
        lambda: jnp.zeros((n_data, k, d), jnp.float32),
        out_shardings=sharding,
    )()


def make_sharded_finalize(
    mesh: Mesh,
    *,
    spherical: bool = False,
    mode: str = "fp32_sharded",
    fuzzy: bool = False,
):
    """Data-axis-sharded centroid finalize (ROADMAP item 3: the
    cross-replica weight-update sharding pattern of arXiv 2004.13336
    applied to the Lloyd divide/renormalize).

    The replicated finalize computes the full (K/Pm, d) divide on every
    data replica — n_data× redundant FLOPs and, once compressed gathers
    exist, the only place left where centroids cross the wire fp32. Here
    each (data, model) shard divides only its 1/n_data slice of the local
    K/Pm rows and the slices cross the data axis in one all_gather
    (compressed under mode='bf16'/'int8', with a persistent per-leaf
    error-feedback residual in the zero_finalize_err layout).

    mode='fp32_sharded' is bit-exact vs the replicated finalize: the
    slice rows run the identical elementwise ops, and the gather moves
    exact f32. Signatures:

      fp32_sharded:  fn(sums, counts, c)      -> (new_c, shift)
      bf16 / int8:   fn(sums, counts, c, err) -> (new_c, shift, new_err)

    fuzzy=True divides by max(weights, 1e-12) with no empty-cluster
    fallback (the streamed fuzzy driver's update). Requires
    (K/Pm) % n_data == 0 — validated by the drivers' gather plan.
    """
    n_data = mesh.devices.shape[0]
    quantized = mode in ("bf16", "int8")
    err_specs = (P(DATA_AXIS, MODEL_AXIS, None),) if quantized else ()
    in_specs = (
        P(MODEL_AXIS, None), P(MODEL_AXIS), P(MODEL_AXIS, None)
    ) + err_specs
    out_specs = (P(MODEL_AXIS, None), P()) + err_specs

    @partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
             check_vma=False)
    def finalize(sums_loc, counts_loc, c_loc, *err_loc):
        k_per, d = sums_loc.shape
        if k_per % n_data:
            raise ValueError(
                f"sharded finalize needs K/Pm={k_per} divisible by the "
                f"data axis ({n_data})"
            )
        rows = k_per // n_data
        start = jax.lax.axis_index(DATA_AXIS) * rows
        s = jax.lax.dynamic_slice_in_dim(sums_loc, start, rows)
        w = jax.lax.dynamic_slice_in_dim(counts_loc, start, rows)
        cf = jax.lax.dynamic_slice_in_dim(
            c_loc, start, rows
        ).astype(jnp.float32)
        if fuzzy:
            new_slice = s / jnp.maximum(w[:, None], 1e-12)
        else:
            new_slice = jnp.where(
                w[:, None] > 0, s / jnp.maximum(w[:, None], 1.0), cf
            )
        if spherical:
            new_slice = _normalize(new_slice)
        # Max centroid shift: per-slice max, then one 4-byte pmax over
        # both axes (the replicated finalize got the cross-model max for
        # free from XLA's auto-sharded reduce).
        shift = jax.lax.pmax(
            jnp.max(jnp.linalg.norm(new_slice - cf, axis=-1)),
            (DATA_AXIS, MODEL_AXIS),
        )
        if quantized:
            e = jax.lax.dynamic_slice(
                err_loc[0], (0, start, 0), (1, rows, d)
            )[0]
            # Delta coding: quantize the iteration's centroid SHIFT, not
            # the centroid values. The reference c is replicated across
            # the data axis, so every shard reconstructs identically with
            # one local add — and the codec's scale tracks the shift
            # magnitude (→ 0 near convergence) instead of the centroid
            # magnitude, which keeps the decode error proportional to the
            # update instead of the data scale. A zero shift (empty
            # cluster keeping cf) encodes to code 0 and decodes to
            # exactly cf.
            g, new_e = gather_lib.compressed_all_gather(
                new_slice - cf, DATA_AXIS, mode, err=e
            )
            new_err = jax.lax.dynamic_update_slice(
                jnp.zeros_like(err_loc[0]), new_e[None], (0, start, 0)
            )
            new_c = g.reshape(k_per, d) + c_loc.astype(jnp.float32)
            return new_c, shift, new_err
        g, _ = gather_lib.compressed_all_gather(new_slice, DATA_AXIS, mode)
        return g.reshape(k_per, d), shift

    return finalize


def plan_gather(gather, mesh: Mesh, k: int, *, assign: str = "exact",
                ckpt_dir=None, ckpt_every_batches: int = 0,
                residency: str = "stream"):
    """Shared validation for the K-sharded drivers' `gather=` knob — the
    gather twin of streaming._reduce_plan, and the ONE copy of the
    guard-rail rules. Returns the resolved GatherStrategy.

    Quantized gathers refuse loudly wherever the error-feedback state
    cannot persist: checkpointed fits (a resume would restart the
    residual — the same bit-identical-resume contract as the quantized
    reduce, and mid-pass ckpt_every_batches saves have no residual slot
    at all), hbm/auto residency (the compiled resident chunk traces the
    centroid update once; the host-held residual cannot ride it), and
    single-device meshes (the gathers are no-ops — there is nothing to
    quantize). assign='bounded' is a bit-exact contract: quantized
    champion mins would invalidate the triangle-inequality certificates.
    """
    strategy = gather_lib.resolve_gather(gather)
    if strategy.mode == "fp32":
        return strategy
    n_data, n_model = mesh.devices.shape
    if (k // n_model) % n_data:
        raise ValueError(
            f"gather={strategy.mode!r} shards the finalize over the data "
            f"axis: K/Pm={k // n_model} must be divisible by "
            f"n_data={n_data}"
        )
    if assign == "bounded":
        raise ValueError(
            "assign='bounded' runs its own tower with the replicated "
            "finalize (a zero-loss contract quantized champion gathers "
            "would invalidate); use gather='fp32'"
        )
    if not strategy.quantized:
        return strategy
    if n_data * n_model <= 1:
        raise ValueError(
            "quantized gather requires a multi-device mesh (on one "
            "device the champion/finalize gathers are no-ops — there is "
            "no cross-device gather to quantize)"
        )
    if ckpt_dir is not None:
        raise ValueError(
            "quantized gather does not support ckpt_dir: a resume would "
            "restart the finalize error-feedback residual, breaking the "
            "bit-identical-resume contract (and mid-pass "
            "ckpt_every_batches saves carry no residual slot at all)"
        )
    if ckpt_every_batches:
        raise ValueError(
            "quantized gather does not support mid-pass checkpointing "
            "(ckpt_every_batches): the finalize error-feedback residual "
            "only exists at pass boundaries"
        )
    if residency not in (None, "stream"):
        raise ValueError(
            f"quantized gather requires residency='stream' (got "
            f"{residency!r}): the compiled resident chunk traces the "
            "centroid update once and cannot carry the gather "
            "error-feedback state across chunk iterations"
        )
    return strategy


def make_sharded_lloyd_step(
    mesh: Mesh,
    kernel: str = "xla",
    block_rows: int = 0,
    spherical: bool = False,
    assign_spec=None,
    gather: str = "fp32",
):
    """Returns a jit'd step: (x (data,)-sharded, c (model,)-sharded, n_valid)
    → (new_c (model,)-sharded, shift, sse). Zero-padding rows beyond n_valid
    are corrected exactly.

    Pass x2sum = Σ‖x‖² (a scalar, computed once per fit — `sum_sq`) to skip
    the per-iteration ‖x‖² re-read: the distance pass then reports shifted
    minima (identical argmin/ties) and the scalar is added back to the SSE.
    Zero-padding rows contribute zero to x2sum, so the same value is valid
    for any n_valid.

    SSE precision caveat (x2sum path): the reported SSE is the sum of two
    large cancelling f32 scalars (Σ shifted mins ≈ −Σ‖x‖² + SSE, plus
    x2sum). When the true SSE is orders of magnitude below Σ‖x‖² (tight
    clusters far from the origin) the result loses relative precision
    against the unshifted per-point-clamped path — assignments and centroid
    updates are unaffected (champions are shift-invariant); only the scalar
    SSE report degrades. Pre-center such data, or call the step without
    x2sum for an exact final report.

    gather != 'fp32' routes the centroid update through the data-axis-
    sharded finalize (make_sharded_finalize); for the quantized modes the
    step takes and returns the persistent gather residual:
    step(x, c, n_valid, x2sum, gerr) -> (new_c, shift, sse, new_gerr)."""
    coarse = assign_spec is not None and assign_spec.coarse
    stats_fn = make_sharded_stats(mesh, kernel, block_rows,
                                  assign_spec=assign_spec, gather=gather)
    stats_shifted = make_sharded_stats(mesh, kernel, block_rows, shifted=True,
                                       assign_spec=assign_spec, gather=gather)
    strategy = gather_lib.resolve_gather(gather)
    finalize = (
        make_sharded_finalize(mesh, spherical=spherical, mode=strategy.mode)
        if strategy.sharded_finalize else None
    )

    @jax.jit
    def step(x, c, n_valid, x2sum=None, gerr=None):
        if coarse:
            # Coarse stats mask padding internally (sentinel champions,
            # zero sse contributions) — no correction term exists.
            if x2sum is None:
                sums, counts, sse = stats_fn(x, c, n_valid)
            else:
                sums, counts, sse = stats_shifted(x, c, n_valid)
                sse = jnp.maximum(sse + x2sum, 0.0)
        elif x2sum is None:
            sums, counts, sse = stats_fn(x, c)
        else:
            sums, counts, sse = stats_shifted(x, c)
            sse = jnp.maximum(sse + x2sum, 0.0)
        if not coarse:
            n_pad = x.shape[0] - n_valid
            counts, sse = padding_correction(counts, sse, c, n_pad)
        if finalize is not None:
            if strategy.quantized:
                new_c, shift, new_gerr = finalize(sums, counts, c, gerr)
                return new_c, shift, sse, new_gerr
            new_c, shift = finalize(sums, counts, c)
            return new_c, shift, sse
        cf = c.astype(jnp.float32)
        new_c = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts[:, None], 1.0),
            cf,
        )
        if spherical:
            new_c = _normalize(new_c)
        shift = jnp.max(jnp.linalg.norm(new_c - cf, axis=-1))
        return new_c, shift, sse

    return step


def sharded_lloyd_step(mesh: Mesh):
    """Back-compat wrapper: (x, c) → (new_c, shift, sse), no padding."""
    step = make_sharded_lloyd_step(mesh)

    def run(x, c):
        return step(x, c, x.shape[0])

    return run


def sharded_assign(mesh: Mesh, kernel: str = "xla", block_rows: int = 0,
                   shifted: bool = True):
    """Jit-able global assignment under the 2-D layout: labels sharded
    (data,). Blocked the same way as the stats tower.

    shifted=True (default) skips the row-constant ‖x‖² re-read — argmin is
    invariant to it — and compares unclamped values, the same tie-break
    semantics as the x2sum step path. Pass shifted=False to match the
    unshifted clamped step exactly on degenerate near-duplicate centroids
    (either index is a valid argmin; the clamp can collapse fp-noise-level
    distances into an index-order tie)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )
    def assign(x_loc, c_loc):
        n_loc, d = x_loc.shape
        if block_rows and n_loc > block_rows and kernel != "pallas":
            if n_loc % block_rows != 0:
                raise ValueError(
                    f"local shard rows {n_loc} not divisible by "
                    f"block_rows={block_rows}"
                )
            xb = x_loc.reshape(n_loc // block_rows, block_rows, d)
            _, garg = jax.lax.scan(
                lambda _, blk: (
                    None, _block_champions(blk, c_loc, kernel, shifted)[1],
                ),
                None,
                xb,
            )
            return garg.reshape(-1)
        return _block_champions(x_loc, c_loc, kernel, shifted)[1]

    return assign


def _device_loop(step, c0, max_iters: int, tol: float):
    """Run `step(c) -> (new_c, shift, cost)` to convergence entirely
    device-side: a lax.while_loop with the tol test in the carry and the
    per-iteration (cost, shift) pairs stacked into a device history array.
    ONE dispatch and ~one host sync per fit instead of a device round trip
    per iteration — the Python iterate-and-float() loop this replaces
    measured ~10× the iteration's compute in per-iter latency on remote
    links (round-4 streamed-driver fix, RESULTS.md).

    Returns (c, shift, n_iter, hist) as device arrays; hist rows at index
    ≥ n_iter are zero. tol < 0 = fixed-iteration mode (no early exit),
    decided at trace time."""

    def cond(carry):
        _, shift, i, _ = carry
        live = i < max_iters
        if tol >= 0:
            live = jnp.logical_and(live, shift > tol)
        return live

    def body(carry):
        c, _, i, hist = carry
        new_c, shift, cost = step(c)
        hist = hist.at[i].set(jnp.stack([cost, shift]))
        return new_c, shift, i + 1, hist

    carry0 = (
        c0,
        jnp.asarray(jnp.inf, jnp.float32),
        jnp.asarray(0, jnp.int32),
        jnp.zeros((max_iters, 2), jnp.float32),
    )
    return jax.lax.while_loop(cond, body, carry0)


def _resolve_init_sharded(x, k: int, init, key, *, sample_rows: int = 65536):
    """Init for the K-sharded fit. Arrays pass through; names resolve on a
    deterministic host-side subsample (the seeding problem is tiny next to
    the fit — k-means++ on ≤64k rows — and must not require the full dataset
    on one device)."""
    if hasattr(init, "shape"):
        c = jnp.asarray(init, jnp.float32)
        if c.shape[0] != k:
            raise ValueError(f"init has {c.shape[0]} rows, expected {k}")
        return c
    sample = jnp.asarray(np.asarray(x[: min(x.shape[0], sample_rows)]))
    return resolve_init(sample, k, init, key)


def kmeans_fit_sharded(
    x,
    k: int,
    mesh: Mesh,
    *,
    init,
    key=None,
    max_iters: int = 20,
    tol: float = 1e-4,
    spherical: bool = False,
    kernel: str = "xla",
    block_rows: int = 0,
    assign: str = "exact",
    probe=None,
    gather: str = "fp32",
) -> KMeansResult:
    """Lloyd K-Means with points sharded over 'data' and centroids over
    'model' (the K=16,384 regime). init may be a (K, d) array or an init name
    ('kmeans++'/'random'/'first_k'/'kmeans||'), resolved on a host subsample.

    gather: 'fp32' (default — byte-identical to the pre-gather schedules) |
    'fp32_sharded' (data-axis-sharded finalize, bit-exact, n_data× fewer
    replicated finalize FLOPs) | 'bf16' | 'int8' (compressed champion +
    finalize gathers with persistent error feedback riding the fit loop's
    carry). See parallel/gather.py / make_sharded_finalize.

    assign="coarse"/"auto" + probe: sub-linear coarse→refine tile-pruned
    assignment per model shard (ops/subk.py; streamed_kmeans_fit_sharded's
    contract — bounded-loss, probe='all' routes to the exact path;
    kernel='auto' resolves via ops/pallas_kernels.resolve_kernel).

    assign="bounded": ZERO-LOSS sub-linear assignment — per-shard Hamerly
    triangle-inequality bounds (make_sharded_bounded_stats) ride the
    compiled fit loop's carry, each model shard bound-certifying or
    re-scanning its own K/Pm slice locally, so centroids/assignments are
    IDENTICAL to assign="exact" while pruned shards skip their local
    distance scans. Adds no collectives (the PR-13 schedule golden pins
    bounded ≡ exact); refuses spherical/kernel='pallas'/block_rows
    combos loudly. The result's `bounds` field carries the BoundsReport.

    Multi-process meshes (SURVEY §7 step 7: sharded centroid tiles at pod
    scale) are supported by passing `x` as the full NUMPY array, identical on
    every process: numpy stays host-side until the global device_put, which
    places only this process's addressable shards. (A jnp input would commit
    to one local device first and cannot be resharded across processes.)
    """
    n_data = mesh.devices.shape[0]
    n_model = mesh.devices.shape[1]
    if not isinstance(x, np.ndarray):
        x = jnp.asarray(x)
    if x.shape[0] % n_data != 0:
        raise ValueError(f"N={x.shape[0]} not divisible by data axis {n_data}")
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    gstrategy = plan_gather(gather, mesh, k, assign=assign)
    if spherical:
        if isinstance(x, np.ndarray):
            norms = np.linalg.norm(x, axis=-1, keepdims=True)
            x = (x / np.maximum(norms, 1e-12)).astype(np.float32)
        else:
            x = _normalize(x.astype(jnp.float32))
    c = _resolve_init_sharded(x, k, init, key)
    if spherical:
        c = _normalize(c)
    x = jax.device_put(x, NamedSharding(mesh, P(DATA_AXIS, None)))
    c = jax.device_put(c, NamedSharding(mesh, P(MODEL_AXIS, None)))
    # Whole fit loop device-side (round-4 VERDICT weak #2: the Python
    # iterate-and-float() loop here cost one device round trip per
    # iteration). Host syncs per fit: the loop-result fetch + the final SSE.
    from tdc_tpu.ops import bounds as bounds_lib
    from tdc_tpu.ops import subk as subk_lib
    from tdc_tpu.ops.pallas_kernels import resolve_kernel

    bounded = assign == "bounded"
    if bounded:
        if probe is not None:
            raise ValueError(
                "probe= only applies to assign='coarse'/'auto' (bounded "
                "assignment is exact)"
            )
        if spherical:
            raise ValueError(
                "assign='bounded' does not support spherical=True; use "
                "assign='exact'"
            )
        if kernel == "pallas":
            raise ValueError(
                "assign='bounded' runs its own masked-recompute tower and "
                "cannot combine with kernel='pallas'"
            )
        if MeshSpec.of(mesh).n_processes > 1:
            raise ValueError(
                "assign='bounded' on the K-sharded drivers is single-"
                "process only (the bounds init and eval-tally fetches "
                "read sharded state host-side); use assign='exact'"
            )
        aspec = subk_lib.EXACT
        bounds_lib.resolve_bounds("hamerly", k, label="kmeans_fit_sharded")
    else:
        kernel = resolve_kernel(kernel, k=k // n_model, d=x.shape[1],
                                model="kmeans_sharded",
                                label="kmeans_fit_sharded")
        aspec = subk_lib.resolve_assign(assign, k // n_model, probe=probe,
                                        label="kmeans_fit_sharded")
    bounds_report = None
    if bounded:
        brun, _ = _lloyd_fit_fns_bounded(mesh, spherical, int(max_iters),
                                         float(tol))
        # The final-report step stays the EXACT tower: identical reported
        # SSE, and the bounds carry must not drift during reporting.
        _, step = _lloyd_fit_fns(mesh, "xla", block_rows, spherical,
                                 int(max_iters), float(tol), subk_lib.EXACT)
        state0 = init_sharded_bounds(mesh, x.shape[0], c)
        c, shift_dev, i_dev, hist, bstate = brun(x, c, state0)
        n_iter = int(i_dev)
        shift = float(shift_dev)
        converged = tol >= 0 and shift <= tol
        _, _, sse = step(x, c, x.shape[0], sum_sq(x))
        counter = bounds_lib.BoundsCounter(_mirror=bounds_lib.GLOBAL_BOUNDS)
        # ev sums actual per-shard evals; the K/Pm shards partition K, so
        # the exact-path total is rows × K per iteration.
        counter.add(float(np.asarray(bstate.ev).sum()),
                    float(x.shape[0]) * float(k) * n_iter)
        bounds_report = bounds_lib.report(
            bounds_lib.BoundsSpec(kind="hamerly"), counter
        )
        return KMeansResult(
            centroids=c,
            n_iter=jnp.asarray(n_iter, jnp.int32),
            sse=jnp.asarray(float(sse), jnp.float32),
            shift=jnp.asarray(shift, jnp.float32),
            converged=jnp.asarray(converged),
            history=np.asarray(hist)[:n_iter],
            bounds=bounds_report,
        )
    run, step = _lloyd_fit_fns(mesh, kernel, block_rows, spherical,
                               int(max_iters), float(tol), aspec,
                               gstrategy.mode)
    x2sum = sum_sq(x)  # once per fit; the step then skips the ‖x‖² re-read
    if gstrategy.quantized:
        gerr0 = zero_finalize_err(mesh, k, x.shape[1])
        c, shift_dev, i_dev, hist, _ = run(x, c, x2sum, gerr0)
        # The final-report step stays the EXACT (fp32-gather) tower, the
        # bounded path's precedent: the reported SSE measures the returned
        # centroids with exact champion mins, so rel-inertia comparisons
        # against fp32 fits are apples-to-apples.
        _, step = _lloyd_fit_fns(mesh, kernel, block_rows, spherical,
                                 int(max_iters), float(tol), aspec)
    else:
        c, shift_dev, i_dev, hist = run(x, c, x2sum)
    n_iter = int(i_dev)
    shift = float(shift_dev)
    converged = tol >= 0 and shift <= tol
    # One extra step so the reported SSE matches the *returned* centroids
    # (every other fit path does the same; the in-loop SSE is measured
    # against the pre-update centroids). step's SSE is computed against its
    # INPUT centroids, so re-invoking the already-compiled step and
    # discarding its update gives exactly that with no extra compile.
    _, _, sse = step(x, c, x.shape[0], x2sum)
    assign_report = None
    if aspec.coarse:
        # The whole fit ran inside the compiled while_loop: book the
        # (deterministic, geometry-only) tile tallies after the fact —
        # n_iter loop passes plus the final reporting step, each refining
        # every (data, model) shard pair's blocks against its own tiles.
        counter = subk_lib.AssignCounter(_mirror=subk_lib.GLOBAL_ASSIGN)
        probed, total = subk_lib.assign_cost(x.shape[0] // n_data, aspec)
        scale = n_data * n_model * (n_iter + 1)
        counter.add(probed * scale, total * scale)
        assign_report = subk_lib.report(aspec, counter)
    return KMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        sse=jnp.asarray(float(sse), jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
        history=np.asarray(hist)[:n_iter],
        assign=assign_report,
    )


@lru_cache(maxsize=32)
def _lloyd_fit_fns_bounded(mesh, spherical, max_iters, tol):
    """kmeans_fit_sharded's bounded-assignment (loop, step) pair: the
    per-shard Hamerly bounds ride the compiled while_loop's carry, so
    the whole zero-loss pruned fit is still ONE dispatch. Returns
    (run(x, c0, state0) -> (c, shift, n_iter, hist, state), step)."""
    bstats = make_sharded_bounded_stats(mesh)

    @jax.jit
    def step(x, c, state: ShardedBoundsState):
        sums, counts, sse, lab, lb, ev = bstats(
            x, c, state.prev_c, state.lab, state.lb
        )
        cf = c.astype(jnp.float32)
        new_c = jnp.where(
            counts[:, None] > 0,
            sums / jnp.maximum(counts[:, None], 1.0),
            cf,
        )
        if spherical:
            new_c = _normalize(new_c)
        shift = jnp.max(jnp.linalg.norm(new_c - cf, axis=-1))
        new_state = ShardedBoundsState(
            prev_c=cf, lab=lab, lb=lb, ev=state.ev + ev
        )
        return new_c, shift, sse, new_state

    @jax.jit
    def run(x, c0, state0):
        def cond(carry):
            _, shift, i, _, _ = carry
            live = i < max_iters
            if tol >= 0:
                live = jnp.logical_and(live, shift > tol)
            return live

        def body(carry):
            c, _, i, hist, st = carry
            new_c, shift, cost, st = step(x, c, st)
            hist = hist.at[i].set(jnp.stack([cost, shift]))
            return new_c, shift, i + 1, hist, st

        carry0 = (
            c0,
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32),
            jnp.zeros((max_iters, 2), jnp.float32),
            state0,
        )
        return jax.lax.while_loop(cond, body, carry0)

    return run, step


@lru_cache(maxsize=64)
def _lloyd_fit_fns(mesh, kernel, block_rows, spherical, max_iters, tol,
                   assign_spec=None, gather="fp32"):
    """Per-configuration jitted (loop, step) pair for kmeans_fit_sharded,
    cached module-wide: a fit call otherwise builds FRESH jit closures and
    re-traces + re-compiles the whole while_loop every invocation —
    measured ~6 s per fit through the remote-compile tunnel even with the
    persistent XLA cache warm (round-5; repeated fits are the sweep
    harness's bread and butter). Keyed by everything the trace closes over
    (assign_spec is the hashable ops/subk.CoarseSpec).

    Quantized gather modes return run(x, c0, x2sum, gerr0): the
    finalize's error-feedback residual joins the while_loop carry (the
    same move the bounded tower makes for its bounds state), so the
    whole error-fed fit is still ONE dispatch."""
    step = make_sharded_lloyd_step(mesh, kernel, block_rows, spherical,
                                   assign_spec, gather)
    if gather_lib.resolve_gather(gather).quantized:

        @jax.jit
        def run(x, c0, x2sum, gerr0):
            def cond(carry):
                _, shift, i, _, _ = carry
                live = i < max_iters
                if tol >= 0:
                    live = jnp.logical_and(live, shift > tol)
                return live

            def body(carry):
                c, _, i, hist, ge = carry
                new_c, shift, cost, ge = step(x, c, x.shape[0], x2sum, ge)
                hist = hist.at[i].set(jnp.stack([cost, shift]))
                return new_c, shift, i + 1, hist, ge

            carry0 = (
                c0,
                jnp.asarray(jnp.inf, jnp.float32),
                jnp.asarray(0, jnp.int32),
                jnp.zeros((max_iters, 2), jnp.float32),
                gerr0,
            )
            return jax.lax.while_loop(cond, body, carry0)

        return run, step

    @jax.jit
    def run(x, c0, x2sum):
        return _device_loop(
            lambda ci: step(x, ci, x.shape[0], x2sum), c0, max_iters, tol
        )

    return run, step


def _gmm_pad_correction(nk, ll, means, variances, weights, n_pad, d):
    """Exact zero-row correction for the K-sharded GMM stats: a zero row's
    log-prob is the x-independent bias term per component; it contributes
    its responsibilities to nk and its log-normalizer to ll, nothing to
    sx/sxx. Computed from the K-sharded parameter vectors (the global max
    and sum are auto-sharded reductions)."""
    from tdc_tpu.models.gmm import _LOG_2PI

    logp0 = (
        -0.5 * (
            jnp.sum(means**2 / variances, axis=1)
            + jnp.sum(jnp.log(variances), axis=1)
            + d * _LOG_2PI
        )
        + jnp.log(weights)
    )
    mx0 = jnp.max(logp0)
    norm0 = mx0 + jnp.log(jnp.sum(jnp.exp(logp0 - mx0)))
    n_pad = jnp.asarray(n_pad, jnp.float32)
    return nk - n_pad * jnp.exp(logp0 - norm0), ll - n_pad * norm0


@lru_cache(maxsize=64)
def _gmm_fit_fns(mesh, block_rows, n, n_pad, reg_covar, max_iters, tol):
    """gmm_fit_sharded's cached jitted EM loop — see _lloyd_fit_fns. The
    device-side while_loop carries the last two mean log-likelihoods so
    the sklearn lower_bound_ convergence test (gain ≤ tol after iteration
    2) runs on-device — one host sync per fit, not per iteration."""
    stats_fn = make_sharded_gmm_stats(mesh, block_rows=block_rows)

    def step(x, means, variances, weights):
        ll, nk, sx, sxx = stats_fn(x, means, variances, weights)
        if n_pad:
            nk, ll = _gmm_pad_correction(
                nk, ll, means, variances, weights, n_pad, x.shape[1]
            )
        safe = jnp.maximum(nk, 1e-12)[:, None]
        new_means = sx / safe
        new_vars = jnp.maximum(sxx / safe - new_means**2, 0.0) + reg_covar
        new_w = jnp.maximum(nk / n, 1e-12)
        new_w = new_w / jnp.sum(new_w)
        return ll / n, new_means, new_vars, new_w

    @jax.jit
    def run(x, means0, var0, w0):
        def cond(carry):
            _, _, _, ll, prev_ll, i = carry
            return jnp.logical_and(
                i < max_iters,
                jnp.logical_or(i < 2, ll - prev_ll > tol),
            )

        def body(carry):
            means, var, w, ll_old, _, i = carry
            ll, nm, nv, nw = step(x, means, var, w)
            return nm, nv, nw, ll, ll_old, i + 1

        neg_inf = jnp.asarray(-jnp.inf, jnp.float32)
        return jax.lax.while_loop(
            cond, body,
            (means0, var0, w0, neg_inf, neg_inf, jnp.asarray(0, jnp.int32)),
        )

    return run


@lru_cache(maxsize=64)
def _fuzzy_fit_fns(mesh, m, block_rows, kernel, n_pad, max_iters, tol):
    """fuzzy_fit_sharded's cached (loop, step) pair — see _lloyd_fit_fns."""
    eps = 1e-9
    stats_fn = make_sharded_fuzzy_stats(
        mesh, m, eps, block_rows=block_rows, kernel=kernel
    )

    @jax.jit
    def step(x, c):
        wsums, weights, obj = stats_fn(x, c)
        if n_pad:
            weights, obj = _fuzzy_pad_correction(
                weights, obj, c, n_pad, m, eps,
                cast_dtype=x.dtype if kernel == "pallas" else None,
            )
        new_c = wsums / jnp.maximum(weights[:, None], 1e-12)
        shift = jnp.max(jnp.linalg.norm(new_c - c, axis=-1))
        return new_c, shift, obj

    @jax.jit
    def run(x, c0):
        return _device_loop(lambda ci: step(x, ci), c0, max_iters, tol)

    return run, step


def _pad_rows_sharded(x, n_data: int, block_rows: int):
    """(padded x, n_pad): zero-pad rows to the n_data x block multiple the
    sharded towers require (they hard-raise on ragged shards); callers
    remove the padding's exact contribution."""
    multiple = n_data * max(block_rows, 1)
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, 0
    if isinstance(x, np.ndarray):
        return np.pad(x, ((0, rem), (0, 0))), rem
    return jnp.pad(x, ((0, rem), (0, 0))), rem


def make_sharded_fuzzy_stats(
    mesh: Mesh, m: float = 2.0, eps: float = 1e-9, block_rows: int = 0,
    kernel: str = "xla", reduce_data: bool = True,
):
    """K-sharded fuzzy c-means sufficient stats (round-3 VERDICT item 5):
    jit-able fn(x, c) → (weighted_sums, weights, objective) with x sharded
    (data,), c sharded (model,); wsums/weights stay K-sharded, objective
    replicated.

    The only cross-shard quantity is the per-point membership normalizer
    Σ_K (d²+eps)^(-1/(m-1)) — a (block, 1) psum over the model axis (the
    fuzzy analog of the Lloyd tower's champion all_gather); every other
    term is local to its K-shard. The reference's fuzzy tower
    (scripts/distribuitedClustering.py:117-148) materialized the full
    (N, K) membership matrix per GPU — here no shard ever holds more than
    (block, K/Pm).

    kernel='pallas' runs the two-pass VMEM-streaming kernels inside each
    shard (ops/pallas_kernels.fuzzy_normalizer / fuzzy_accumulate) with the
    normalizer psum between the passes — no (n, K/Pm) tile anywhere, the
    fuzzy analog of the Lloyd tower's Pallas route. The kernels are
    internally N-blocked, so block_rows is ignored on that path (same rule
    as the Lloyd pallas route).

    reduce_data=False defers the stats reduces (parallel/reduce per-pass
    strategy): wsums (n_data, K, d) / weights (n_data, K) stay unreduced
    over the data axis and the objective stays a per-(data, model)-shard
    partial (n_data·n_model,); reduce once per pass with
    make_sharded_fuzzy_deferred_reduce. The per-point membership normalizer
    psum still runs per batch (N-proportional, not deferrable)."""
    out_specs = (
        (P(MODEL_AXIS, None), P(MODEL_AXIS), P()) if reduce_data
        else (P(DATA_AXIS, MODEL_AXIS, None), P(DATA_AXIS, MODEL_AXIS),
              P((DATA_AXIS, MODEL_AXIS)))
    )

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(MODEL_AXIS, None)),
        out_specs=out_specs,
        check_vma=False,
    )
    def stats(x_loc, c_loc):
        n_loc, d = x_loc.shape
        k_per = c_loc.shape[0]

        if kernel == "pallas":
            from tdc_tpu.ops.pallas_kernels import (
                fuzzy_accumulate,
                fuzzy_normalizer,
            )

            s_loc = fuzzy_normalizer(x_loc, c_loc, float(m), float(eps))
            s = jax.lax.psum(s_loc, MODEL_AXIS)  # (n, 1) global normalizer
            fs = fuzzy_accumulate(x_loc, c_loc, s, float(m), float(eps))
            wsums, weights, obj = (
                fs.weighted_sums, fs.weights, fs.objective,
            )
        else:
            def block(x_blk):
                d2 = pairwise_sq_dist(x_blk, c_loc)  # (b, K/Pm)
                inv = (d2 + eps) ** (-1.0 / (m - 1.0))
                s = jax.lax.psum(
                    jnp.sum(inv, axis=1, keepdims=True), MODEL_AXIS
                )  # (b, 1) — global normalizer
                u = inv / s
                mu = u**m
                wsums = jax.lax.dot_general(
                    mu,
                    x_blk.astype(jnp.float32),
                    (((0,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32,
                )  # (K/Pm, d)
                return wsums, jnp.sum(mu, axis=0), jnp.sum(mu * d2)

            if block_rows and n_loc > block_rows:
                if n_loc % block_rows != 0:
                    raise ValueError(
                        f"local shard rows {n_loc} not divisible by "
                        f"block_rows={block_rows}"
                    )
                xb = x_loc.reshape(n_loc // block_rows, block_rows, d)

                def body(acc, blk):
                    ws, w, o = block(blk)
                    return (acc[0] + ws, acc[1] + w, acc[2] + o), None

                zero = (
                    jnp.zeros((k_per, d), jnp.float32),
                    jnp.zeros((k_per,), jnp.float32),
                    jnp.zeros((), jnp.float32),
                )
                (wsums, weights, obj), _ = jax.lax.scan(body, zero, xb)
            else:
                wsums, weights, obj = block(x_loc)
        if not reduce_data:
            return wsums[None], weights[None], obj[None]
        wsums = jax.lax.psum(wsums, DATA_AXIS)
        weights = jax.lax.psum(weights, DATA_AXIS)
        # The objective sums over K too: reduce over BOTH axes.
        obj = jax.lax.psum(jax.lax.psum(obj, DATA_AXIS), MODEL_AXIS)
        return wsums, weights, obj

    return stats


def make_sharded_fuzzy_deferred_reduce(mesh: Mesh):
    """Per-pass reduce of the deferred K-sharded fuzzy accumulator: one
    data-axis psum of wsums/weights, one (data × model) psum of the
    objective partials. fn(wsums, weights, obj) → reduced K-sharded stats."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, MODEL_AXIS, None), P(DATA_AXIS, MODEL_AXIS),
                  P((DATA_AXIS, MODEL_AXIS))),
        out_specs=(P(MODEL_AXIS, None), P(MODEL_AXIS), P()),
        check_vma=False,
    )
    def red(wsums, weights, obj):
        return (
            jax.lax.psum(wsums[0], DATA_AXIS),
            jax.lax.psum(weights[0], DATA_AXIS),
            jax.lax.psum(
                jax.lax.psum(obj[0], DATA_AXIS), MODEL_AXIS
            ),
        )

    return red


def _fuzzy_pad_correction(weights, obj, c, n_pad, m: float, eps: float,
                          cast_dtype=None):
    """Exact zero-row correction (the soft analog of padding_correction):
    a zero row's memberships depend only on the centroid norms —
    u0 ∝ (‖c‖²+eps)^(-1/(m-1)) — adding u0^m to the weights and u0^m·‖c‖²
    to the objective, nothing to Σx. Computed from the K-sharded (K,) norm
    vector directly (the global Σ inv0 is an auto-sharded reduction).

    cast_dtype: the dtype the stats kernel cast the centroids to before
    computing ‖c‖² (the Pallas two-pass kernels use x.dtype —
    ops/pallas_kernels._twopass_prep). The correction must subtract exactly
    what the kernel added: with bf16 points the zero-row distances were
    built from bf16-rounded centroid norms (~0.4% off f32), so an f32-norm
    correction would leave a residual scaling with pad rows × iterations."""
    cf = c if cast_dtype is None else c.astype(cast_dtype)
    c2 = jnp.sum(cf.astype(jnp.float32) ** 2, axis=-1)
    inv0 = (c2 + eps) ** (-1.0 / (m - 1.0))
    u0 = inv0 / jnp.sum(inv0)
    mu0 = u0**m
    n_pad = jnp.asarray(n_pad, jnp.float32)
    return weights - n_pad * mu0, obj - n_pad * jnp.sum(mu0 * c2)


def fuzzy_fit_sharded(
    x,
    k: int,
    mesh: Mesh,
    *,
    m: float = 2.0,
    init,
    key=None,
    max_iters: int = 20,
    tol: float = 1e-4,
    block_rows: int = 0,
    kernel: str = "xla",
    dtype=None,
):
    """Fuzzy C-Means with points sharded over 'data' and centroids over
    'model' — the large-K regime for the reference's fastest algorithm
    (326 M pt·iter/s at K=3 in its log,
    scripts/distribuitedClustering.py:72-178). Same layout/init contract as
    kmeans_fit_sharded; kernel='pallas' runs the two-pass VMEM kernels
    inside each shard; dtype (e.g. jnp.bfloat16) converts the points before
    the device_put (stats stay f32). The fit loop runs device-side
    (lax.while_loop) — one host sync per fit, not per iteration."""
    from tdc_tpu.models.fuzzy import FuzzyCMeansResult

    n_data = mesh.devices.shape[0]
    n_model = mesh.devices.shape[1]
    if not isinstance(x, np.ndarray):
        x = jnp.asarray(x)
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    if m <= 1.0:
        raise ValueError(f"fuzzifier m must be > 1, got {m}")
    from tdc_tpu.ops.pallas_kernels import resolve_kernel

    kernel = resolve_kernel(kernel, k=k // n_model, d=x.shape[1],
                            model="fuzzy_sharded",
                            label="fuzzy_fit_sharded")
    c = _resolve_init_sharded(x, k, init, key)
    x, n_pad = _pad_rows_sharded(x, n_data, block_rows)
    x = jax.device_put(_cast_points(x, dtype),
                       NamedSharding(mesh, P(DATA_AXIS, None)))
    c = jax.device_put(c, NamedSharding(mesh, P(MODEL_AXIS, None)))
    run, step = _fuzzy_fit_fns(mesh, float(m), block_rows, kernel,
                               int(n_pad), int(max_iters), float(tol))
    c, shift_dev, i_dev, hist = run(x, c)
    n_iter = int(i_dev)
    shift = float(shift_dev)
    converged = tol >= 0 and shift <= tol
    _, _, obj = step(x, c)  # objective of the RETURNED centroids
    return FuzzyCMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        objective=jnp.asarray(float(obj), jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
        history=np.asarray(hist)[:n_iter],
    )


def make_sharded_gmm_stats(mesh: Mesh, block_rows: int = 0):
    """K-sharded diag-GMM E-step sufficient stats (round-3 VERDICT item 5):
    jit-able fn(x, means, variances, weights) → (ll_sum, nk, sx, sxx) with
    x sharded (data,) and all component parameters sharded (model,);
    nk/sx/sxx stay K-sharded, ll_sum replicated.

    The cross-shard quantity is the per-point log-normalizer: a pmax over
    the model axis for the stable max, then a psum of Σ exp(logp − max) —
    a distributed logsumexp, the soft analog of the Lloyd champion
    reduction. Responsibilities and moments stay local per K-shard."""
    from tdc_tpu.models.gmm import _LOG_2PI

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None), P(MODEL_AXIS, None), P(MODEL_AXIS, None),
            P(MODEL_AXIS),
        ),
        out_specs=(
            P(), P(MODEL_AXIS), P(MODEL_AXIS, None), P(MODEL_AXIS, None)
        ),
        check_vma=False,
    )
    def stats(x_loc, means_loc, var_loc, w_loc):
        n_loc, d = x_loc.shape
        k_per = means_loc.shape[0]
        inv = 1.0 / var_loc
        log_det = jnp.sum(jnp.log(var_loc), axis=1)  # (K/Pm,)
        log_w = jnp.log(w_loc)

        def block(x_blk):
            xf = x_blk.astype(jnp.float32)
            xsq = xf * xf
            maha = (
                xsq @ inv.T
                - 2.0 * (xf @ (means_loc * inv).T)
                + jnp.sum(means_loc**2 * inv, axis=1)[None, :]
            )  # (b, K/Pm)
            logp = (
                -0.5 * (maha + log_det[None, :] + d * _LOG_2PI)
                + log_w[None, :]
            )
            mx = jax.lax.pmax(
                jnp.max(logp, axis=1, keepdims=True), MODEL_AXIS
            )  # (b, 1) — global max
            se = jax.lax.psum(
                jnp.sum(jnp.exp(logp - mx), axis=1, keepdims=True),
                MODEL_AXIS,
            )
            norm = mx + jnp.log(se)  # (b, 1) — global logsumexp
            r = jnp.exp(logp - norm)  # (b, K/Pm) — local responsibilities
            nk = jnp.sum(r, axis=0)
            sx = jax.lax.dot_general(
                r, xf, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            sxx = jax.lax.dot_general(
                r, xsq, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return jnp.sum(norm), nk, sx, sxx

        if block_rows and n_loc > block_rows:
            if n_loc % block_rows != 0:
                raise ValueError(
                    f"local shard rows {n_loc} not divisible by "
                    f"block_rows={block_rows}"
                )
            xb = x_loc.reshape(n_loc // block_rows, block_rows, d)

            def body(acc, blk):
                ll, nk, sx, sxx = block(blk)
                return (acc[0] + ll, acc[1] + nk, acc[2] + sx,
                        acc[3] + sxx), None

            zero = (
                jnp.zeros((), jnp.float32),
                jnp.zeros((k_per,), jnp.float32),
                jnp.zeros((k_per, d), jnp.float32),
                jnp.zeros((k_per, d), jnp.float32),
            )
            (ll, nk, sx, sxx), _ = jax.lax.scan(body, zero, xb)
        else:
            ll, nk, sx, sxx = block(x_loc)
        # norm is identical on every model shard (already globally reduced),
        # so ll only reduces over the data axis.
        ll = jax.lax.psum(ll, DATA_AXIS)
        nk = jax.lax.psum(nk, DATA_AXIS)
        sx = jax.lax.psum(sx, DATA_AXIS)
        sxx = jax.lax.psum(sxx, DATA_AXIS)
        return ll, nk, sx, sxx

    return stats


def gmm_fit_sharded(
    x,
    k: int,
    mesh: Mesh,
    *,
    init="kmeans++",
    key=None,
    max_iters: int = 100,
    tol: float = 1e-3,
    reg_covar: float = 1e-6,
    block_rows: int = 0,
    dtype=None,
):
    """Diag-covariance GMM EM with points sharded over 'data' and components
    sharded over 'model'. Seeding mirrors _resolve_init_sharded (host
    subsample); variances start at the subsample's per-dimension variance,
    weights uniform. Convergence: mean per-point log-likelihood gain ≤ tol
    (sklearn's lower_bound_ criterion). dtype (e.g. jnp.bfloat16) converts
    the points before the device_put — halves HBM/H2D; the E-step itself
    computes in f32 regardless (the stats tower casts per block)."""
    from tdc_tpu.models.gmm import GMMResult

    n_data = mesh.devices.shape[0]
    n_model = mesh.devices.shape[1]
    if not isinstance(x, np.ndarray):
        x = jnp.asarray(x)
    n = x.shape[0]
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    if isinstance(init, str) and init == "kmeans":
        raise ValueError(
            "gmm_fit_sharded seeds from a host subsample "
            "(_resolve_init_sharded); init='kmeans' (a full K-Means pre-fit) "
            "is the unsharded gmm_fit's mode — pass 'kmeans++' or an array"
        )
    means = _resolve_init_sharded(x, k, init, key)
    # Initial variances/weights from the hard assignment to the initial
    # means (gmm_fit's _moments_from_hard_assign — a loose global-variance
    # init lets early E-steps merge separated components), computed on the
    # same deterministic host subsample the seeding uses: the init moments
    # are a starting heuristic, and a full-data pass here would need the
    # very (N, K) work the sharded layout exists to avoid.
    from tdc_tpu.models.gmm import _moments_from_hard_assign

    sample = jnp.asarray(np.asarray(x[: min(n, 65536)], np.float32))
    variances, weights = _moments_from_hard_assign(sample, means, reg_covar)
    x, n_pad = _pad_rows_sharded(x, n_data, block_rows)
    x = jax.device_put(_cast_points(x, dtype),
                       NamedSharding(mesh, P(DATA_AXIS, None)))
    put_k = lambda a: jax.device_put(
        a, NamedSharding(mesh, P(MODEL_AXIS) if a.ndim == 1
                         else P(MODEL_AXIS, None))
    )
    means, variances, weights = map(put_k, (means, variances, weights))
    run = _gmm_fit_fns(mesh, block_rows, int(n), int(n_pad),
                       float(reg_covar), int(max_iters), float(tol))
    means, variances, weights, ll_dev, prev_ll_dev, i_dev = run(
        x, means, variances, weights
    )
    n_iter = int(i_dev)
    ll = float(ll_dev)
    converged = n_iter >= 2 and ll - float(prev_ll_dev) <= tol
    return GMMResult(
        means=means,
        variances=variances,
        weights=weights,
        log_likelihood=jnp.asarray(ll, jnp.float32),
        n_iter=jnp.asarray(n_iter, jnp.int32),
        converged=jnp.asarray(converged),
        covariance_type="diag",
    )


class _ShardedAcc(NamedTuple):
    sums: jax.Array  # (K, d) — K-sharded
    counts: jax.Array  # (K,) — K-sharded
    sse: jax.Array  # () — replicated


def _host_full(arr) -> np.ndarray:
    """Assemble a global (possibly K-sharded) array on THIS host from its
    addressable shards. Valid whenever every model shard has a replica on
    every process — the (data × model) layout with the data axis spanning
    the processes, where each process's local devices cover every K-shard
    column. The gang checkpoint writer needs the full array host-side;
    np.asarray alone refuses non-fully-addressable global arrays."""
    if isinstance(arr, np.ndarray):
        return arr
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    out = np.empty(arr.shape, arr.dtype)
    covered = np.zeros(arr.shape, bool)
    for s in arr.addressable_shards:
        out[s.index] = np.asarray(s.data)
        covered[s.index] = True
    if not covered.all():
        raise ValueError(
            "checkpoint gather: this process does not hold every K-shard "
            "(model axis spans processes); put the data axis across "
            "processes so centroid shards are process-local"
        )
    return out


class _GatheringCheckpointer:
    """_StreamCheckpointer adapter for multi-process K-sharded gangs:
    gathers the sharded centroids/accumulator to host before the write
    (the inner checkpointer then runs the gang single-writer protocol —
    process 0 writes, everyone barriers; utils/checkpoint.py)."""

    def __init__(self, inner):
        self._inner = inner

    @property
    def dir(self):  # _run_pass consults ckpt.dir for mid-pass saves
        return self._inner.dir

    def restore(self, acc_cls, mesh):
        return self._inner.restore(acc_cls, mesh)

    def save(self, n_iter, c, shift, history, *, batch_cursor=0, acc=None,
             rows_seen=0):
        if jax.process_index() == 0:
            if acc is not None:
                acc = type(acc)(*[_host_full(t) for t in acc])
            c = _host_full(c)
        else:
            # Non-writers only rendezvous in save_checkpoint's barrier;
            # skip their D2H gather (tens of MB per mid-pass save at the
            # K=16384·d=768 target) and hand the inner checkpointer cheap
            # placeholders it never reads.
            c = np.zeros((0, 0), np.float32)
            if acc is not None:
                acc = type(acc)(
                    *[np.zeros(0, np.float32) for _ in acc]
                )
        return self._inner.save(
            n_iter, c, shift, history,
            batch_cursor=batch_cursor, acc=acc, rows_seen=rows_seen,
        )


@jax.jit
def _spherical_rows(xb):
    # Normalize real rows; zero padding rows stay zero (norm 0 guard).
    norms = jnp.linalg.norm(xb, axis=-1, keepdims=True)
    return jnp.where(norms > 0, xb / jnp.maximum(norms, 1e-12), xb)


def _cast_points(x, dtype):
    """Host-or-device dtype cast for the in-memory sharded fits (bf16
    halves the H2D/HBM cost; stats stay f32) — one copy shared by the
    fuzzy and GMM towers, same rationale as _make_put_batch."""
    if dtype is None:
        return x
    return x.astype(dtype) if isinstance(x, np.ndarray) else jnp.asarray(
        x, dtype
    )


def _make_put_batch(mesh, pad_multiple: int, dtype, spherical: bool = False):
    """The per-batch host→device staging closure shared by all three
    streamed K-sharded drivers: zero-pad rows to the shard multiple,
    optional host-side dtype cast (bf16 halves the transfer), device_put
    data-sharded, optional row normalization (spherical — zero pad rows
    stay zero). One copy so pad/cast/placement can never drift between
    the towers (the fuzzy cast_dtype episode)."""

    def put_batch(batch):
        with trace.span("stage"):
            batch = np.asarray(batch)
            n_valid = batch.shape[0]
            rem = (-n_valid) % pad_multiple
            if rem:
                batch = np.pad(batch, ((0, rem), (0, 0)))
            if dtype is not None:
                import ml_dtypes  # noqa: F401 — registers bfloat16 w/ numpy

                batch = batch.astype(np.dtype(dtype))  # host-side cast
            xb = jax.device_put(batch,
                                NamedSharding(mesh, P(DATA_AXIS, None)))
            if spherical:
                xb = _spherical_rows(xb)
            return xb, n_valid

    return put_batch


def _stream_kernel_itemsize(batches, dtype) -> int:
    """Element width the kernels will actually see for a streamed fit:
    the host-side `dtype` cast when one is requested, else the stream's
    own element size (stream_itemsize; bf16 .npz streams advertise 2),
    else the f32 default — so kernel='auto' evaluates VMEM feasibility
    against the real operand width, not a pessimistic f32 guess."""
    from tdc_tpu.data import device_cache as dc

    if dtype is not None:
        return int(np.dtype(dtype).itemsize)
    return dc.stream_itemsize(batches) or 4


def _plan_sharded_residency(residency, batches, k, d, spec: MeshSpec, *,
                            pad_multiple, kernel, dtype, cursor, label,
                            mid_pass_ckpt=False):
    """Residency planning for the K-sharded streamed drivers. Geometry
    comes off the MeshSpec: every process streams IDENTICAL GLOBAL
    batches (the sharded contract — spec.process_scale is 1 on the 2-D
    layout), padded to n_data*block_rows and sharded over the data axis
    only — the cache is replicated across the model axis, so the
    per-device budget divides by spec.n_data, not n_data*n_model.
    `dtype` (the host-side bf16 cast) halves the cache itemsize; without
    the cast the stream's own element width (stream_itemsize) budgets
    natively-bf16 streams."""
    from tdc_tpu.data import device_cache as dc

    if residency not in dc.RESIDENCY_MODES:
        raise ValueError(
            f"residency={residency!r}: use one of {dc.RESIDENCY_MODES}"
        )
    if residency == "stream":
        return None, None
    itemsize = (
        int(np.dtype(dtype).itemsize) if dtype is not None
        else dc.stream_itemsize(batches) or 4
    )
    plan = dc.plan_residency(
        residency, hints=dc.stream_hints(batches), d=d, k=k,
        n_devices=spec.n_data, pad_multiple=pad_multiple,
        process_scale=spec.process_scale,
        itemsize=itemsize, weighted=False, kernel=kernel, cursor=cursor,
        mid_pass_ckpt=mid_pass_ckpt, label=label,
    )
    builder = None
    if plan.resident:
        builder = dc.DeviceCacheBuilder(plan.hints.n_batches,
                                        mesh=spec.mesh,
                                        weighted=False, label=label)
    return plan, builder


def _sharded_stream_loop(
    *,
    batches,
    prefetch: int,
    ckpt,
    ckpt_dir,
    ckpt_every: int,
    ckpt_every_batches,
    max_iters: int,
    tol: float,
    c,
    state,
    put_acc,
    zero_acc,
    step_batch,
    update,
    acc_cost,
    finalize=None,
    fill=None,
    make_resident=None,
    resident_cost=None,
    chunk_iters: int = 0,
    mesh=None,
    gang: bool = False,
    counter=None,
    make_aux=None,
    assign_counter=None,
    assign_pass_cost=None,
    report_step=None,
):
    """The deferred-sync iteration driver shared by the streamed K-sharded
    fits (Lloyd and fuzzy differ only in their accumulator algebra): resume
    bookkeeping from a restored `state`, one accumulation pass per
    iteration via models/streaming._run_pass, the update, and the sync
    policy — only the convergence test / checkpoint metadata justify a
    per-iteration device fetch (a round trip costs ~10× the iteration's
    dispatch on remote links; round-4 streamed-driver fix).

    step_batch(acc, batch, c) -> (acc, n_rows); update(acc, c) ->
    (new_c, shift); acc_cost(acc) -> the history cost scalar (sse / obj);
    put_acc re-device_puts a restored accumulator to its shardings.
    finalize(acc, c) — set by the per-pass reduce mode — runs right after
    each pass (including the final reporting pass) to issue the pass's ONE
    cross-device reduce and padding correction; update/acc_cost then see a
    standard reduced accumulator.

    Residency (data/device_cache.py): with a `fill` builder, the first
    executed pass streams AND fills the HBM cache — step_batch is then
    called as step_batch(acc, batch, c, fill) — and iterations 2..N run as
    make_resident(cache)'s compiled chunk loop (models/resident.py) with
    host fetches, checkpoint saves, and gang-agreed preemption drains only
    at chunk boundaries. resident_cost(cache) -> the per-resident-iteration
    comms (reduces, bytes) the counter should book.

    make_aux(cache) builds the resident chunk's aux carry (the bounded
    fits' per-shard bounds state; () when absent). assign_counter /
    assign_pass_cost(cache) -> (probed, total): EXACT per-pass coarse
    tile accounting booked per chunk against the while-loop's carried
    pass count (replacing the PR-11 extrapolation).

    Returns (c, n_iter, start_iter, shift, converged, history, final_acc,
    resident_passes, aux) where final_acc is one extra pass at the
    RETURNED centroids (its cost is the fit's reported SSE/objective —
    parity with streamed_kmeans_fit) and aux is the resident carry after
    the final pass (the bounded fits read their eval tallies off it).

    report_step, when given, replaces step_batch for that final reporting
    pass only: the quantized-gather fits route it through full-precision
    champion stats so the REPORTED SSE measures centroid quality, not
    wire compression (the convention kmeans_fit_sharded's fp32 report
    step established; per-iteration history rows keep the fit's own
    quantized cost).
    """
    from tdc_tpu.models import resident as resident_lib
    from tdc_tpu.models.streaming import _run_pass

    shift = state.shift
    history = state.history
    start_iter = state.start_iter
    resume_cursor, resume_rows = state.cursor, state.rows_seen
    resume_acc = None if state.acc is None else put_acc(state.acc)

    def full_pass(c, n_iter=0, skip=0, acc0=None, rows0=0, pass_fill=None,
                  step=None):
        fn = step_batch if step is None else step

        def pass_step(acc, batch):
            maybe_beat()  # supervised-gang liveness
            if pass_fill is None:
                return fn(acc, batch, c)
            return fn(acc, batch, c, pass_fill)

        return _run_pass(
            batches, prefetch, zero_acc, pass_step,
            ckpt=ckpt, ckpt_every_batches=ckpt_every_batches, n_iter=n_iter,
            skip=skip, acc0=acc0, rows0=rows0,
            save_args=(c, shift, history),
        )

    n_iter = start_iter
    resume_converged = tol >= 0 and shift <= tol
    converged = resume_converged
    cache = None
    iters = (
        () if resume_converged else range(start_iter + 1, max_iters + 1)
    )
    for n_iter in iters:
        use_fill = (fill if n_iter == start_iter + 1 and not resume_cursor
                    else None)
        acc = full_pass(c, n_iter, skip=resume_cursor, acc0=resume_acc,
                        rows0=resume_rows, pass_fill=use_fill)
        resume_cursor, resume_acc, resume_rows = 0, None, 0
        if use_fill is not None:
            # Even a fit that converges on iteration 1 reuses the cache
            # for the final reporting pass below.
            cache = use_fill.finish()
        if finalize is not None:
            # The pass's ONE cross-device reduce (per-pass mode); the
            # span's hard sync (tracing only) reads device truth.
            with trace.span("reduce", n_iter=n_iter):
                acc = finalize(acc, c)
                trace.sync(acc)
        with trace.span("shift_check", n_iter=n_iter):
            c, shift_dev = update(acc, c)
            # Tracing re-establishes device truth per iteration (the
            # span must not read dispatch time), accepting the fetch the
            # async path otherwise defers.
            sync = tol >= 0 or ckpt_dir is not None or trace.enabled()
            shift = float(shift_dev) if sync else shift_dev
        cost = acc_cost(acc)
        history.append((float(cost) if sync else cost, shift))
        trace.timeline_shift(n_iter, shift if sync else None)
        done = sync and tol >= 0 and shift <= tol
        if ckpt_dir is not None and (done or n_iter % ckpt_every == 0
                                     or n_iter == max_iters):
            ckpt.save(n_iter, c, shift, history)
        if done:
            converged = True
            break
        if cache is not None:
            break  # iterations 2..N run on-device over the cache
    chunk_fns = None
    resident_passes = 0
    aux = ()
    if cache is not None and make_resident is not None:
        chunk_fns = make_resident(cache)
        cost_ri = resident_cost(cache)
        cost_ai = (assign_pass_cost(cache)
                   if assign_counter is not None and assign_pass_cost
                   else (0, 0))
        if make_aux is not None:
            aux = make_aux(cache)
        if n_iter < max_iters and not (tol >= 0 and float(shift) <= tol):
            shift = float(shift)
            iter_before_resident = n_iter
            c, aux, n_iter, shift, converged, history = (
                resident_lib.run_resident_loop(
                    chunk=chunk_fns[0], cache=cache, c=c, aux=aux,
                    n_iter=n_iter, max_iters=max_iters, tol=tol,
                    shift=shift, history=history, chunk_iters=chunk_iters,
                    mesh=mesh, gang=gang, ckpt=ckpt, ckpt_dir=ckpt_dir,
                    ckpt_every=ckpt_every, counter=counter,
                    comms_per_iter=cost_ri,
                    assign_counter=assign_counter, assign_per_pass=cost_ai,
                )
            )
            resident_passes += n_iter - iter_before_resident
    shift = float(shift)  # one deferred fetch on the async path
    if chunk_fns is not None:
        final_acc, aux = resident_lib.final_pass(
            chunk_fns[1], c, aux, cache, counter=counter,
            comms_per_iter=cost_ri,
            assign_counter=assign_counter, assign_per_pass=cost_ai,
        )
        resident_passes += 1
    else:
        final_acc = full_pass(c, step=report_step)
        if finalize is not None:
            with trace.span("reduce", n_iter=0):
                final_acc = finalize(final_acc, c)
                trace.sync(final_acc)
    return (c, n_iter, start_iter, shift, converged, history, final_acc,
            resident_passes, aux)


def streamed_kmeans_fit_sharded(
    batches: Callable[[], Iterable],
    k: int,
    d: int,
    mesh: Mesh,
    *,
    init,
    key=None,
    max_iters: int = 20,
    tol: float = 1e-4,
    spherical: bool = False,
    kernel: str = "xla",
    block_rows: int = 0,
    dtype=None,
    prefetch: int = 0,
    ckpt_dir: str | None = None,
    ckpt_every: int = 1,
    ckpt_every_batches: int | None = None,
    reduce="per_batch",
    residency: str = "stream",
    ingest=None,
    assign: str = "exact",
    probe=None,
    gather: str = "fp32",
) -> KMeansResult:
    """Exact out-of-core Lloyd under the 2-D (data × model) layout — the
    1B×768, K=16,384 configuration: batches stream host→device, each batch's
    K-sharded sufficient stats accumulate on-device across the pass, and the
    centroid state never exists unsharded.

    reduce: "per_batch" (default, exact) issues the data-axis psum of the
    K-sharded stats once per streamed batch; "per_pass"
    (parallel/reduce.py) keeps the per-data-shard partials local across the
    whole pass and issues ONE data-axis reduce per Lloyd iteration — O(1)
    vs O(num_batches) collectives, at the cost of reordered f32 summation
    (tolerance-level, not bitwise, parity) and no mid-pass checkpointing.
    The fit result's `comms` field reports reduces issued / logical bytes,
    split by mesh axis (data_bytes = stats reduces, model_bytes = champion
    + finalize gathers).

    gather: "fp32" (default — the pre-PR schedules, byte-identical),
    "fp32_sharded" (full-precision wire, data-axis-sharded centroid
    finalize: each device divides its 1/n_data K-slice and the slices
    cross in one all_gather — bit-exact vs the replicated finalize),
    "bf16" or "int8" (the sharded-finalize structure with the champion
    mins and finalize slices compressed per parallel/gather.py; the
    finalize gather carries a persistent error-feedback residual across
    passes). Quantized modes refuse checkpointing, hbm/auto residency,
    bounded assignment, and single-device meshes (plan_gather's loud
    rules — the EF residual must persist host-side across passes).
    Model-axis byte accounting covers the streamed path; resident
    (hbm/auto) iterations book data-axis reduces only.

    residency: "stream" (default), "hbm", "spill", or "auto" — under
    "hbm"/"auto" iteration 1 streams AND fills a per-device HBM cache of
    the padded, data-axis-sharded batches (replicated over the model axis;
    the bf16 `dtype` cast halves the cache), and iterations 2..N run as a
    compiled on-device chunk loop with zero host transfers per iteration
    (models/resident.py; same contract as streamed_kmeans_fit). An
    over-budget dataset whose slot ring still fits runs as "spill"
    (data/spill.py): the host-side cast + `device_put` staging moves onto
    a producer thread 2+ slots ahead of the consumer, hiding each batch's
    H2D copy behind the previous batch's compute, fp32-bit-exact with
    plain streaming; the result's `h2d` field reports the ring's transfer
    accounting. Only when even the ring does not fit does "auto" fall
    back to synchronous streaming — loudly, via a structlog
    `residency_fallback` event.

    `batches` follows the models/streaming contract: a zero-arg callable
    returning a fresh iterator of (rows, d) arrays per Lloyd iteration.
    `dtype` (e.g. jnp.bfloat16) converts batches host-side before transfer —
    the MXU fast path for the bf16 K=16,384 regime; stats stay f32.

    ingest: the hardened-ingest policy (data/ingest.IngestPolicy; see
    streamed_kmeans_fit) — read retry/backoff, zero-mass corrupt-batch
    quarantine (every process streams IDENTICAL global batches here, so
    quarantine verdicts are symmetric across a gang by construction), and
    bounded-loss accounting on the result's `ingest` field with the
    strict max_bad_fraction=0.0 default.

    assign="bounded": the ZERO-LOSS sub-linear mode — per-shard Hamerly
    bounds live NEXT TO the HBM cache as the resident chunk's donated
    aux carry (ShardedResidentBounds), so it requires residency
    "hbm"/"auto" reaching hbm; streamed/spill fits fall back to exact
    LOUDLY (`bounds_fallback`). Streamed passes (incl. the cache fill)
    run exact; resident iterations 2..N run the bounded tower
    (make_sharded_bounded_stats) with the exact tower's collective
    schedule byte for byte. Refuses spherical / kernel='pallas' /
    reduce='per_pass'. The result's `bounds` field carries the
    BoundsReport.

    ckpt_dir enables checkpoint/resume with the models/streaming contract
    (per-iteration saves every `ckpt_every` iterations; mid-pass accumulator
    + batch-cursor saves every `ckpt_every_batches` batches; resume is
    bit-identical to the uninterrupted fit).

    Multi-process gangs: pass a mesh whose DATA axis spans the processes
    (model columns process-local, the pod deployment shape) and have every
    process stream IDENTICAL global batches (the kmeans_fit_sharded
    contract: device_put places only this host's addressable rows).
    Checkpointing then runs the gang single-writer protocol — every
    process assembles the K-sharded state from its local shard replicas
    (_host_full), process 0 writes, all rendezvous — so a supervised gang
    (parallel/supervisor.py) can kill-and-resume mid-fit.
    """
    from tdc_tpu.models.streaming import (
        _StreamCheckpointer,
        _first_for_init,
        _history_array,
        _lloyd_example,
        _mesh_layout,
        _reduce_plan,
    )
    from tdc_tpu.parallel import reduce as reduce_lib

    spec = MeshSpec.of(mesh)
    n_data, n_model = spec.n_data, spec.n_model
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    from tdc_tpu.ops import subk as subk_lib
    from tdc_tpu.ops.pallas_kernels import resolve_kernel
    from tdc_tpu.testing.faults import fault_point

    bounded = assign == "bounded"
    if bounded:
        from tdc_tpu.ops import bounds as bounds_lib

        if probe is not None:
            raise ValueError(
                "probe= only applies to assign='coarse'/'auto' (bounded "
                "assignment is exact)"
            )
        if spherical:
            raise ValueError(
                "assign='bounded' does not support spherical=True; use "
                "assign='exact'"
            )
        if kernel == "pallas":
            raise ValueError(
                "assign='bounded' runs its own masked-recompute tower and "
                "cannot combine with kernel='pallas'"
            )
        if spec.n_processes > 1:
            raise ValueError(
                "assign='bounded' on the K-sharded drivers is single-"
                "process only (the bounds init and eval-tally fetches "
                "read sharded state host-side); use assign='exact'"
            )
        kernel = "xla"
        aspec = subk_lib.EXACT  # streamed passes (incl. the fill) run exact
        bounds_lib.resolve_bounds("hamerly", k,
                                  label="streamed_kmeans_fit_sharded")
    else:
        kernel = resolve_kernel(
            kernel, k=k // n_model, d=d,
            itemsize=_stream_kernel_itemsize(batches, dtype),
            model="kmeans_sharded",
            label="streamed_kmeans_fit_sharded")
        # Tiles are per model shard: the coarse plan (and the auto
        # threshold) see K/Pm local centroids, mirroring where the
        # pruning runs.
        aspec = subk_lib.resolve_assign(assign, k // n_model, probe=probe,
                                        label="streamed_kmeans_fit_sharded")
    strategy = reduce_lib.resolve_reduce(reduce)
    gstrategy = plan_gather(gather, mesh, k, assign=assign,
                            ckpt_dir=ckpt_dir,
                            ckpt_every_batches=ckpt_every_batches or 0,
                            residency=residency)
    if bounded and strategy.deferred:
        raise ValueError(
            "assign='bounded' is wired for reduce='per_batch' (the bounded "
            "tower reduces its stats per batch like the exact one); drop "
            "reduce='per_pass' or use assign='exact'"
        )
    deferred, _ = _reduce_plan(strategy, mesh, ckpt_dir, ckpt_every_batches,
                               allow_quantize=False)
    gang = spec.gang
    if ckpt_dir is not None and gang:
        # Gang checkpointing needs every K-shard process-local so process 0
        # can assemble the full (K, d) state host-side (_host_full): every
        # process must own a device in every model column. The data-axis-
        # across-processes layout (the pod deployment shape) satisfies
        # this; a model axis spanning processes does not.
        nproc = spec.n_processes
        for j in range(n_model):
            col_procs = {dev.process_index for dev in mesh.devices[:, j]}
            if len(col_procs) != nproc:
                raise ValueError(
                    "K-sharded gang checkpointing requires the data axis "
                    "to span the processes (every process holding every "
                    f"K-shard); model column {j} is only on processes "
                    f"{sorted(col_procs)}"
                )
    pad_multiple = n_data * max(block_rows, 1)

    # shard_model is NOT a validated hyperparameter: the checkpoint keeps
    # the gathered full (K, d) state plus a layout manifest, so a save
    # taken under one (data, model) split restores under any other
    # (reshard.redistribute below) — that is the elastic-resize contract.
    ckpt = _StreamCheckpointer(
        ckpt_dir, k, d,
        params={"spherical": bool(spherical)},
        acc_map={"acc_sums": "sums", "acc_counts": "counts",
                 "acc_sse": "sse"},
        key=key,
        gang=gang,
        spec=spec,
    )
    if gang:
        ckpt = _GatheringCheckpointer(ckpt)
    guard = ingest_lib.guard_stream(batches, ingest, d=d,
                                    label="streamed_kmeans_fit_sharded")
    if gang and getattr(guard, "disjoint_shards", False):
        raise ValueError(
            "streamed_kmeans_fit_sharded: disjoint-shard manifest streams "
            "in a multi-process gang are not supported on the K-sharded "
            "driver — its padding correction folds n_valid as a replicated "
            "scalar, and disjoint shards quarantine per HOST, which would "
            "fork the replicated state; use the 1-D streamed driver "
            "(streamed_kmeans_fit) for gang object-store ingestion"
        )
    # Restore FIRST (models/streaming convention): a resume must not re-pay
    # init resolution, and must report the checkpointed state faithfully.
    state = ckpt.restore(_ShardedAcc, None)
    if state.cursor:
        # Re-validate with the restored cursor (mid-pass per-batch
        # checkpoints cannot resume under per_pass — _reduce_plan's rule).
        _reduce_plan(strategy, mesh, ckpt_dir, ckpt_every_batches,
                     cursor=state.cursor, allow_quantize=False)
    if state.centroids is not None:
        c = jnp.asarray(state.centroids, jnp.float32)
        restored = True
    else:
        restored = False
        first = None
        if not hasattr(init, "shape"):
            first = np.asarray(_first_for_init(guard))
            if spherical:
                first = np.asarray(
                    _normalize(jnp.asarray(first, jnp.float32))
                )
            init = _resolve_init_sharded(first, k, init, key)
        c = jnp.asarray(init, jnp.float32)
        if c.shape != (k, d):
            raise ValueError(f"init shape {c.shape} != {(k, d)}")
        if spherical:
            c = _normalize(c)

    def put_c(t):
        return jax.device_put(t, NamedSharding(mesh, P(MODEL_AXIS, None)))

    if restored:
        # The gathered full-(K, d) save re-slices under THIS mesh's model
        # split — K % n_model re-checked above, so a resize that changed
        # the split lands bit-exactly on the new shards.
        c = reshard_lib.redistribute(c, state.layout, spec, place=put_c)
    else:
        c = put_c(c)

    def put_acc(acc):
        # Mid-pass accumulators are persisted gathered too; a resize
        # restore re-slices them the same way as the centroids (the
        # observability fired once at the centroid redistribute).
        return _ShardedAcc(
            sums=jax.device_put(
                acc.sums, NamedSharding(mesh, P(MODEL_AXIS, None))
            ),
            counts=jax.device_put(
                acc.counts, NamedSharding(mesh, P(MODEL_AXIS))
            ),
            sse=acc.sse,
        )

    stats_fn = make_sharded_stats(mesh, kernel, block_rows,
                                  reduce_data=not deferred,
                                  assign_spec=aspec, gather=gstrategy.mode)
    r_plan, r_builder = _plan_sharded_residency(
        residency, batches, k, d, spec,
        pad_multiple=pad_multiple, kernel=kernel, dtype=dtype,
        cursor=state.cursor, label="streamed_kmeans_fit_sharded",
        mid_pass_ckpt=ckpt_every_batches is not None,
    )
    if bounded and (r_plan is None or not r_plan.resident):
        # Per-shard bounds are multi-iteration device state living next
        # to the HBM cache; streamed/spill fits re-upload every batch and
        # the bounds die with it. Loud, zero-loss fallback: exact.
        from tdc_tpu.utils.structlog import emit

        emit("bounds_fallback", label="streamed_kmeans_fit_sharded",
             requested=assign, residency=residency,
             reason="stream" if r_plan is None else r_plan.reason,
             detail="bounded assignment needs the HBM-resident cache "
                    "(per-shard bounds are multi-iteration device "
                    "state); running exact assignment instead")
        bounded = False
    chunk_iters = _chunk_iters_for(ckpt_dir, ckpt_every)
    counter = reduce_lib.CommsCounter(_mirror=reduce_lib.GLOBAL_COMMS)
    assign_counter = (
        subk_lib.AssignCounter(_mirror=subk_lib.GLOBAL_ASSIGN)
        if aspec.coarse else None
    )

    def _book_assign(rows_padded: int) -> None:
        # Every (data, model) shard pair refines its own blocks against
        # its own tiles: the logical tile tally scales by both axes.
        probed, total = subk_lib.assign_cost(rows_padded // n_data, aspec)
        scale = n_data * n_model
        assign_counter.add(probed * scale, total * scale)
    cost_reduce = (
        reduce_lib.tree_reduce_cost(_lloyd_example(k, d), (DATA_AXIS,))
        if n_data > 1 else (0, 0)
    )

    def _book_champion(rows_padded: int, gmode: str) -> None:
        # Model-axis accounting for the batch's champion (min, argmin)
        # all_gather pair: every row's champion crosses the model axis
        # once, so the logical bytes cover the full padded batch (data
        # shards gather DISTINCT rows — unlike the replicated psum, the
        # per-shard buffers don't collapse into one logical payload).
        if n_model <= 1:
            return
        rows_loc = rows_padded // n_data
        g, b = gather_lib.champion_gather_cost(rows_padded, n_model, gmode)
        if block_rows and rows_loc > block_rows:
            g *= rows_loc // block_rows  # one pair per scanned block
        counter.add(0, b, axis="model", gathers=g)

    if gstrategy.sharded_finalize:
        _fin = jax.jit(make_sharded_finalize(mesh, spherical=spherical,
                                             mode=gstrategy.mode))
        cost_fin = (
            gather_lib.finalize_gather_cost(k, d, (n_data,), gstrategy.mode)
            if n_data > 1 else (0, 0)
        )
        if gstrategy.quantized:
            # ONE persistent error-feedback residual slot per gathered
            # leaf: update() runs host-side once per pass, so a host cell
            # carries the residual across passes (the streamed twin of
            # the while_loop carry in kmeans_fit_sharded).
            gerr_cell = [zero_finalize_err(mesh, k, d)]

            def update(acc: _ShardedAcc, c):
                counter.add(0, cost_fin[1], axis="model",
                            gathers=cost_fin[0])
                new_c, shift, gerr_cell[0] = _fin(
                    acc.sums, acc.counts, c, gerr_cell[0]
                )
                return new_c, shift
        else:
            def update(acc: _ShardedAcc, c):
                counter.add(0, cost_fin[1], axis="model",
                            gathers=cost_fin[0])
                return _fin(acc.sums, acc.counts, c)
    else:
        @jax.jit
        def update(acc: _ShardedAcc, c):
            cf = c.astype(jnp.float32)
            new_c = jnp.where(
                acc.counts[:, None] > 0,
                acc.sums / jnp.maximum(acc.counts[:, None], 1.0),
                cf,
            )
            if spherical:
                new_c = _normalize(new_c)
            shift = jnp.max(jnp.linalg.norm(new_c - cf, axis=-1))
            return new_c, shift

    put_batch = _make_put_batch(mesh, pad_multiple, dtype, spherical)

    if deferred:
        _dred = make_sharded_deferred_reduce(mesh)
        pad_cell = [0.0]

        # donate_argnums: see reduce.make_deferred_fns — the deferred
        # accumulator is n_data× the reduced one; update it in place.
        accumulate = make_sharded_deferred_accumulate(
            stats_fn, _ShardedAcc, coarse=aspec.coarse
        )

        @jax.jit
        def _finalize_jit(acc: _ShardedAcc, c, n_pad) -> _ShardedAcc:
            sums, counts, sse = _dred(acc.sums, acc.counts, acc.sse)
            counts, sse = padding_correction(counts, sse, c, n_pad)
            return _ShardedAcc(sums, counts, sse)

        def finalize(acc, c):
            # Coarse stats mask padding internally — pad_cell stays 0 and
            # the correction is the identity there.
            n_pad, pad_cell[0] = pad_cell[0], 0.0
            counter.add(*cost_reduce)
            return _finalize_jit(acc, c, jnp.asarray(n_pad, jnp.float32))

        def _make_step(accum, gmode):
            def step_batch(acc, batch, c, fill=None):
                # _stage (below) handles raw AND Quarantined batches; rows
                # for resume accounting come from n_local (stream
                # geometry), which a quarantine verdict never changes.
                sb = (batch if isinstance(batch, spill_lib.StagedBatch)
                      else _stage(batch))
                xb, n_valid = sb.xb, sb.n_valid
                if fill is not None:
                    fill.add(xb, n_valid)
                _book_champion(xb.shape[0], gmode)
                if aspec.coarse:
                    fault_point("assign.refine")
                    _book_assign(xb.shape[0])
                    return (accum(acc, xb, c, jnp.asarray(n_valid)),
                            sb.n_local)
                pad_cell[0] += xb.shape[0] - n_valid
                return accum(acc, xb, c), sb.n_local
            return step_batch

        step_batch = _make_step(accumulate, gstrategy.mode)
        report_step = None
        if gstrategy.quantized:
            # Full-precision champion stats for the final reporting pass:
            # the reported SSE measures the centroids the quantized fit
            # produced, not the quantization noise of one more gather
            # (kmeans_fit_sharded's fp32 report-step convention).
            report_step = _make_step(
                make_sharded_deferred_accumulate(
                    make_sharded_stats(mesh, kernel, block_rows,
                                       reduce_data=False, assign_spec=aspec,
                                       gather="fp32"),
                    _ShardedAcc, coarse=aspec.coarse,
                ),
                "fp32",
            )

        def zero_acc() -> _ShardedAcc:
            # Sharding-first zeros: this runs once per pass and the
            # deferred accumulator is n_data× the reduced one — see
            # reduce.zero_deferred.
            return _ShardedAcc(
                sums=jnp.zeros(
                    (n_data, k, d), jnp.float32,
                    device=NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS,
                                                 None)),
                ),
                counts=jnp.zeros(
                    (n_data, k), jnp.float32,
                    device=NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)),
                ),
                sse=jnp.zeros(
                    (n_data,), jnp.float32,
                    device=NamedSharding(mesh, P(DATA_AXIS)),
                ),
            )

    else:
        finalize = None

        def _make_accumulate(sfn):
            @jax.jit
            def accumulate(acc: _ShardedAcc, x, c, n_valid) -> _ShardedAcc:
                if aspec.coarse:
                    # Padding masked inside the coarse stats — no
                    # correction.
                    sums, counts, sse = sfn(x, c, n_valid)
                else:
                    sums, counts, sse = sfn(x, c)
                    n_pad = x.shape[0] - n_valid
                    counts, sse = padding_correction(counts, sse, c, n_pad)
                return _ShardedAcc(
                    acc.sums + sums, acc.counts + counts, acc.sse + sse
                )
            return accumulate

        accumulate = _make_accumulate(stats_fn)

        def _make_step(accum, gmode):
            def step_batch(acc, batch, c, fill=None):
                sb = (batch if isinstance(batch, spill_lib.StagedBatch)
                      else _stage(batch))
                xb, n_valid = sb.xb, sb.n_valid
                if fill is not None:
                    fill.add(xb, n_valid)
                counter.add(*cost_reduce)
                _book_champion(xb.shape[0], gmode)
                if aspec.coarse:
                    fault_point("assign.refine")
                    _book_assign(xb.shape[0])
                return accum(acc, xb, c, n_valid), sb.n_local
            return step_batch

        step_batch = _make_step(accumulate, gstrategy.mode)
        report_step = None
        if gstrategy.quantized:
            # See the deferred branch: fp32 champion stats for the final
            # reporting pass only.
            report_step = _make_step(
                _make_accumulate(
                    make_sharded_stats(mesh, kernel, block_rows,
                                       reduce_data=True, assign_spec=aspec,
                                       gather="fp32")
                ),
                "fp32",
            )

        def zero_acc() -> _ShardedAcc:
            return _ShardedAcc(
                sums=jax.device_put(
                    jnp.zeros((k, d), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS, None)),
                ),
                counts=jax.device_put(
                    jnp.zeros((k,), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS)),
                ),
                sse=jnp.zeros((), jnp.float32),
            )

    def make_resident(cache):
        """(chunk, pass_only) over the HBM cache — the pass body mirrors
        the streamed accumulate/finalize ops EXACTLY (same per-batch stats
        in stream order, same one-per-pass deferred reduce and padding
        correction), which keeps resident results bit-exact.

        Bounded fits swap the per-batch stats for the zero-loss
        make_sharded_bounded_stats tower, threading the per-shard bounds
        carry (ShardedResidentBounds, the chunk's donated aux) batch for
        batch; the final reporting pass stays the EXACT tower (bounds
        must not drift during reporting)."""
        from tdc_tpu.data import device_cache as dc
        from tdc_tpu.models import resident as resident_lib

        if bounded:
            bstats = make_sharded_bounded_stats(mesh)

            def bounded_pass(c, aux, cache_):
                acc0 = _ShardedAcc(
                    sums=jax.lax.with_sharding_constraint(
                        jnp.zeros((k, d), jnp.float32),
                        NamedSharding(mesh, P(MODEL_AXIS, None)),
                    ),
                    counts=jax.lax.with_sharding_constraint(
                        jnp.zeros((k,), jnp.float32),
                        NamedSharding(mesh, P(MODEL_AXIS)),
                    ),
                    sse=jnp.zeros((), jnp.float32),
                )

                def one(a, ev, xb, nv, lab, lb):
                    sums, counts, sse, lab2, lb2, evb = bstats(
                        xb, c, aux.prev_c, lab, lb
                    )
                    counts, sse = padding_correction(
                        counts, sse, c, xb.shape[0] - nv
                    )
                    a = _ShardedAcc(
                        a.sums + sums, a.counts + counts, a.sse + sse
                    )
                    return a, ev + evb, (lab2, lb2)

                acc, ev = acc0, aux.ev
                lab_s = lb_s = None
                if cache_.stacked is not None:
                    def body(carry, xs):
                        a, ev = carry
                        xb, lab, lb = xs
                        a, ev, ys = one(a, ev, xb, cache_.nv_full,
                                        lab, lb)
                        return (a, ev), ys

                    (acc, ev), (lab_s, lb_s) = jax.lax.scan(
                        body, (acc, ev),
                        (cache_.stacked, aux.lab_s, aux.lb_s),
                    )
                acc, ev, (lab_t, lb_t) = one(
                    acc, ev, cache_.tail, cache_.nv_tail,
                    aux.lab_t, aux.lb_t,
                )
                new_aux = ShardedResidentBounds(
                    prev_c=c.astype(jnp.float32),
                    lab_s=lab_s, lb_s=lb_s,
                    lab_t=lab_t, lb_t=lb_t, ev=ev,
                )
                return acc, new_aux

            def exact_pass(c, aux, cache_):
                acc = _ShardedAcc(
                    sums=jax.lax.with_sharding_constraint(
                        jnp.zeros((k, d), jnp.float32),
                        NamedSharding(mesh, P(MODEL_AXIS, None)),
                    ),
                    counts=jax.lax.with_sharding_constraint(
                        jnp.zeros((k,), jnp.float32),
                        NamedSharding(mesh, P(MODEL_AXIS)),
                    ),
                    sse=jnp.zeros((), jnp.float32),
                )

                def one(a, xb, wb, nv):
                    sums, counts, sse = stats_fn(xb, c)
                    counts, sse = padding_correction(
                        counts, sse, c, xb.shape[0] - nv
                    )
                    return _ShardedAcc(
                        a.sums + sums, a.counts + counts, a.sse + sse
                    )

                return dc.scan_cache(acc, cache_, one, False), aux

            def update_fn(acc, c):
                new_c, shift = update(acc, c)
                return new_c, shift, acc.sse

            chunk = resident_lib.make_resident_chunk(
                bounded_pass, update_fn, float(tol), chunk_iters
            )
            return chunk, jax.jit(exact_pass)

        def pass_fn(c, aux, cache_):
            if deferred:
                acc = _ShardedAcc(
                    sums=jax.lax.with_sharding_constraint(
                        jnp.zeros((n_data, k, d), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None)),
                    ),
                    counts=jax.lax.with_sharding_constraint(
                        jnp.zeros((n_data, k), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)),
                    ),
                    sse=jax.lax.with_sharding_constraint(
                        jnp.zeros((n_data,), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS)),
                    ),
                )

                def one(a, xb, wb, nv):
                    if aspec.coarse:
                        sums, counts, sse = stats_fn(xb, c, nv)
                    else:
                        sums, counts, sse = stats_fn(xb, c)
                    return _ShardedAcc(
                        a.sums + sums, a.counts + counts, a.sse + sse
                    )

                acc = dc.scan_cache(acc, cache_, one, False)
                sums, counts, sse = _dred(acc.sums, acc.counts, acc.sse)
                if not aspec.coarse:  # coarse masks padding internally
                    counts, sse = padding_correction(
                        counts, sse, c, dc.cache_pad_rows(cache_)
                    )
                return _ShardedAcc(sums, counts, sse), aux

            acc = _ShardedAcc(
                sums=jax.lax.with_sharding_constraint(
                    jnp.zeros((k, d), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS, None)),
                ),
                counts=jax.lax.with_sharding_constraint(
                    jnp.zeros((k,), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS)),
                ),
                sse=jnp.zeros((), jnp.float32),
            )

            def one(a, xb, wb, nv):
                if aspec.coarse:
                    sums, counts, sse = stats_fn(xb, c, nv)
                else:
                    sums, counts, sse = stats_fn(xb, c)
                    counts, sse = padding_correction(
                        counts, sse, c, xb.shape[0] - nv
                    )
                return _ShardedAcc(
                    a.sums + sums, a.counts + counts, a.sse + sse
                )

            return dc.scan_cache(acc, cache_, one, False), aux

        def update_fn(acc, c):
            new_c, shift = update(acc, c)
            return new_c, shift, acc.sse

        chunk = resident_lib.make_resident_chunk(
            pass_fn, update_fn, float(tol), chunk_iters
        )
        return chunk, jax.jit(pass_fn)

    def resident_cost(cache):
        if deferred:
            return cost_reduce
        return (cost_reduce[0] * cache.n_batches,
                cost_reduce[1] * cache.n_batches)

    def _stage(batch):
        # Quarantined (data/ingest.py): stage the all-padding zero-mass
        # batch — zero rows, zero valid count; n_local keeps the raw
        # stream row count for resume accounting.
        if isinstance(batch, ingest_lib.Quarantined):
            xb, n_valid = put_batch(batch.x)
            return spill_lib.StagedBatch(xb, 0, n_valid)
        xb, n_valid = put_batch(batch)
        return spill_lib.StagedBatch(xb, n_valid, n_valid)

    def _assign_pass_cost(cache):
        # EXACT per-pass tile tallies from the cache's batch geometry
        # (the cached batches replay the streamed batches shape for
        # shape; subk.assign_cost is geometry-only) — every (data,
        # model) shard pair refines its own blocks against its own
        # tiles, so the logical tally scales by both axes.
        probed = total = 0
        shapes = ([cache.stacked.shape[1]] * cache.stacked.shape[0]
                  if cache.stacked is not None else [])
        shapes.append(cache.tail.shape[0])
        for rows in shapes:
            p, t = subk_lib.assign_cost(rows // n_data, aspec)
            probed += p * n_data * n_model
            total += t * n_data * n_model
        return probed, total

    make_aux = None
    if bounded:
        from tdc_tpu.testing.faults import fault_point as _fp

        def make_aux(cache):
            with trace.span("bounds_init", kind="hamerly"):
                _fp("assign.bounds_recompute")
                return init_resident_sharded_bounds(mesh, cache, c)

    loop_batches, h2d = spill_lib.wrap_stream(r_plan, guard, _stage)
    loop_prefetch = prefetch if h2d is None else 0
    # Per-fit timeline (obs/trace): None unless tracing is enabled.
    tl = trace.begin_fit("streamed_kmeans_fit_sharded", k=k, d=d)

    (c, n_iter, start_iter, shift, converged, history, final_acc, res_p,
     res_aux) = (
        _sharded_stream_loop(
            batches=loop_batches, prefetch=loop_prefetch, ckpt=ckpt,
            ckpt_dir=ckpt_dir,
            ckpt_every=ckpt_every, ckpt_every_batches=ckpt_every_batches,
            max_iters=max_iters, tol=tol, c=c, state=state, put_acc=put_acc,
            zero_acc=zero_acc, step_batch=step_batch, update=update,
            acc_cost=lambda acc: acc.sse, finalize=finalize,
            fill=r_builder, make_resident=make_resident,
            resident_cost=resident_cost, chunk_iters=chunk_iters,
            mesh=mesh, gang=gang, counter=counter,
            make_aux=make_aux, assign_counter=assign_counter,
            assign_pass_cost=_assign_pass_cost, report_step=report_step,
        )
    )
    bounds_report = None
    if bounded:
        from tdc_tpu.ops import bounds as bounds_lib

        if isinstance(res_aux, ShardedResidentBounds):
            bcounter = bounds_lib.BoundsCounter(
                _mirror=bounds_lib.GLOBAL_BOUNDS
            )
            rows = ((res_aux.lab_s.shape[0] * res_aux.lab_s.shape[1]
                     if res_aux.lab_s is not None else 0)
                    + res_aux.lab_t.shape[0])
            # res_p counts the final reporting pass, which runs the
            # EXACT tower — only res_p - 1 passes went through bounds.
            bcounter.add(float(np.asarray(res_aux.ev).sum()),
                         float(rows) * float(k) * max(res_p - 1, 0))
            bounds_report = bounds_lib.report(
                bounds_lib.BoundsSpec(kind="hamerly"), bcounter
            )
        else:
            # The plan said resident but the fill never completed: the
            # fit streamed exact — still zero-loss, but say so (the 1-D
            # driver's cache_unfilled rule).
            from tdc_tpu.utils.structlog import emit

            emit("bounds_fallback", label="streamed_kmeans_fit_sharded",
                 requested=assign, residency=residency,
                 reason="cache_unfilled",
                 detail="the HBM cache fill did not complete; the fit "
                        "ran exact streamed assignment")
    sse = float(final_acc.sse)
    # The fit is done: cancel the pass-persistent ring's speculative
    # next-pass staging and join its pool (no-op off the spill tier).
    spill_lib.release(loop_batches)
    return KMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        sse=jnp.asarray(sse, jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
        history=_history_array(history),
        n_iter_run=n_iter - start_iter,
        comms=reduce_lib.CommsReport(
            strategy=strategy.label(), reduces=counter.reduces,
            logical_bytes=counter.logical_bytes,
            passes=(n_iter - start_iter) + 1,
            data_bytes=counter.data_bytes, model_bytes=counter.model_bytes,
            gathers=counter.gathers,
        ),
        h2d=None if h2d is None else h2d.report(r_plan.spill_slots),
        ingest=guard.report(),
        assign=(None if assign_counter is None
                else subk_lib.report(aspec, assign_counter)),
        bounds=bounds_report,
        timeline=trace.end_fit(tl),
    )


class _ShardedFuzzyAcc(NamedTuple):
    wsums: jax.Array  # (K, d) — K-sharded
    weights: jax.Array  # (K,) — K-sharded
    obj: jax.Array  # () — replicated


def streamed_fuzzy_fit_sharded(
    batches: Callable[[], Iterable],
    k: int,
    d: int,
    mesh: Mesh,
    *,
    m: float = 2.0,
    init,
    key=None,
    max_iters: int = 20,
    tol: float = 1e-4,
    kernel: str = "xla",
    block_rows: int = 0,
    dtype=None,
    prefetch: int = 0,
    ckpt_dir: str | None = None,
    ckpt_every: int = 1,
    ckpt_every_batches: int | None = None,
    reduce="per_batch",
    residency: str = "stream",
    ingest=None,
    gather: str = "fp32",
):
    """Exact out-of-core Fuzzy C-Means under the 2-D (data × model) layout —
    the large-K regime of the reference's fastest algorithm, streamed: each
    batch's K-sharded (u^m-weighted sums, weights, objective) accumulate
    on-device across the pass and the centroid state never exists unsharded.
    Soft memberships make this exact with no mini-batch caveat: the M-step
    is a pure ratio of the accumulated sufficient statistics.

    Same contracts as streamed_kmeans_fit_sharded: `batches` is a zero-arg
    callable yielding (rows, d) arrays per iteration; `dtype` converts
    host-side (bf16 MXU fast path; stats stay f32); ckpt_dir enables the
    models/streaming checkpoint/resume contract (bit-identical resume,
    mid-pass accumulator saves with ckpt_every_batches; single-process
    meshes only — the I/O gathers K-sharded state to this host).
    kernel='pallas' runs the two-pass VMEM kernels inside each shard.
    reduce="per_pass" defers the data-axis stats reduce to once per
    iteration (streamed_kmeans_fit_sharded's contract; the per-point
    membership-normalizer psum still runs per batch).
    residency="hbm"/"auto" caches the padded batches in HBM during
    iteration 1 and runs iterations 2..N as a compiled on-device chunk
    loop (streamed_kmeans_fit_sharded's contract). ingest= is the
    hardened-ingest policy (retry + zero-mass quarantine + bounded-loss
    accounting; streamed_kmeans_fit_sharded's contract).
    gather="fp32_sharded"/"bf16"/"int8" routes the centroid update
    through the data-axis-sharded finalize (streamed_kmeans_fit_sharded's
    contract; fuzzy has no champion gathers — its memberships reduce via
    the per-point normalizer psum — so only the finalize exchange rides
    the gather= wire).
    """
    from tdc_tpu.models.fuzzy import FuzzyCMeansResult
    from tdc_tpu.models.streaming import (
        _StreamCheckpointer,
        _first_for_init,
        _fuzzy_example,
        _history_array,
        _mesh_layout,
        _reduce_plan,
    )
    from tdc_tpu.parallel import reduce as reduce_lib

    spec = MeshSpec.of(mesh)
    n_data, n_model = spec.n_data, spec.n_model
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    if m <= 1.0:
        raise ValueError(f"fuzzifier m must be > 1, got {m}")
    from tdc_tpu.ops.pallas_kernels import resolve_kernel

    kernel = resolve_kernel(kernel, k=k // n_model, d=d,
                            itemsize=_stream_kernel_itemsize(batches, dtype),
                            model="fuzzy_sharded",
                            label="streamed_fuzzy_fit_sharded")
    strategy = reduce_lib.resolve_reduce(reduce)
    gstrategy = plan_gather(gather, mesh, k,
                            ckpt_dir=ckpt_dir,
                            ckpt_every_batches=ckpt_every_batches or 0,
                            residency=residency)
    deferred, _ = _reduce_plan(strategy, mesh, ckpt_dir, ckpt_every_batches,
                               allow_quantize=False)
    gang = spec.gang
    if ckpt_dir is not None and gang:
        raise ValueError(
            "K-sharded checkpointing gathers state to one host and supports "
            "single-process meshes only (multi-process gang checkpointing "
            "of K-sharded state is not implemented)"
        )
    eps = 1e-9
    pad_multiple = n_data * max(block_rows, 1)

    # shard_model deliberately not validated: the save is gathered +
    # layout-manifested, portable across (data, model) splits (see
    # streamed_kmeans_fit_sharded).
    ckpt = _StreamCheckpointer(
        ckpt_dir, k, d,
        params={"m": float(m)},
        acc_map={"acc_wsums": "wsums", "acc_weights": "weights",
                 "acc_obj": "obj"},
        key=key,
        spec=spec,
    )
    guard = ingest_lib.guard_stream(batches, ingest, d=d,
                                    label="streamed_fuzzy_fit_sharded")
    if gang and getattr(guard, "disjoint_shards", False):
        raise ValueError(
            "streamed_fuzzy_fit_sharded: disjoint-shard manifest streams "
            "in a multi-process gang are not supported on the K-sharded "
            "driver — its padding correction folds n_valid as a replicated "
            "scalar, and disjoint shards quarantine per HOST, which would "
            "fork the replicated state; use the 1-D streamed driver "
            "(streamed_fuzzy_fit) for gang object-store ingestion"
        )
    state = ckpt.restore(_ShardedFuzzyAcc, None)
    if state.cursor:
        _reduce_plan(strategy, mesh, ckpt_dir, ckpt_every_batches,
                     cursor=state.cursor, allow_quantize=False)

    def put_c(t):
        return jax.device_put(t, NamedSharding(mesh, P(MODEL_AXIS, None)))

    if state.centroids is not None:
        c = reshard_lib.redistribute(
            jnp.asarray(state.centroids, jnp.float32), state.layout, spec,
            place=put_c,
        )
    else:
        if not hasattr(init, "shape"):
            first = np.asarray(_first_for_init(guard))
            init = _resolve_init_sharded(first, k, init, key)
        c = jnp.asarray(init, jnp.float32)
        if c.shape != (k, d):
            raise ValueError(f"init shape {c.shape} != {(k, d)}")
        c = put_c(c)

    def put_acc(acc):
        return _ShardedFuzzyAcc(
            wsums=jax.device_put(
                acc.wsums, NamedSharding(mesh, P(MODEL_AXIS, None))
            ),
            weights=jax.device_put(
                acc.weights, NamedSharding(mesh, P(MODEL_AXIS))
            ),
            obj=acc.obj,
        )

    stats_fn = make_sharded_fuzzy_stats(
        mesh, m, eps, block_rows=block_rows, kernel=kernel,
        reduce_data=not deferred,
    )
    r_plan, r_builder = _plan_sharded_residency(
        residency, batches, k, d, spec,
        pad_multiple=pad_multiple, kernel=kernel, dtype=dtype,
        cursor=state.cursor, label="streamed_fuzzy_fit_sharded",
        mid_pass_ckpt=ckpt_every_batches is not None,
    )
    chunk_iters = _chunk_iters_for(ckpt_dir, ckpt_every)
    counter = reduce_lib.CommsCounter(_mirror=reduce_lib.GLOBAL_COMMS)
    cost_reduce = (
        reduce_lib.tree_reduce_cost(_fuzzy_example(k, d), (DATA_AXIS,))
        if n_data > 1 else (0, 0)
    )

    if gstrategy.sharded_finalize:
        _fin = jax.jit(make_sharded_finalize(mesh, mode=gstrategy.mode,
                                             fuzzy=True))
        cost_fin = (
            gather_lib.finalize_gather_cost(k, d, (n_data,), gstrategy.mode)
            if n_data > 1 else (0, 0)
        )
        if gstrategy.quantized:
            # Host-cell error-feedback residual, one slot per gathered
            # leaf (see streamed_kmeans_fit_sharded).
            gerr_cell = [zero_finalize_err(mesh, k, d)]

            def update(acc: _ShardedFuzzyAcc, c):
                counter.add(0, cost_fin[1], axis="model",
                            gathers=cost_fin[0])
                new_c, shift, gerr_cell[0] = _fin(
                    acc.wsums, acc.weights, c, gerr_cell[0]
                )
                return new_c, shift
        else:
            def update(acc: _ShardedFuzzyAcc, c):
                counter.add(0, cost_fin[1], axis="model",
                            gathers=cost_fin[0])
                return _fin(acc.wsums, acc.weights, c)
    else:
        @jax.jit
        def update(acc: _ShardedFuzzyAcc, c):
            new_c = acc.wsums / jnp.maximum(acc.weights[:, None], 1e-12)
            shift = jnp.max(jnp.linalg.norm(new_c - c, axis=-1))
            return new_c, shift

    put_batch = _make_put_batch(mesh, pad_multiple, dtype)

    if deferred:
        _dred = make_sharded_fuzzy_deferred_reduce(mesh)
        pad_cell = [0.0]
        cast_cell = ["float32"]

        # donate_argnums: see reduce.make_deferred_fns.
        accumulate = make_sharded_deferred_accumulate(
            stats_fn, _ShardedFuzzyAcc
        )

        @partial(jax.jit, static_argnames=("cast",))
        def _finalize_jit(acc, c, n_pad, cast=None):
            wsums, weights, obj = _dred(acc.wsums, acc.weights, acc.obj)
            weights, obj = _fuzzy_pad_correction(
                weights, obj, c, n_pad, m, eps,
                cast_dtype=jnp.dtype(cast) if cast else None,
            )
            return _ShardedFuzzyAcc(wsums, weights, obj)

        def finalize(acc, c):
            n_pad, pad_cell[0] = pad_cell[0], 0.0
            counter.add(*cost_reduce)
            return _finalize_jit(
                acc, c, jnp.asarray(n_pad, jnp.float32),
                cast=cast_cell[0] if kernel == "pallas" else None,
            )

        def step_batch(acc, batch, c, fill=None):
            sb = (batch if isinstance(batch, spill_lib.StagedBatch)
                  else _stage(batch))
            xb, n_valid = sb.xb, sb.n_valid
            if fill is not None:
                fill.add(xb, n_valid)
            pad_cell[0] += xb.shape[0] - n_valid
            cast_cell[0] = str(xb.dtype)
            return accumulate(acc, xb, c), sb.n_local

        def zero_acc() -> _ShardedFuzzyAcc:
            # Sharding-first zeros (see reduce.zero_deferred).
            return _ShardedFuzzyAcc(
                wsums=jnp.zeros(
                    (n_data, k, d), jnp.float32,
                    device=NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS,
                                                 None)),
                ),
                weights=jnp.zeros(
                    (n_data, k), jnp.float32,
                    device=NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)),
                ),
                obj=jnp.zeros(
                    (n_data * n_model,), jnp.float32,
                    device=NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS))),
                ),
            )

    else:
        finalize = None

        @jax.jit
        def accumulate(acc: _ShardedFuzzyAcc, x, c,
                       n_valid) -> _ShardedFuzzyAcc:
            wsums, weights, obj = stats_fn(x, c)
            n_pad = x.shape[0] - n_valid
            weights, obj = _fuzzy_pad_correction(
                weights, obj, c, n_pad, m, eps,
                cast_dtype=x.dtype if kernel == "pallas" else None,
            )
            return _ShardedFuzzyAcc(
                acc.wsums + wsums, acc.weights + weights, acc.obj + obj
            )

        def step_batch(acc, batch, c, fill=None):
            sb = (batch if isinstance(batch, spill_lib.StagedBatch)
                  else _stage(batch))
            xb, n_valid = sb.xb, sb.n_valid
            if fill is not None:
                fill.add(xb, n_valid)
            counter.add(*cost_reduce)
            return accumulate(acc, xb, c, n_valid), sb.n_local

        def zero_acc() -> _ShardedFuzzyAcc:
            return _ShardedFuzzyAcc(
                wsums=jax.device_put(
                    jnp.zeros((k, d), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS, None)),
                ),
                weights=jax.device_put(
                    jnp.zeros((k,), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS)),
                ),
                obj=jnp.zeros((), jnp.float32),
            )

    def make_resident(cache):
        """(chunk, pass_only) over the HBM cache — mirrors the streamed
        accumulate/finalize op order exactly (bit-exact contract; see
        streamed_kmeans_fit_sharded's make_resident)."""
        from tdc_tpu.data import device_cache as dc
        from tdc_tpu.models import resident as resident_lib

        def pass_fn(c, aux, cache_):
            cast = (jnp.dtype(str(cache_.tail.dtype))
                    if kernel == "pallas" else None)
            if deferred:
                acc = _ShardedFuzzyAcc(
                    wsums=jax.lax.with_sharding_constraint(
                        jnp.zeros((n_data, k, d), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS, None)),
                    ),
                    weights=jax.lax.with_sharding_constraint(
                        jnp.zeros((n_data, k), jnp.float32),
                        NamedSharding(mesh, P(DATA_AXIS, MODEL_AXIS)),
                    ),
                    obj=jax.lax.with_sharding_constraint(
                        jnp.zeros((n_data * n_model,), jnp.float32),
                        NamedSharding(mesh, P((DATA_AXIS, MODEL_AXIS))),
                    ),
                )

                def one(a, xb, wb, nv):
                    wsums, weights, obj = stats_fn(xb, c)
                    return _ShardedFuzzyAcc(
                        a.wsums + wsums, a.weights + weights, a.obj + obj
                    )

                acc = dc.scan_cache(acc, cache_, one, False)
                wsums, weights, obj = _dred(acc.wsums, acc.weights, acc.obj)
                weights, obj = _fuzzy_pad_correction(
                    weights, obj, c, dc.cache_pad_rows(cache_), m, eps,
                    cast_dtype=cast,
                )
                return _ShardedFuzzyAcc(wsums, weights, obj), aux

            acc = _ShardedFuzzyAcc(
                wsums=jax.lax.with_sharding_constraint(
                    jnp.zeros((k, d), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS, None)),
                ),
                weights=jax.lax.with_sharding_constraint(
                    jnp.zeros((k,), jnp.float32),
                    NamedSharding(mesh, P(MODEL_AXIS)),
                ),
                obj=jnp.zeros((), jnp.float32),
            )

            def one(a, xb, wb, nv):
                wsums, weights, obj = stats_fn(xb, c)
                weights, obj = _fuzzy_pad_correction(
                    weights, obj, c, xb.shape[0] - nv, m, eps,
                    cast_dtype=cast,
                )
                return _ShardedFuzzyAcc(
                    a.wsums + wsums, a.weights + weights, a.obj + obj
                )

            return dc.scan_cache(acc, cache_, one, False), aux

        def update_fn(acc, c):
            new_c, shift = update(acc, c)
            return new_c, shift, acc.obj

        chunk = resident_lib.make_resident_chunk(
            pass_fn, update_fn, float(tol), chunk_iters
        )
        return chunk, jax.jit(pass_fn)

    def resident_cost(cache):
        if deferred:
            return cost_reduce
        return (cost_reduce[0] * cache.n_batches,
                cost_reduce[1] * cache.n_batches)

    def _stage(batch):
        # Quarantined: the all-padding zero-mass batch (see
        # streamed_kmeans_fit_sharded._stage).
        if isinstance(batch, ingest_lib.Quarantined):
            xb, n_valid = put_batch(batch.x)
            return spill_lib.StagedBatch(xb, 0, n_valid)
        xb, n_valid = put_batch(batch)
        return spill_lib.StagedBatch(xb, n_valid, n_valid)

    loop_batches, h2d = spill_lib.wrap_stream(r_plan, guard, _stage)
    loop_prefetch = prefetch if h2d is None else 0
    # Per-fit timeline (obs/trace): None unless tracing is enabled.
    tl = trace.begin_fit("streamed_fuzzy_fit_sharded", k=k, d=d)

    c, n_iter, start_iter, shift, converged, history, final_acc, _, _ = (
        _sharded_stream_loop(
            batches=loop_batches, prefetch=loop_prefetch, ckpt=ckpt,
            ckpt_dir=ckpt_dir,
            ckpt_every=ckpt_every, ckpt_every_batches=ckpt_every_batches,
            max_iters=max_iters, tol=tol, c=c, state=state, put_acc=put_acc,
            zero_acc=zero_acc, step_batch=step_batch, update=update,
            acc_cost=lambda acc: acc.obj, finalize=finalize,
            fill=r_builder, make_resident=make_resident,
            resident_cost=resident_cost, chunk_iters=chunk_iters,
            mesh=mesh, gang=gang, counter=counter,
        )
    )
    # The final pass's objective is measured at the RETURNED centroids.
    obj = float(final_acc.obj)
    # Cancel the pass-persistent ring's speculation and join its pool
    # (no-op off the spill tier).
    spill_lib.release(loop_batches)
    return FuzzyCMeansResult(
        centroids=c,
        n_iter=jnp.asarray(n_iter, jnp.int32),
        objective=jnp.asarray(obj, jnp.float32),
        shift=jnp.asarray(shift, jnp.float32),
        converged=jnp.asarray(converged),
        history=_history_array(history),
        n_iter_run=n_iter - start_iter,
        comms=reduce_lib.CommsReport(
            strategy=strategy.label(), reduces=counter.reduces,
            logical_bytes=counter.logical_bytes,
            passes=(n_iter - start_iter) + 1,
            data_bytes=counter.data_bytes, model_bytes=counter.model_bytes,
            gathers=counter.gathers,
        ),
        h2d=None if h2d is None else h2d.report(r_plan.spill_slots),
        ingest=guard.report(),
        timeline=trace.end_fit(tl),
    )


class _ShardedGMMAcc(NamedTuple):
    ll: jax.Array  # () — replicated
    nk: jax.Array  # (K,) — K-sharded
    sx: jax.Array  # (K, d) — K-sharded
    sxx: jax.Array  # (K, d) — K-sharded


def streamed_gmm_fit_sharded(
    batches: Callable[[], Iterable],
    k: int,
    d: int,
    mesh: Mesh,
    *,
    init="kmeans++",
    key=None,
    max_iters: int = 100,
    tol: float = 1e-3,
    reg_covar: float = 1e-6,
    block_rows: int = 0,
    prefetch: int = 0,
    dtype=None,
    ckpt_dir: str | None = None,
    ckpt_every: int = 5,
):
    """Exact out-of-core diag-covariance GMM EM under the 2-D (data ×
    model) layout: each batch's K-sharded E-step sufficient statistics
    (ll, nk, Σr·x, Σr·x²) accumulate on-device across the pass, the
    M-step is a pure ratio of the totals, and the component state never
    exists unsharded — the soft analog of streamed_kmeans_fit_sharded,
    completing the --shard_k streaming story for all three methods.

    Same batch contract as the other sharded streamed drivers. Seeding
    mirrors gmm_fit_sharded: a host subsample of the stream's first
    ≤65536 rows — read across as many leading batches as that takes, so
    streamed and in-memory fits see the SAME prefix and follow identical
    trajectories (init='kmeans' is the unsharded mode and is rejected).
    Convergence is
    the sklearn lower_bound_ criterion (mean log-likelihood gain ≤ tol
    after iteration 2), which requires the per-iteration ll on host —
    the GMM drivers are inherently sync-per-iteration, so there is no
    deferred-fetch mode here.

    ckpt_dir: per-iteration checkpoint/resume with streamed_gmm_fit's
    contract (means + variances + weights + ll persisted every
    `ckpt_every` iterations and at the end; restore validates
    k/d/reg_covar and the shard layout; a finished checkpoint's no-op
    resume reuses its stored final ll instead of re-streaming).
    Iteration-granular only — an interrupted pass is re-run — and
    single-process meshes only (the I/O gathers K-sharded state to this
    host, the streamed_kmeans_fit_sharded rule).
    """
    from tdc_tpu.models.gmm import (
        GMMResult,
        _moments_from_hard_assign,
    )
    from tdc_tpu.models.streaming import _mesh_layout, _run_pass

    n_data = int(mesh.devices.shape[0])
    n_model = int(mesh.devices.shape[1])
    if k % n_model != 0:
        raise ValueError(f"K={k} not divisible by model axis {n_model}")
    if isinstance(init, str) and init == "kmeans":
        raise ValueError(
            "streamed_gmm_fit_sharded seeds from a host subsample; "
            "init='kmeans' (a full K-Means pre-fit) is the unsharded mode"
        )
    if ckpt_dir is not None and _mesh_layout(mesh)[0] > 1:
        raise ValueError(
            "K-sharded checkpointing gathers state to one host and supports "
            "single-process meshes only (multi-process gang checkpointing "
            "of K-sharded state is not implemented)"
        )
    pad_multiple = n_data * max(block_rows, 1)

    put_k = lambda a: jax.device_put(
        a, NamedSharding(mesh, P(MODEL_AXIS) if a.ndim == 1
                         else P(MODEL_AXIS, None))
    )
    start_iter = 0
    prev_ll = -float("inf")
    saved_final_ll = None
    resume_converged = False
    means = variances = weights = None
    if ckpt_dir is not None:
        from tdc_tpu.utils.checkpoint import restore_checkpoint

        saved = restore_checkpoint(ckpt_dir)
        if saved is not None:
            if saved.meta.get("model") != "gmm_sharded":
                raise ValueError(
                    f"checkpoint in {ckpt_dir} is not a K-sharded GMM "
                    "checkpoint"
                )
            if (int(saved.meta.get("k")) != k
                    or int(saved.meta.get("d")) != d
                    or float(saved.meta.get("reg")) != float(reg_covar)
                    or int(saved.meta.get("shard_model")) != n_model):
                raise ValueError(
                    f"checkpoint in {ckpt_dir} was written with "
                    f"k={saved.meta.get('k')}, d={saved.meta.get('d')}, "
                    f"reg_covar={saved.meta.get('reg')}, "
                    f"shard_model={saved.meta.get('shard_model')} — "
                    "refusing to mix state"
                )
            means = jnp.asarray(saved.centroids, jnp.float32)
            variances = jnp.asarray(saved.meta["variances"], jnp.float32)
            weights = jnp.asarray(saved.meta["weights"], jnp.float32)
            start_iter = saved.n_iter
            prev_ll = float(saved.meta.get("ll", -float("inf")))
            saved_final_ll = saved.meta.get("final_ll")
            resume_converged = bool(
                np.asarray(saved.meta.get("converged", False))
            )
    if means is None:
        # Seed from the stream's first ≤65536 rows — the SAME prefix
        # gmm_fit_sharded's host subsample sees on the equivalent in-memory
        # array, so the two fits follow identical trajectories (a
        # single-batch sample gave different init moments and measurably
        # divergent EM).
        chunks, got = [], 0
        for b in batches():
            # Snapshot stash (np.array copies): a stream may reuse its
            # batch buffer between yields, so raw references held across
            # iterations would alias to the last read.
            chunks.append(np.array(b, np.float32))  # tdclint: disable=TDC002 — deliberate host snapshot (streams may reuse batch buffers); the seeding scan breaks at 65536 rows
            got += int(getattr(b, "shape", (len(b),))[0])
            if got >= 65536:
                break
        first = np.concatenate(chunks)[:65536]
        means = _resolve_init_sharded(first, k, init, key)
        if means.shape != (k, d):
            raise ValueError(
                f"init means shape {means.shape} != {(k, d)} — either the "
                f"stream's rows ({first.shape[1]}-wide) don't match d={d}, "
                "or an explicit init array has the wrong feature width"
            )
        variances, weights = _moments_from_hard_assign(
            jnp.asarray(first, jnp.float32), means, reg_covar
        )
    means, variances, weights = map(put_k, (means, variances, weights))

    def save_ckpt(n_iter, ll, done, final_ll=None):
        from tdc_tpu.utils.checkpoint import ClusterState, save_checkpoint

        save_checkpoint(
            ckpt_dir,
            ClusterState(
                centroids=np.asarray(means), n_iter=n_iter, key=None,
                batch_cursor=0,
                meta={
                    "model": "gmm_sharded", "k": k, "d": d,
                    "reg": float(reg_covar), "shard_model": n_model,
                    "variances": np.asarray(variances),
                    "weights": np.asarray(weights),
                    "ll": float(ll), "converged": bool(done),
                    **({"final_ll": float(final_ll)}
                       if final_ll is not None else {}),
                },
            ),
            step=n_iter,
            # The gate above restricts ckpt to single-process meshes, so
            # this host is the sole writer even inside a jax.distributed
            # runtime (gang=None would infer gang mode from
            # jax.process_count() and deadlock on the barrier).
            gang=False,
        )

    stats_fn = make_sharded_gmm_stats(mesh, block_rows=block_rows)

    @jax.jit
    def accumulate(acc, x, means, variances, weights, n_valid):
        ll, nk, sx, sxx = stats_fn(x, means, variances, weights)
        n_pad = x.shape[0] - n_valid
        nk, ll = _gmm_pad_correction(
            nk, ll, means, variances, weights, n_pad, d
        )
        return _ShardedGMMAcc(acc.ll + ll, acc.nk + nk, acc.sx + sx,
                              acc.sxx + sxx)

    @jax.jit
    def m_step(acc, n_rows):
        # The single shared M-step (models/gmm._m_step — its floors and
        # variance clamp must never drift across drivers).
        from tdc_tpu.models.gmm import _m_step

        new_means, new_vars, new_w = _m_step(
            acc.nk, acc.sx, acc.sxx, n_rows, reg_covar
        )
        return new_means, new_vars, new_w, acc.ll / n_rows

    def zero_acc():
        return _ShardedGMMAcc(
            ll=jnp.zeros((), jnp.float32),
            nk=jax.device_put(jnp.zeros((k,), jnp.float32),
                              NamedSharding(mesh, P(MODEL_AXIS))),
            sx=jax.device_put(jnp.zeros((k, d), jnp.float32),
                              NamedSharding(mesh, P(MODEL_AXIS, None))),
            sxx=jax.device_put(jnp.zeros((k, d), jnp.float32),
                               NamedSharding(mesh, P(MODEL_AXIS, None))),
        )

    put_batch = _make_put_batch(mesh, pad_multiple, dtype)

    rows_seen = [0]

    def full_pass(means, variances, weights):
        rows_seen[0] = 0

        def pass_step(acc, batch):
            maybe_beat()  # supervised-gang liveness
            xb, n_valid = put_batch(batch)
            rows_seen[0] += n_valid
            return accumulate(acc, xb, means, variances, weights,
                              n_valid), n_valid

        return _run_pass(batches, prefetch, zero_acc, pass_step)

    ll = prev_ll
    n_iter = start_iter
    converged = resume_converged
    iters = (
        () if resume_converged else range(start_iter + 1, max_iters + 1)
    )
    for n_iter in iters:
        acc = full_pass(means, variances, weights)
        means, variances, weights, ll_dev = m_step(acc, rows_seen[0])
        ll = float(ll_dev)
        done = n_iter > 1 and ll - prev_ll <= tol
        if ckpt_dir is not None and (done or n_iter % ckpt_every == 0
                                     or n_iter == max_iters):
            save_ckpt(n_iter, ll, done)
        if done:
            converged = True
            break
        prev_ll = ll
    resume_done = resume_converged or start_iter >= max_iters
    if resume_done and saved_final_ll is not None:
        # No-op resume of a finished checkpoint: reuse its stored final ll
        # instead of re-streaming the dataset (streamed_gmm_fit's rule).
        final_ll = float(saved_final_ll)
    else:
        # Final ll of the RETURNED parameters (the loop's ll is pre-update
        # — parity with streamed_gmm_fit).
        acc = full_pass(means, variances, weights)
        final_ll = float(acc.ll) / max(rows_seen[0], 1)
        if ckpt_dir is not None and (converged or n_iter >= max_iters):
            save_ckpt(n_iter, ll, converged, final_ll=final_ll)
    return GMMResult(
        means=means,
        variances=variances,
        weights=weights,
        log_likelihood=jnp.asarray(final_ll, jnp.float32),
        n_iter=jnp.asarray(n_iter, jnp.int32),
        converged=jnp.asarray(converged),
        covariance_type="diag",
        n_iter_run=n_iter - start_iter,
    )
