"""Multi-host execution: jax.distributed over DCN with per-host data shards.

The reference is strictly single-process in-graph replication — no
ClusterSpec/gRPC/MPI/Horovod anywhere (SURVEY.md §2.3 "Multi-host" row; its
only orchestration is subprocess.Popen per experiment). This module is the
pod-scale path: one process per host, `jax.distributed.initialize` for the
coordinator handshake, a mesh spanning all hosts' devices, and
`jax.make_array_from_process_local_data` to build the global sharded points
array from host-local shards (each host loads only its slice — no single-host
full-dataset staging, the reference's anti-pattern at
scripts/distribuitedClustering.py:273).

Everything downstream (models/, parallel/collectives.py, sharded_k.py) is
written against global arrays + meshes and works unchanged on a multi-host
mesh: psum rides ICI within a slice and DCN across slices, placed by XLA.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdc_tpu.parallel.mesh import DATA_AXIS


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Initialize jax.distributed (no-op when single-process / already up).

    Args come from the environment in managed deployments (TPU VMs autodetect);
    pass explicitly for manual clusters. Returns (process_index, num_processes).

    Also the workers' persistent-compile-cache hook: a $TDC_COMPILE_CACHE
    inherited from the supervisor (or the deployment env) is enabled here,
    so a gang relaunched after preemption deserializes its fit executables
    instead of recompiling (utils/compile_cache).
    """
    from tdc_tpu.utils.compile_cache import enable_from_env

    enable_from_env()
    if num_processes is not None and num_processes > 1:
        _enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _reassert_preemption_handler()
    elif coordinator_address is not None:
        _enable_cpu_collectives()
        jax.distributed.initialize(coordinator_address=coordinator_address)
        _reassert_preemption_handler()
    out = jax.process_index(), jax.process_count()
    if out[1] > 1:
        # Gang log attribution: stamp process_index on every structlog
        # record (interleaved gang stderr is otherwise unattributable).
        from tdc_tpu.utils.structlog import set_process_index

        set_process_index(out[0])
    return out


def _reassert_preemption_handler() -> None:
    """jax.distributed.initialize registers TSL's preemption notifier as a
    C-level SIGTERM handler, silently displacing a graceful-drain handler
    (utils/preempt) installed earlier — the worker would then die to the
    default action instead of checkpointing. Put ours back on top."""
    from tdc_tpu.utils.preempt import reinstall_if_installed

    reinstall_if_installed()


def _enable_cpu_collectives() -> None:
    """Multi-process collectives on the CPU backend need gloo; some jax
    versions default it off ('Multiprocess computations aren't implemented
    on the CPU backend'). Flip it before the backend initializes; harmless
    for TPU runs (the option only affects the CPU client) and absent on
    versions where gloo is the default."""
    name = "jax_cpu_collectives_implementation"
    try:  # attribute read works on some versions, _read on others
        current = getattr(jax.config, name)
    except AttributeError:
        try:
            current = jax.config._read(name)
        except Exception:
            current = None
    if current in (None, "none", ""):
        try:
            jax.config.update(name, "gloo")
        except Exception:
            pass  # option absent: gloo is this version's default


def initialize_from_env() -> tuple[int, int]:
    """Initialize jax.distributed from the supervisor's TDC_* environment.

    Workers launched by `parallel.supervisor.run_gang` call this first thing:
    it reads TDC_COORDINATOR / TDC_NUM_PROCESSES / TDC_PROCESS_ID (absent →
    single-process no-op, so the same worker script runs standalone too).
    Returns (process_index, num_processes).
    """
    import os

    coord = os.environ.get("TDC_COORDINATOR")
    nproc = os.environ.get("TDC_NUM_PROCESSES")
    pid = os.environ.get("TDC_PROCESS_ID")
    if coord is None or nproc is None or pid is None or int(nproc) <= 1:
        # A 1-process supervised gang needs no coordinator handshake (and
        # initialize(coordinator_address=...) alone would try to autodetect
        # a process count, which fails off managed TPU/SLURM machines).
        out = initialize_distributed()
    else:
        out = initialize_distributed(coord, int(nproc), int(pid))
    if nproc is not None:
        # One line per launch naming the gang size this worker came up at:
        # with elastic resize (parallel/supervisor.py) the size changes
        # across attempts, and the worker logs are where an operator
        # confirms the relaunch actually happened at the requested size.
        from tdc_tpu.utils.structlog import emit

        emit("gang_init", process_id=out[0], num_processes=out[1],
             attempt=int(os.environ.get("TDC_ATTEMPT", -1)))
    return out


def global_mesh(axis_name: str = DATA_AXIS) -> Mesh:
    """1-D mesh over every device of every process."""
    return Mesh(np.asarray(jax.devices()), (axis_name,))


def barrier(name: str = "tdc_exit") -> None:
    """Cross-process barrier; no-op single-process.

    Call before a gang worker exits: the first process to tear down its
    jax.distributed runtime cancels its peers' in-flight RPCs, so an
    unsynchronized exit turns a SUCCESSFUL run into a spurious nonzero peer
    exit that the supervisor then 'recovers' with a pointless restart."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def host_shard_bounds(n_global: int) -> tuple[int, int]:
    """[start, end) of this host's contiguous row range; even split with the
    remainder spread over the first hosts (np.array_split semantics, matching
    the reference's batch split at scripts/distribuitedClustering.py:335)."""
    p, np_ = jax.process_index(), jax.process_count()
    base, extra = divmod(n_global, np_)
    start = p * base + min(p, extra)
    return start, start + base + (1 if p < extra else 0)


def points_from_host_shards(
    local_rows: np.ndarray, n_global: int, mesh: Mesh, axis_name: str = DATA_AXIS
) -> jax.Array:
    """Assemble the global (n_global, d) points array from this host's rows.

    Each process passes only its own host_shard_bounds slice; the result is a
    single global jax.Array sharded over the mesh's data axis. Requires
    n_global divisible by the total device count (pad upstream otherwise).
    """
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_rows), (n_global,) + local_rows.shape[1:]
    )
