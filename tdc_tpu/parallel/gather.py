"""Compressed model/data-axis all_gather — the gather-side twin of
parallel/reduce (ROADMAP item 3, EQuARX-style block-wise quantization,
arXiv 2506.17615).

PR 2 compressed the data-axis stats psum; the model-axis traffic of the
K-sharded towers — the per-batch champion (min, argmin) all_gathers and
the centroid slices the data-sharded finalize exchanges — stayed full
fp32. This module provides the quantized gather primitives both ride:

- int8 with per-BLOCK (128-element) shared scales. Unlike the psum,
  gather payloads are never summed across devices, so the scales are
  LOCAL per source shard — no pmax agreement round is needed, and the
  codes + bitcast-to-int8 scales travel as ONE packed int8 buffer in a
  single all_gather. The collective count/order is therefore identical
  to the fp32 schedule (only operand dtypes/shapes change — the
  property tdcverify pins via same_schedule_as).
- bf16: cast → all_gather → upcast, same one-collective shape.
- Error feedback for pass-persistent leaves (the finalize's centroid
  slices): residual = y − decode(encode(y)) is returned to the caller,
  held in one persistent slot per gathered leaf, and re-injected into
  the next pass's encode — the EXACT algebra of reduce._q_psum_leaf.
  The finalize feeds the codec centroid DELTAS (new − current, with the
  replicated current added back after the gather), so the shared scales
  track the per-pass shift magnitude rather than the centroid
  magnitude — decode error shrinks with the update as the fit
  converges, instead of staying proportional to the data scale.
  Per-batch leaves (champion mins) are NOT error-fed: their payloads are
  new data every batch, there is no "next pass" for the residual of a
  batch that never recurs.
- Hierarchical staging (staged_all_gather): innermost-first over
  (dcn, ici)-style axis tuples with only the LAST (outermost = DCN)
  stage compressed — the expensive hop is the one quantized, mirroring
  reduce.tree_psum's last-stage-only policy.

Exactness invariant the coarse assignment path relies on: 0.0 encodes
to code 0 under any positive scale and decodes to exactly 0.0, so
zero-padding rows report min 0.0 on every shard after the quantized
gather, same as fp32.

Byte accounting (leaf_gather_cost / staged_gather_cost /
champion_gather_cost) mirrors reduce.tree_reduce_cost: logical bytes of
the gathered buffer per stage, not wire bytes. CommsCounter books these
under axis="model" (see parallel/reduce.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

GATHER_MODES = ("fp32", "fp32_sharded", "bf16", "int8")

# Shared-scale block width (EQuARX-style). 128 matches the TPU lane
# width; payloads are zero-padded up to a multiple internally.
BLOCK = 128

_EPS = 1e-30  # all-zero blocks keep a positive scale (0 -> code 0 -> 0.0)


@dataclass(frozen=True)
class GatherStrategy:
    """Validated `gather=` knob for the K-sharded drivers (the gather
    twin of reduce.ReduceStrategy).

    mode:
      'fp32'         — the pre-PR schedules, byte-identical: fp32
                       champion gathers, fully replicated finalize.
      'fp32_sharded' — full-precision wire, but the centroid finalize is
                       computed on each device's 1/n_data K-slice and
                       all-gathered (the FLOP-reduction ablation mode;
                       bit-exact vs the replicated finalize).
      'bf16' / 'int8' — fp32_sharded's structure with the champion and
                       finalize gathers compressed; the finalize gather
                       carries a persistent error-feedback residual.
    """

    mode: str = "fp32"

    def __post_init__(self):
        if self.mode not in GATHER_MODES:
            raise ValueError(
                f"gather mode {self.mode!r} not in {GATHER_MODES}"
            )

    @property
    def quantized(self) -> bool:
        return self.mode in ("bf16", "int8")

    @property
    def sharded_finalize(self) -> bool:
        return self.mode != "fp32"

    def label(self) -> str:
        return self.mode


def resolve_gather(gather) -> GatherStrategy:
    """'fp32' | 'fp32_sharded' | 'bf16' | 'int8' | GatherStrategy →
    GatherStrategy (same shorthand contract as reduce.resolve_reduce)."""
    if isinstance(gather, GatherStrategy):
        return gather
    return GatherStrategy(mode=str(gather))


# ---------------------------------------------------------------------------
# int8 block codec: (B, BLOCK) rows -> int8 codes + one f32 scale per row.
# ---------------------------------------------------------------------------


def _encode_int8(blocks):
    """(B, L) f32 → (codes (B, L) int8, scales (B,) f32), symmetric
    per-row scale = max|y|/127 (the reduce._q_psum_leaf quantizer with
    local instead of pmax-agreed scales)."""
    amax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.maximum(amax, _EPS) / 127.0
    codes = jnp.clip(
        jnp.round(blocks / scales[:, None]), -127, 127
    ).astype(jnp.int8)
    return codes, scales


def _decode_int8(codes, scales):
    return codes.astype(jnp.float32) * scales[..., None]


def _pad_to_block(flat):
    n = flat.shape[0]
    n_pad = -(-n // BLOCK) * BLOCK
    if n_pad != n:
        flat = jnp.pad(flat, (0, n_pad - n))
    return flat, n_pad


def _pack(codes_flat, scales):
    """codes (int8) ++ scales bitcast to int8 bytes: ONE flat payload so
    the compressed gather stays ONE collective."""
    sbytes = jax.lax.bitcast_convert_type(scales, jnp.int8).reshape(-1)
    return jnp.concatenate([codes_flat, sbytes])


def _unpack(gathered, n_codes, n_scales):
    """(G, payload) → (codes (G, n_codes) int8, scales (G, n_scales) f32)."""
    codes = gathered[:, :n_codes]
    sbytes = gathered[:, n_codes:].reshape(gathered.shape[0], n_scales, 4)
    return codes, jax.lax.bitcast_convert_type(sbytes, jnp.float32)


# ---------------------------------------------------------------------------
# The compressed all_gather primitive.
# ---------------------------------------------------------------------------


def compressed_all_gather(y, axis_name, mode: str, *, err=None):
    """all_gather an f32 leaf across ONE mesh axis under `mode`.

    Returns (gathered (G,) + y.shape f32, new_err). `err` (same shape as
    y, or None) is the persistent error-feedback residual: injected into
    the encode, and the returned new_err holds this round's quantization
    error for the NEXT call — reduce.py's EF algebra applied to a gather
    leaf. err=None skips EF entirely (per-batch leaves); fp32 modes pass
    err through untouched (the residual stays identically zero).

    Must be called inside shard_map (axis_name must be bound).
    """
    if mode in ("fp32", "fp32_sharded"):
        return jax.lax.all_gather(y, axis_name), err
    if mode == "bf16":
        src = y if err is None else y + err
        enc = src.astype(jnp.bfloat16)
        new_err = None if err is None else src - enc.astype(jnp.float32)
        g = jax.lax.all_gather(enc, axis_name).astype(jnp.float32)
        return g, new_err
    if mode != "int8":
        raise ValueError(f"gather mode {mode!r} not in {GATHER_MODES}")
    src = y if err is None else y + err
    flat = src.reshape(-1)
    n = flat.shape[0]
    flat_p, n_pad = _pad_to_block(flat)
    codes, scales = _encode_int8(flat_p.reshape(-1, BLOCK))
    if err is None:
        new_err = None
    else:
        dec_local = _decode_int8(codes, scales).reshape(-1)[:n]
        new_err = (flat - dec_local).reshape(y.shape)
    gathered = jax.lax.all_gather(_pack(codes.reshape(-1), scales), axis_name)
    cg, sg = _unpack(gathered, n_pad, n_pad // BLOCK)
    dec = _decode_int8(cg.reshape(gathered.shape[0], -1, BLOCK), sg)
    dec = dec.reshape(gathered.shape[0], -1)[:, :n]
    return dec.reshape((gathered.shape[0],) + y.shape), new_err


def staged_all_gather(y, axes, mode: str, *, err=None):
    """all_gather across one or more mesh axes, innermost-first, with
    only the LAST (outermost — the DCN hop on hierarchical meshes) stage
    compressed — the staging policy of reduce.tree_psum applied to
    gathers: ICI stages stay fp32, the expensive hop is the one
    quantized.

    Returns (gathered (prod(G),) + y.shape f32, new_err). For EF, `err`
    matches the LAST stage's input shape: (inner groups…,) + y.shape —
    for single-axis calls that is just y.shape.
    """
    axes = tuple(axes)
    if not axes:
        raise ValueError("staged_all_gather needs at least one axis")
    leaf = y
    for ax in axes[:0:-1]:  # inner stages, innermost first, full precision
        leaf = jax.lax.all_gather(leaf, ax)
    g, new_err = compressed_all_gather(leaf, axes[0], mode, err=err)
    return g.reshape((-1,) + y.shape), new_err


# ---------------------------------------------------------------------------
# Byte accounting (the gather twin of reduce.tree_reduce_cost): logical
# bytes of the gathered buffer per stage. Booked under axis="model".
# ---------------------------------------------------------------------------


def _payload_bytes(n_elems: int, mode: str) -> int:
    if mode in ("fp32", "fp32_sharded"):
        return n_elems * 4
    if mode == "bf16":
        return n_elems * 2
    n_pad = -(-n_elems // BLOCK) * BLOCK
    return n_pad + 4 * (n_pad // BLOCK)  # int8 codes + f32 block scales


def leaf_gather_cost(n_elems: int, group: int, mode: str) -> int:
    """Logical bytes one all_gather stage materializes: group × the
    per-source payload (codes + scales when quantized)."""
    return group * _payload_bytes(n_elems, mode)


def staged_gather_cost(n_elems: int, groups, mode: str):
    """Per-stage logical bytes for staged_all_gather, innermost-first
    (the order the stages execute). groups is (outer, …, inner) matching
    the axes tuple; inner stages are fp32, the last is `mode`."""
    groups = tuple(groups)
    stages = []
    cur = n_elems
    for g in groups[:0:-1]:
        stages.append(leaf_gather_cost(cur, g, "fp32"))
        cur *= g
    stages.append(leaf_gather_cost(cur, groups[0], mode))
    return stages


def champion_gather_cost(n_rows: int, n_model: int, mode: str):
    """(gathers, logical bytes) for ONE batch's champion (min, argmin)
    all_gather pair over the model axis. The int32 argmin column is
    never quantized (champion ids must survive exactly)."""
    mins = leaf_gather_cost(n_rows, n_model, mode)
    args = n_model * n_rows * 4
    return 2, mins + args


def finalize_gather_cost(k: int, d: int, groups, mode: str):
    """(collectives, logical bytes) for one sharded-finalize exchange:
    the staged slice all_gather (each stage's gathered buffer) plus the
    4-byte shift pmax. groups = data-axis stage sizes, outer-first;
    k is the GLOBAL centroid count — each of prod(groups) slices carries
    k·d / prod(groups) elements, so the bytes sum telescopes to the full
    (K, d) buffer per model column at the final stage."""
    groups = tuple(groups)
    n_data = 1
    for g in groups:
        n_data *= g
    slice_elems = (k * d) // n_data
    stages = staged_gather_cost(slice_elems, groups, mode)
    return len(stages) + 1, sum(stages) + 4
