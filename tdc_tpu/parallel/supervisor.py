"""Elastic gang supervision: worker-loss detection + restart-from-checkpoint.

The reference's entire failure story is per-experiment subprocess isolation
plus an OOM retry (SURVEY.md §5; scripts/new_experiment.py:59-64,
scripts/distribuitedClustering.py:357-360) — a lost or hung process simply
loses the run. This module adds the multi-host equivalent the SURVEY plan
calls for: a gang of `jax.distributed` worker processes is supervised, and

- a worker exiting nonzero, or going heartbeat-silent past a deadline, marks
  the GANG failed — JAX collectives cannot survive a lost participant, so the
  recovery unit is the whole gang, never a single worker;
- the survivors are killed, the checkpoint directory is trimmed to the latest
  fully-written step (orbax leaves *.orbax-checkpoint-tmp-* droppings when a
  save is interrupted; with per-worker dirs, steps are additionally trimmed
  to the latest step COMMON to all dirs — resuming from different steps
  would diverge or deadlock in the first collective), and
- the gang is relaunched on a fresh coordinator port; workers resume from
  the aligned checkpoint (models/streaming.py persists centroids, iteration,
  and optionally the mid-pass accumulator).

Checkpoint-directory semantics: a gang shares ONE checkpoint directory —
process 0 is the single writer (utils/checkpoint.py writes an atomic
state.npz per step in multi-process mode), every worker restores the same
step; on real pods that is the usual shared filesystem (GCS/NFS), here the
local disk. Pass `ckpt_dirs=[shared_dir]` to run_gang (a single entry is
broadcast to every worker); per-worker dirs remain supported for
single-process gangs or non-shared state.

Scope: supervises the processes it spawned — one machine, e.g. the per-host
launcher of a real pod deployment or the CPU-device simulation the tests use.
The restart + checkpoint-alignment logic is the portable core.

Workers receive their gang coordinates via environment variables
(TDC_PROCESS_ID, TDC_NUM_PROCESSES, TDC_COORDINATOR, TDC_ATTEMPT, and
optionally TDC_CKPT_DIR / TDC_HEARTBEAT_FILE) and should call
`tdc_tpu.parallel.multihost.initialize_from_env()` first thing.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import sys
import time
from dataclasses import dataclass


class GangFailed(RuntimeError):
    """All restart attempts exhausted; carries per-worker log tails."""


@dataclass
class GangResult:
    attempts: int  # total launches (1 = no restart was needed)
    returncodes: list[int]  # final attempt's per-worker exit codes (all 0)
    log_paths: list[str]  # final attempt's per-worker stdout+stderr logs


def free_port() -> int:
    """An OS-assigned free TCP port for the jax.distributed coordinator."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _checkpoint_steps(ckpt_dir: str) -> set[int]:
    # Deliberately duplicates utils/checkpoint._all_steps' step_<N> parsing:
    # the supervisor stays stdlib-only (importing tdc_tpu.utils.checkpoint
    # would pull jax into the supervising process). Keep the two in sync if
    # the on-disk step layout ever changes.
    if not os.path.isdir(ckpt_dir):
        return set()
    steps = set()
    for name in os.listdir(ckpt_dir):
        parts = name.split("_")
        if name.startswith("step_") and len(parts) == 2 and parts[1].isdigit():
            steps.add(int(parts[1]))
    return steps


def align_checkpoints(ckpt_dirs: list[str], log=lambda *_: None) -> int | None:
    """Trim per-worker checkpoint dirs to the latest step present in ALL of
    them; returns that step (None = no common step, all checkpoints removed
    and the gang restarts from scratch).

    Also removes orbax temp dirs (step_*.orbax-checkpoint-tmp-*) left by a
    save that was interrupted mid-write.
    """
    per_dir = [_checkpoint_steps(d) for d in ckpt_dirs]
    common = set.intersection(*per_dir) if per_dir else set()
    target = max(common) if common else None
    for d, steps in zip(ckpt_dirs, per_dir):
        if not os.path.isdir(d):
            continue
        for name in os.listdir(d):
            path = os.path.join(d, name)
            if not name.startswith("step_"):
                continue
            parts = name.split("_")
            is_step = len(parts) == 2 and parts[1].isdigit()
            if is_step and (target is None or int(parts[1]) > target):
                log(f"supervisor: dropping {path} (beyond common step {target})")
                shutil.rmtree(path, ignore_errors=True)
            elif not is_step:  # interrupted orbax tmp dir
                shutil.rmtree(path, ignore_errors=True)
    return target


def _kill(procs, grace: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def run_gang(
    cmd: list[str],
    num_processes: int,
    *,
    max_restarts: int = 2,
    heartbeat_timeout: float | None = None,
    ckpt_dirs: list[str] | None = None,
    log_dir: str,
    env: dict | None = None,
    poll_interval: float = 0.25,
    grace: float = 5.0,
    echo=lambda msg: print(msg, file=sys.stderr, flush=True),
) -> GangResult:
    """Run `cmd` as a gang of `num_processes` workers; restart on failure.

    Args:
      cmd: the worker command line, identical for every worker — workers read
        their coordinates from the TDC_* environment.
      max_restarts: restarts after the first launch (total attempts = 1 + this).
      heartbeat_timeout: if set, a worker whose TDC_HEARTBEAT_FILE goes
        untouched for this many seconds is treated as hung (the clock starts
        at spawn, so slow startup counts against it — size accordingly, e.g.
        several compile times).
      ckpt_dirs: checkpoint directories, exported as TDC_CKPT_DIR and aligned
        with `align_checkpoints` before every relaunch. A single entry is
        shared by every worker (required for orbax state — see module
        docstring); otherwise len must equal num_processes. Without it,
        restarts are from scratch.
      log_dir: per-attempt, per-worker stdout+stderr capture files.

    Returns GangResult on success; raises GangFailed when attempts run out.
    """
    if ckpt_dirs is not None and len(ckpt_dirs) not in (1, num_processes):
        raise ValueError(
            f"need 1 (shared) or {num_processes} ckpt_dirs, got {len(ckpt_dirs)}"
        )
    if ckpt_dirs is not None and len(ckpt_dirs) == 1:
        ckpt_dirs = ckpt_dirs * num_processes
    elif ckpt_dirs is not None and num_processes > 1:
        echo("supervisor: warning — per-worker ckpt_dirs with a "
             "jax.distributed gang will not recover (the gang's checkpoints "
             "are written by process 0 only; non-primary dirs stay empty and "
             "align_checkpoints then wipes everything). Use one shared dir "
             "unless the workers run independent single-process fits.")
    os.makedirs(log_dir, exist_ok=True)
    base_env = dict(os.environ if env is None else env)

    for attempt in range(max_restarts + 1):
        if attempt > 0 and ckpt_dirs is not None:
            step = align_checkpoints(ckpt_dirs, log=echo)
            echo(f"supervisor: attempt {attempt + 1}, resuming from "
                 f"{'scratch' if step is None else f'common step {step}'}")
        coordinator = f"127.0.0.1:{free_port()}"
        procs, logs, hb_files, log_paths = [], [], [], []
        failed_why = None
        try:
            # Spawn inside the try so a mid-loop Popen/open failure (fd or
            # memory exhaustion) still kills the workers already started —
            # they would otherwise block forever in the coordinator
            # handshake waiting for peers that never came up.
            for pid in range(num_processes):
                worker_env = dict(base_env)
                worker_env.update(
                    TDC_PROCESS_ID=str(pid),
                    TDC_NUM_PROCESSES=str(num_processes),
                    TDC_COORDINATOR=coordinator,
                    TDC_ATTEMPT=str(attempt),
                )
                hb = None
                if heartbeat_timeout is not None:
                    hb = os.path.join(log_dir, f"hb_a{attempt}_p{pid}")
                    worker_env["TDC_HEARTBEAT_FILE"] = hb
                hb_files.append(hb)
                if ckpt_dirs is not None:
                    worker_env["TDC_CKPT_DIR"] = ckpt_dirs[pid]
                log_path = os.path.join(log_dir,
                                        f"worker_a{attempt}_p{pid}.log")
                log_paths.append(log_path)
                logf = open(log_path, "w")
                logs.append(logf)
                procs.append(
                    subprocess.Popen(cmd, env=worker_env, stdout=logf,
                                     stderr=subprocess.STDOUT)
                )
            # Wall clock, not monotonic: heartbeat staleness compares against
            # file mtimes, which are epoch seconds.
            start = time.time()
            while True:
                codes = [p.poll() for p in procs]
                bad = [(i, c) for i, c in enumerate(codes)
                       if c is not None and c != 0]
                if bad:
                    failed_why = ", ".join(
                        f"worker {i} exited {c}" for i, c in bad)
                    break
                if all(c == 0 for c in codes):
                    for f in logs:
                        f.close()
                    return GangResult(
                        attempts=attempt + 1,
                        returncodes=[int(c) for c in codes],
                        log_paths=log_paths,
                    )
                if heartbeat_timeout is not None:
                    now = time.time()
                    for i, (hb, c) in enumerate(zip(hb_files, codes)):
                        if c is not None:
                            continue  # already exited 0; not hung
                        try:
                            last = os.path.getmtime(hb)
                        except OSError:
                            last = start
                        if now - max(last, start) > heartbeat_timeout:
                            failed_why = (f"worker {i} heartbeat silent "
                                          f"> {heartbeat_timeout}s")
                            break
                    if failed_why:
                        break
                time.sleep(poll_interval)
        finally:
            _kill(procs, grace)
            for f in logs:
                f.close()
        echo(f"supervisor: gang attempt {attempt + 1} failed ({failed_why})")
        if attempt == max_restarts:
            tails = []
            for i, path in enumerate(log_paths):
                try:
                    with open(path) as f:
                        tails.append(f"--- worker {i} ---\n{f.read()[-2000:]}")
                except OSError:
                    pass
            raise GangFailed(
                f"gang failed after {max_restarts + 1} attempts "
                f"(last: {failed_why})\n" + "\n".join(tails)
            )
    raise AssertionError("unreachable")


__all__ = [
    "GangFailed",
    "GangResult",
    "align_checkpoints",
    "free_port",
    "run_gang",
]
