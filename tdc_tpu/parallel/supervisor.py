"""Elastic gang supervision: worker-loss detection + restart-from-checkpoint
+ preemption-aware failure domains.

The reference's entire failure story is per-experiment subprocess isolation
plus an OOM retry (SURVEY.md §5; scripts/new_experiment.py:59-64,
scripts/distribuitedClustering.py:357-360) — a lost or hung process simply
loses the run. This module adds the multi-host equivalent the SURVEY plan
calls for: a gang of `jax.distributed` worker processes is supervised, and

- a worker exiting nonzero, or going heartbeat-silent past a deadline, marks
  the GANG failed — JAX collectives cannot survive a lost participant, so the
  recovery unit is the whole gang, never a single worker;
- the survivors are killed, the checkpoint directory is trimmed to the latest
  fully-written step (orbax leaves *.orbax-checkpoint-tmp-* droppings when a
  save is interrupted; with per-worker dirs, steps are additionally trimmed
  to the latest step COMMON to all dirs — resuming from different steps
  would diverge or deadlock in the first collective), and
- the gang is relaunched on a fresh coordinator port; workers resume from
  the aligned checkpoint (models/streaming.py persists centroids, iteration,
  and optionally the mid-pass accumulator).

Failure-domain semantics (gang-scheduled SPMD makes the QUALITY of each
recovery the whole robustness budget — Mesh-TensorFlow, arxiv 1811.02084):

- **Preemption is not a crash.** A worker that exits with
  PREEMPTED_EXIT_CODE (75 — utils/preempt.py: SIGTERM caught, checkpoint
  written at a safe boundary) marks the attempt *preempted*: the gang is
  relaunched immediately and the restart budget is NOT charged. A SIGTERM
  delivered to the supervisor itself is forwarded to the whole gang, the
  workers are given `drain_grace` seconds to checkpoint and exit, and
  GangPreempted is raised (exit the job; the scheduler will rerun it).
- **Only non-progress restarts burn budget.** Before charging a failure
  against `max_restarts`, the supervisor compares the aligned common
  checkpoint step with the one recorded at the previous relaunch: if the
  step advanced, the workload is making progress and the attempt counter
  resets — a workload that crashes every N hours runs forever, while a
  crash-loop (same step every time) exhausts the budget fast.
- **Backoff between failure relaunches.** Exponential with jitter
  (`backoff_base * 2^(consecutive non-progress failures)`, capped at
  `backoff_max`) so a crash-looping gang cannot hammer the coordinator /
  filesystem back-to-back. Preemption relaunches skip the backoff — the
  replacement capacity is already allocated.
- **Resize is the third outcome** (besides relaunch and fail): elastic
  capacity means the gang's size can change without a cold restart. An
  operator (or autoscaler) writes the desired size into the resize
  request file (`<log_dir>/resize` by default; `$TDC_RESIZE` on the
  supervisor's environment sets the INITIAL size) — a request observed
  mid-run drains the gang exactly like a preemption (SIGTERM, grace
  window, workers exit 75 at a checkpoint boundary; SIGHUP to the
  supervisor forces an immediate re-read), and the relaunch comes up at
  the new size, resuming from the latest aligned checkpoint. The
  checkpoints are layout-portable (parallel/reshard.py: full host-side
  arrays plus a layout manifest), so the resized workers redistribute
  the state onto their new mesh. Resize relaunches charge NEITHER the
  failure budget nor the preemption cap, and a standing request is also
  honored at preemption/failure relaunches — losing a slice for good
  shrinks the gang instead of crash-looping at a size the capacity can
  no longer satisfy. `GangResult.size_history` records the size of
  every launch. Resize requires a SHARED checkpoint dir (or none):
  per-worker dirs have no meaning at a different size.

Checkpoint-directory semantics: a gang shares ONE checkpoint directory —
process 0 is the single writer (utils/checkpoint.py writes an atomic
state.npz per step in multi-process mode), every worker restores the same
step; on real pods that is the usual shared filesystem (GCS/NFS), here the
local disk. Pass `ckpt_dirs=[shared_dir]` to run_gang (a single entry is
broadcast to every worker); per-worker dirs remain supported for
single-process gangs or non-shared state.

Scope: supervises the processes it spawned — one machine, e.g. the per-host
launcher of a real pod deployment or the CPU-device simulation the tests use.
The restart + checkpoint-alignment logic is the portable core.

Workers receive their gang coordinates via environment variables
(TDC_PROCESS_ID, TDC_NUM_PROCESSES, TDC_COORDINATOR, TDC_ATTEMPT, and
optionally TDC_CKPT_DIR / TDC_HEARTBEAT_FILE) and should call
`tdc_tpu.parallel.multihost.initialize_from_env()` first thing.
"""

from __future__ import annotations

import os
import random
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field

# The single definition lives with the worker-side drain machinery; any
# tdc_tpu.* import already runs the package __init__ (jax included), so
# duplicating the value here would buy no import savings — only the risk
# of the refund check silently desyncing from the workers' exit code.
from tdc_tpu.utils.preempt import PREEMPTED_EXIT_CODE


class GangFailed(RuntimeError):
    """All restart attempts exhausted; carries per-worker log tails."""


class GangPreempted(RuntimeError):
    """The SUPERVISOR received SIGTERM: the gang was drained (forwarded
    SIGTERM, waited for checkpoint-and-exit) and the job should stop —
    the external scheduler owns the relaunch. `.step` is the aligned
    checkpoint step the next run will resume from (None = none)."""

    def __init__(self, message: str, step: int | None = None):
        super().__init__(message)
        self.step = step


@dataclass
class GangResult:
    attempts: int  # total launches (1 = no restart was needed)
    returncodes: list[int]  # final attempt's per-worker exit codes (all 0)
    log_paths: list[str]  # final attempt's per-worker stdout+stderr logs
    preemptions: int = 0  # launches that ended in a preemption exit (75)
    budget_used: int = 0  # failure restarts charged against max_restarts
    restart_delays: list[float] = field(default_factory=list)  # backoffs slept
    resizes: int = 0  # relaunches that changed the gang size
    size_history: list[int] = field(default_factory=list)  # size per launch


def _default_echo(msg: str) -> None:
    # Routed through utils/structlog so recovery events are one JSON line
    # each, machine-parseable next to the serve request log (lazy import:
    # only the default path pays for the package import).
    from tdc_tpu.utils.structlog import emit

    emit("supervisor", msg=msg)


def free_port() -> int:
    """An OS-assigned free TCP port for the jax.distributed coordinator."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _checkpoint_steps(ckpt_dir: str) -> set[int]:
    # One parser for the on-disk step_<N> layout: utils/checkpoint owns it
    # (lazy import keeps module import light; the process has the package
    # loaded anyway).
    from tdc_tpu.utils.checkpoint import _all_steps

    return set(_all_steps(ckpt_dir))


def _common_step(ckpt_dirs: list[str]) -> int | None:
    """Latest step present in ALL dirs (read-only; align_checkpoints is the
    trimming counterpart). The supervisor's progress signal."""
    per_dir = [_checkpoint_steps(d) for d in ckpt_dirs]
    common = set.intersection(*per_dir) if per_dir else set()
    return max(common) if common else None


def align_checkpoints(ckpt_dirs: list[str], log=lambda *_: None) -> int | None:
    """Trim per-worker checkpoint dirs to the latest step present in ALL of
    them; returns that step (None = no common step, all checkpoints removed
    and the gang restarts from scratch).

    Also removes orbax temp dirs (step_*.orbax-checkpoint-tmp-*) left by a
    save that was interrupted mid-write.
    """
    per_dir = [_checkpoint_steps(d) for d in ckpt_dirs]
    common = set.intersection(*per_dir) if per_dir else set()
    target = max(common) if common else None
    for d, steps in zip(ckpt_dirs, per_dir):
        if not os.path.isdir(d):
            continue
        for name in os.listdir(d):
            path = os.path.join(d, name)
            if not name.startswith("step_"):
                continue
            parts = name.split("_")
            is_step = len(parts) == 2 and parts[1].isdigit()
            if is_step and (target is None or int(parts[1]) > target):
                log(f"supervisor: dropping {path} (beyond common step {target})")
                shutil.rmtree(path, ignore_errors=True)
            elif not is_step:  # interrupted orbax tmp dir
                shutil.rmtree(path, ignore_errors=True)
    return target


def _kill(procs, grace: float) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + grace
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def _prune_heartbeats(hb_files) -> None:
    """A completed attempt's heartbeat files are dead weight — without this
    a long-lived elastic job accumulates one per worker per attempt in
    log_dir, unbounded."""
    for hb in hb_files:
        if hb:
            try:
                os.remove(hb)
            except OSError:
                pass


def _prune_stale_heartbeats(log_dir: str) -> None:
    """Drop hb_a<N>_p<M> files left by a PREVIOUS supervisor run in the
    same log_dir. Attempt numbering restarts at 0 per run, so a stale
    file can collide with a fresh attempt's path — and with resize in
    play the old size's files would linger forever (a 4->2 shrink never
    recreates hb_a*_p3). The in-run hang detector already guards against
    stale mtimes (max(last, start)); this keeps the DIRECTORY honest."""
    try:
        names = os.listdir(log_dir)
    except OSError:
        return
    for name in names:
        if not name.startswith("hb_a"):
            continue
        parts = name[3:].split("_")  # ["a<N>", "p<M>"]
        if (len(parts) == 2 and parts[0][:1] == "a" and parts[1][:1] == "p"
                and parts[0][1:].isdigit() and parts[1][1:].isdigit()):
            try:
                os.remove(os.path.join(log_dir, name))
            except OSError:
                pass


def _parse_size(txt: str, src: str, echo) -> int | None:
    """Parse one desired-gang-size integer (shared by the request file
    and $TDC_RESIZE — ONE copy of the validation, so the two channels
    cannot drift). Malformed content is ignored LOUDLY: a typo'd
    autoscaler write must not kill the supervisor, but silence would
    make the no-op undebuggable."""
    try:
        want = int(txt)
    except ValueError:
        echo(f"supervisor: ignoring resize request {txt!r} from {src}: "
             "not an integer")
        return None
    if want < 1:
        echo(f"supervisor: ignoring resize request {want} from {src}: "
             "gang size must be >= 1")
        return None
    return want


def _read_resize_request(path: str | None, echo) -> int | None:
    """The resize-request file: one integer, the desired gang size.
    Absent/empty file means no request."""
    if path is None:
        return None
    try:
        with open(path) as f:
            txt = f.read().strip()
    except OSError:
        return None
    if not txt:
        return None
    return _parse_size(txt, path, echo)


def _read_env_resize(echo) -> int | None:
    """$TDC_RESIZE on the SUPERVISOR's environment: the initial gang size
    (the env-only hook for schedulers that cannot write the request file
    before exec). Read once at run_gang entry."""
    txt = os.environ.get("TDC_RESIZE", "").strip()
    if not txt:
        return None
    return _parse_size(txt, "$TDC_RESIZE", echo)


def run_gang(
    cmd: list[str],
    num_processes: int,
    *,
    max_restarts: int = 2,
    max_preemption_restarts: int = 32,
    heartbeat_timeout: float | None = None,
    ckpt_dirs: list[str] | None = None,
    log_dir: str,
    env: dict | None = None,
    poll_interval: float = 0.25,
    grace: float = 5.0,
    drain_grace: float = 30.0,
    backoff_base: float = 0.5,
    backoff_max: float = 30.0,
    resize_request_path: str | None = None,
    echo=_default_echo,
) -> GangResult:
    """Run `cmd` as a gang of `num_processes` workers; restart on failure,
    resize on request (see the module docstring's resize bullet).

    Args:
      cmd: the worker command line, identical for every worker — workers read
        their coordinates from the TDC_* environment.
      max_restarts: budget of NON-PROGRESS failure restarts (crash-loop
        detection): a relaunch whose aligned checkpoint step advanced past
        the previous relaunch's resets the counter, and preemption exits
        (PREEMPTED_EXIT_CODE) never charge it.
      max_preemption_restarts: hard cap on free preemption relaunches — a
        worker that (buggily) always exits 75 must not loop forever.
      heartbeat_timeout: if set, a worker whose TDC_HEARTBEAT_FILE goes
        untouched for this many seconds is treated as hung (the clock starts
        at spawn, so slow startup counts against it — size accordingly, e.g.
        several compile times).
      ckpt_dirs: checkpoint directories, exported as TDC_CKPT_DIR and aligned
        with `align_checkpoints` before every relaunch. A single entry is
        shared by every worker (required for orbax state — see module
        docstring); otherwise len must equal num_processes. Without it,
        restarts are from scratch.
      log_dir: per-attempt, per-worker stdout+stderr capture files.
      drain_grace: on supervisor SIGTERM (or a partial preemption — some
        workers exited 75 while peers still run), how long the remaining
        workers get to checkpoint and exit before being killed.
      backoff_base / backoff_max: exponential-backoff-with-jitter bounds
        between FAILURE relaunches (base * 2^failures, capped; preemption
        relaunches are immediate). backoff_base=0 disables (tests).
      resize_request_path: the resize-request file (one integer: the
        desired gang size). Default `<log_dir>/resize`. Polled while the
        gang runs (a write drains the gang and relaunches at the new
        size; SIGHUP forces an immediate re-read) and consulted as a
        standing request before every preemption/failure relaunch.
        Resize relaunches charge neither budget. Needs a shared (or no)
        checkpoint dir; requests are ignored loudly otherwise.

    Returns GangResult on success; raises GangFailed when the restart budget
    runs out, GangPreempted when the supervisor itself was told to drain.
    """
    if ckpt_dirs is not None and len(ckpt_dirs) not in (1, num_processes):
        raise ValueError(
            f"need 1 (shared) or {num_processes} ckpt_dirs, got {len(ckpt_dirs)}"
        )
    shared_ckpt: str | None = None
    fixed_ckpt_dirs: list[str] | None = None
    if ckpt_dirs is not None:
        if len(set(ckpt_dirs)) == 1:
            shared_ckpt = ckpt_dirs[0]
        else:
            fixed_ckpt_dirs = list(ckpt_dirs)
            if num_processes > 1:
                echo("supervisor: warning — per-worker ckpt_dirs with a "
                     "jax.distributed gang will not recover (the gang's "
                     "checkpoints are written by process 0 only; non-primary "
                     "dirs stay empty and align_checkpoints then wipes "
                     "everything). Use one shared dir unless the workers run "
                     "independent single-process fits.")
    # Resize needs per-size checkpoint-dir semantics: a shared dir (or
    # none) broadcasts to any size; distinct per-worker dirs do not.
    resizable = fixed_ckpt_dirs is None

    def dirs_for(size: int) -> list[str] | None:
        if shared_ckpt is not None:
            return [shared_ckpt] * size
        return fixed_ckpt_dirs  # per-worker (never resized) or None

    os.makedirs(log_dir, exist_ok=True)
    # Heartbeat hygiene: a previous supervisor run's (possibly other-sized)
    # hb files must not linger into this run's attempt numbering.
    _prune_stale_heartbeats(log_dir)
    base_env = dict(os.environ if env is None else env)
    resize_path = resize_request_path
    if resize_path is None:
        resize_path = os.path.join(log_dir, "resize")

    # Supervisor-level SIGTERM: forward to the gang and drain. SIGHUP:
    # re-read the resize request immediately. Installed only on the main
    # thread (signal.signal's requirement); elsewhere the supervisor
    # simply has no drain/resize-signal path of its own.
    sigterm_box: list[float] = []
    sighup_box: list[float] = []
    old_handler = None
    handler_installed = False
    old_hup = None
    hup_installed = False
    if threading.current_thread() is threading.main_thread():
        try:
            old_handler = signal.signal(
                signal.SIGTERM, lambda *_: sigterm_box.append(time.time())
            )
            handler_installed = True
        except (ValueError, OSError):  # exotic embeddings
            pass
        try:
            old_hup = signal.signal(
                signal.SIGHUP, lambda *_: sighup_box.append(time.time())
            )
            hup_installed = True
        except (ValueError, OSError, AttributeError):  # no SIGHUP here
            pass

    from tdc_tpu.testing.faults import fault_point

    attempt = 0  # launch index: TDC_ATTEMPT and log-file naming
    budget_used = 0
    preemptions = 0
    resizes = 0
    size_history: list[int] = []
    restart_delays: list[float] = []
    last_step: int | None = None  # aligned step at the previous relaunch
    cur_size = num_processes
    resize_denied_echoed = False

    def _deny_resize() -> None:
        """One loud (once-per-run) line for the per-worker-ckpt_dirs case."""
        nonlocal resize_denied_echoed
        if not resize_denied_echoed:
            echo("supervisor: resize requested but per-worker ckpt_dirs "
                 "cannot change size — ignoring (use one shared "
                 "checkpoint dir to enable elastic resize)")
            resize_denied_echoed = True

    def _apply_standing_resize(reason: str) -> None:
        """Honor a pending resize request at a relaunch boundary."""
        nonlocal cur_size, resizes
        want = _read_resize_request(resize_path, echo)
        if want is None or want == cur_size:
            return
        if not resizable:
            _deny_resize()
            return
        fault_point("supervisor.resize")
        echo(f"supervisor: resizing gang {cur_size} -> {want} ({reason}); "
             "relaunching from the latest aligned checkpoint")
        cur_size = want
        resizes += 1

    env_size = _read_env_resize(echo)
    if env_size is not None and env_size != cur_size:
        if resizable:
            echo(f"supervisor: $TDC_RESIZE — starting the gang at size "
                 f"{env_size} instead of {num_processes}")
            cur_size = env_size
        else:
            _deny_resize()
    # A request file surviving from BEFORE this run (possibly a previous
    # supervisor in the same log_dir) is a standing request: it will not
    # interrupt the gang, but WILL be honored at the first relaunch
    # boundary — say so at launch, so a week-old leftover can never
    # resize a new run silently (rm the file to cancel).
    standing = _read_resize_request(resize_path, echo)
    if standing is not None and standing != cur_size:
        if not resizable:
            _deny_resize()
        else:
            echo(f"supervisor: standing resize request for size {standing} "
                 f"found at startup (gang starts at {cur_size}); it "
                 f"applies at the next relaunch boundary — remove "
                 f"{resize_path} to cancel")
    try:
        while True:
            launch_dirs = dirs_for(cur_size)
            if attempt > 0 and launch_dirs is not None:
                step = align_checkpoints(launch_dirs, log=echo)
                echo(f"supervisor: attempt {attempt + 1}, resuming from "
                     f"{'scratch' if step is None else f'common step {step}'}")
                last_step = step if step is not None else last_step
            coordinator = f"127.0.0.1:{free_port()}"
            procs, logs, hb_files, log_paths = [], [], [], []
            failed_why = None
            preempted_attempt = False
            resize_draining = False
            last_resize_mtime = None
            drain_deadline = None
            forwarded = False
            size_history.append(cur_size)
            # The live resize watch compares request-file mtimes against
            # the moment THIS attempt began — taken before the spawn
            # loop, which can run for seconds on a big gang: a request
            # written mid-spawn must drain the attempt, not silently
            # demote to a standing request (heartbeat staleness keeps
            # its own post-spawn `start` so spawn time never counts
            # against the workers).
            watch_since = time.time()
            try:
                # Spawn inside the try so a mid-loop Popen/open failure (fd or
                # memory exhaustion) still kills the workers already started —
                # they would otherwise block forever in the coordinator
                # handshake waiting for peers that never came up.
                for pid in range(cur_size):
                    worker_env = dict(base_env)
                    worker_env.update(
                        TDC_PROCESS_ID=str(pid),
                        TDC_NUM_PROCESSES=str(cur_size),
                        TDC_COORDINATOR=coordinator,
                        TDC_ATTEMPT=str(attempt),
                    )
                    hb = None
                    if heartbeat_timeout is not None:
                        hb = os.path.join(log_dir, f"hb_a{attempt}_p{pid}")
                        worker_env["TDC_HEARTBEAT_FILE"] = hb
                    hb_files.append(hb)
                    if launch_dirs is not None:
                        worker_env["TDC_CKPT_DIR"] = launch_dirs[pid]
                    log_path = os.path.join(log_dir,
                                            f"worker_a{attempt}_p{pid}.log")
                    log_paths.append(log_path)
                    logf = open(log_path, "w")
                    logs.append(logf)
                    fault_point("supervisor.spawn")
                    procs.append(
                        subprocess.Popen(cmd, env=worker_env, stdout=logf,
                                         stderr=subprocess.STDOUT)
                    )
                # Wall clock, not monotonic: heartbeat staleness compares
                # against file mtimes, which are epoch seconds.
                start = time.time()
                while True:
                    if sigterm_box and not forwarded:
                        echo("supervisor: SIGTERM received — forwarding to "
                             f"the gang and draining (grace {drain_grace}s)")
                        for p in procs:
                            if p.poll() is None:
                                p.terminate()
                        forwarded = True
                        drain_deadline = time.monotonic() + drain_grace
                    if not forwarded and drain_deadline is None:
                        # Live resize watch: SIGHUP forces a re-read;
                        # otherwise only a request file WRITTEN during this
                        # attempt triggers a drain (an older file is a
                        # standing request, honored at the next relaunch
                        # boundary — not grounds to interrupt a healthy
                        # gang that already matches it or predates it).
                        check = bool(sighup_box)
                        if sighup_box:
                            del sighup_box[:]
                        else:
                            try:
                                mt = os.path.getmtime(resize_path)
                            except OSError:
                                mt = None
                            if (mt is not None and mt >= watch_since
                                    and mt != last_resize_mtime):
                                last_resize_mtime = mt
                                check = True
                        if check:
                            want = _read_resize_request(resize_path, echo)
                            if want is not None and want != cur_size:
                                if not resizable:
                                    _deny_resize()
                                else:
                                    echo(f"supervisor: resize request "
                                         f"{cur_size} -> {want} — draining "
                                         f"the gang (grace {drain_grace}s)")
                                    for p in procs:
                                        if p.poll() is None:
                                            p.terminate()
                                    resize_draining = True
                                    drain_deadline = (time.monotonic()
                                                      + drain_grace)
                    codes = [p.poll() for p in procs]
                    ok_codes = (0, PREEMPTED_EXIT_CODE)
                    if resize_draining:
                        # A worker that had no drain handler yet (still
                        # importing jax at terminate time) dies from OUR
                        # SIGTERM with -15: that is the resize drain doing
                        # its job, not a worker failure — it must not
                        # charge the budget a resize promises not to touch
                        # (resume falls back to the last aligned step).
                        ok_codes = (0, PREEMPTED_EXIT_CODE,
                                    -signal.SIGTERM)
                    bad = [(i, c) for i, c in enumerate(codes)
                           if c is not None and c not in ok_codes]
                    if bad:
                        failed_why = ", ".join(
                            f"worker {i} exited {c}" for i, c in bad)
                        break
                    preempted = [i for i, c in enumerate(codes)
                                 if c == PREEMPTED_EXIT_CODE]
                    if preempted and drain_deadline is None:
                        # Some worker(s) took a preemption exit: peers are
                        # draining too (the drivers agree per pass) — give
                        # them the grace window instead of killing them
                        # mid-checkpoint.
                        drain_deadline = time.monotonic() + drain_grace
                    if all(c is not None for c in codes):
                        if all(c == 0 for c in codes):
                            # Completed — even when a SIGTERM was
                            # forwarded mid-final-pass: the work is done;
                            # returning success beats telling the
                            # scheduler to retry a finished job. (Log
                            # close + heartbeat prune happen in the
                            # finally on the way out.)
                            return GangResult(
                                attempts=attempt + 1,
                                returncodes=[int(c) for c in codes],
                                log_paths=log_paths,
                                preemptions=preemptions,
                                budget_used=budget_used,
                                restart_delays=restart_delays,
                                resizes=resizes,
                                size_history=size_history,
                            )
                        # remaining codes are 75s (+0s; resize drains may
                        # add -15s — see ok_codes above): a clean drain
                        preempted_attempt = True
                        break
                    if drain_deadline is not None:
                        if time.monotonic() > drain_deadline:
                            # NOT a clean preemption: worker(s) hung
                            # through the grace window. Charge the budget
                            # (else a deterministic drain-wedge loops
                            # max_preemption_restarts times for free);
                            # a supervisor-SIGTERM drain still raises
                            # GangPreempted below regardless.
                            failed_why = (
                                "drain grace expired (worker(s) hung "
                                "during "
                                + ("resize" if resize_draining
                                   else "preemption")
                                + " drain)"
                            )
                            break
                    elif heartbeat_timeout is not None:
                        now = time.time()
                        for i, (hb, c) in enumerate(zip(hb_files, codes)):
                            if c is not None:
                                continue  # already exited 0; not hung
                            try:
                                last = os.path.getmtime(hb)
                            except OSError:
                                last = start
                            if now - max(last, start) > heartbeat_timeout:
                                failed_why = (f"worker {i} heartbeat silent "
                                              f"> {heartbeat_timeout}s")
                                break
                        if failed_why:
                            break
                    time.sleep(poll_interval)
            finally:
                _kill(procs, grace)
                for f in logs:
                    f.close()
                _prune_heartbeats(hb_files)

            if forwarded:
                step = None
                if launch_dirs is not None:
                    step = align_checkpoints(launch_dirs, log=echo)
                echo("supervisor: gang drained after SIGTERM"
                     + ("" if step is None else f"; resume step {step}"))
                raise GangPreempted(
                    f"gang drained after supervisor SIGTERM (attempt "
                    f"{attempt + 1}); resume from "
                    f"{'scratch' if step is None else f'step {step}'}",
                    step=step,
                )

            if preempted_attempt:
                if resize_draining:
                    # Operator-initiated drain: a RESIZE, not a preemption —
                    # it charges neither the failure budget nor the
                    # preemption cap, and the accounting must not inflate
                    # `preemptions` (tests and autoscalers key on it).
                    _apply_standing_resize("resize request")
                    echo(f"supervisor: gang attempt {attempt + 1} drained "
                         f"for resize — relaunching at size {cur_size} "
                         "without charging the restart budget")
                    attempt += 1
                    continue
                preemptions += 1
                if preemptions > max_preemption_restarts:
                    raise GangFailed(
                        f"gang preempted {preemptions} times "
                        f"(max_preemption_restarts={max_preemption_restarts})"
                        " — refusing to relaunch forever"
                    )
                # Capacity just changed under us: a standing resize request
                # rides along with the preemption relaunch (losing a slice
                # for good must shrink the gang, not crash-loop it).
                _apply_standing_resize("standing request at preemption "
                                       "relaunch")
                echo(f"supervisor: gang attempt {attempt + 1} preempted — "
                     "relaunching without charging the restart budget")
                attempt += 1
                continue

            echo(f"supervisor: gang attempt {attempt + 1} failed ({failed_why})")
            # Progress-aware budget: a failure AFTER the checkpoint advanced
            # is a workload that recovers — reset the crash-loop counter.
            if launch_dirs is not None:
                cur = _common_step(launch_dirs)
                if (cur is not None and last_step is not None
                        and cur > last_step and budget_used):
                    echo(f"supervisor: progress since last restart (step "
                         f"{last_step} -> {cur}) — resetting restart budget")
                    budget_used = 0
            budget_used += 1
            if budget_used > max_restarts:
                tails = []
                for i, path in enumerate(log_paths):
                    try:
                        with open(path) as f:
                            tails.append(
                                f"--- worker {i} (attempt {attempt + 1}) "
                                f"---\n{f.read()[-2000:]}"
                            )
                    except OSError:
                        pass
                raise GangFailed(
                    f"gang failed on attempt {attempt + 1} with the restart "
                    f"budget exhausted ({budget_used - 1}/{max_restarts} "
                    "non-progress restarts already used and another "
                    f"failure occurred; last: {failed_why})\n"
                    + "\n".join(tails)
                )
            if backoff_base > 0:
                delay = min(backoff_max,
                            backoff_base * 2 ** (budget_used - 1))
                delay *= random.uniform(0.5, 1.5)  # jitter: desync relaunches
                restart_delays.append(delay)
                echo(f"supervisor: backing off {delay:.2f}s before "
                     f"relaunch (failure {budget_used}/{max_restarts + 1})")
                deadline = time.monotonic() + delay
                while time.monotonic() < deadline:
                    if sigterm_box:
                        raise GangPreempted(
                            "supervisor SIGTERM during restart backoff",
                            step=(_common_step(launch_dirs)
                                  if launch_dirs else None),
                        )
                    time.sleep(min(poll_interval,
                                   max(deadline - time.monotonic(), 0.01)))
            # A standing resize request also applies to a FAILURE relaunch:
            # if the crash was the capacity change (peer slice gone for
            # good), relaunching at the old size would just fail again.
            # The failure itself stays charged above.
            _apply_standing_resize("standing request at failure relaunch")
            attempt += 1
    finally:
        if handler_installed:
            # getsignal-style None means the previous handler was set at
            # the C level (e.g. TSL's notifier); signal.signal(sig, None)
            # raises TypeError — fall back to the default disposition.
            signal.signal(
                signal.SIGTERM,
                old_handler if old_handler is not None else signal.SIG_DFL,
            )
        if hup_installed:
            signal.signal(
                signal.SIGHUP,
                old_hup if old_hup is not None else signal.SIG_DFL,
            )


__all__ = [
    "GangFailed",
    "GangPreempted",
    "GangResult",
    "PREEMPTED_EXIT_CODE",
    "align_checkpoints",
    "free_port",
    "run_gang",
]
