"""Device-mesh construction and sharding specs.

Replaces the reference's communication layer (SURVEY.md §2.4): its broadcast was
a /cpu:0 tf.Variable read by every GPU tower through an implicit H2D copy
(scripts/distribuitedClustering.py:199,221); its all-reduce was tf.add_n on the
CPU (:257-258). Here the data axis is a `jax.sharding.Mesh` axis: points are
sharded along it, centroids are replicated in HBM, and the reduce is a psum (or
an XLA-inserted all-reduce when using the auto-sharded jit path).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, axis_name: str = DATA_AXIS) -> Mesh:
    """1-D data-parallel mesh over the first `n_devices` devices.

    The reference selected GPUs uniformly at random *without a seed*
    (scripts/distribuitedClustering.py:69, defect 3); device choice here is
    deterministic: the first n in `jax.devices()` order.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n_devices]), (axis_name,))


def data_sharding(mesh: Mesh, axis_name: str = DATA_AXIS) -> NamedSharding:
    """Shard leading (points) axis across the mesh."""
    return NamedSharding(mesh, P(axis_name))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (centroids and other model state)."""
    return NamedSharding(mesh, P())


def pad_to_multiple(x, multiple: int, fill_value=np.nan):
    """Pad the leading axis to a multiple of `multiple` (mesh size) so the
    array is evenly shardable. Returns (padded, n_valid)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(np.asarray(x), pad_width, constant_values=fill_value), n


def shard_points(x, mesh: Mesh, axis_name: str = DATA_AXIS) -> jax.Array:
    """Place points on the mesh sharded along the data axis.

    Replaces the reference's tf.split-on-CPU + per-tower Variables staged
    through a full-dataset feed_dict (scripts/distribuitedClustering.py:197,217,273).
    """
    return jax.device_put(x, data_sharding(mesh, axis_name))


def replicate(x, mesh: Mesh) -> jax.Array:
    """Place an array fully replicated on every device of the mesh.

    On a multi-process mesh (devices this process cannot address) the value
    is assembled per process via make_array_from_callback — every host holds
    the same value by SPMD contract, so the result is a consistent global
    replicated array.
    """
    sharding = replicated_sharding(mesh)
    if any(d.process_index != jax.process_index()
           for d in mesh.devices.ravel()):
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )
    return jax.device_put(x, sharding)
