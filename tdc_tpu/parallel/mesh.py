"""Device-mesh construction and sharding specs.

Replaces the reference's communication layer (SURVEY.md §2.4): its broadcast was
a /cpu:0 tf.Variable read by every GPU tower through an implicit H2D copy
(scripts/distribuitedClustering.py:199,221); its all-reduce was tf.add_n on the
CPU (:257-258). Here the data axis is a `jax.sharding.Mesh` axis: points are
sharded along it, centroids are replicated in HBM, and the reduce is a psum (or
an XLA-inserted all-reduce when using the auto-sharded jit path).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"

# Two-level hierarchical data-parallel mesh axes (parallel/reduce.py): the
# outer axis crosses hosts (DCN — data-center network), the inner axis stays
# within a host's chips (ICI — inter-chip interconnect). A reduce over
# ("dcn", "ici") done ICI-first sends each host's payload over DCN once,
# instead of letting a flat ring drag every device's partial across the
# slow link (Mesh-TensorFlow's hierarchy argument, PAPERS.md).
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_mesh(n_devices: int | None = None, axis_name: str = DATA_AXIS) -> Mesh:
    """1-D data-parallel mesh over the first `n_devices` devices.

    The reference selected GPUs uniformly at random *without a seed*
    (scripts/distribuitedClustering.py:69, defect 3); device choice here is
    deterministic: the first n in `jax.devices()` order.
    """
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(f"requested {n_devices} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:n_devices]), (axis_name,))


def make_hierarchical_mesh(
    n_hosts: int | None = None, n_devices: int | None = None
) -> Mesh:
    """2-level (dcn, ici) data-parallel mesh: host axis × local-device axis,
    derived from the process structure of `jax.devices()` (devices grouped
    by process_index). On a single-process runtime (the CPU 8-device sim,
    or one host's chips) pass `n_hosts` to emulate the host grouping — the
    reduce structure is identical, only the link speeds differ.

    The streamed fits detect this mesh shape (`data_axes`) and reduce
    sufficient stats ICI-first: one intra-host psum, then one inter-host
    psum of the already-combined per-host payload.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if n_hosts is None:
        n_hosts = len({d.process_index for d in devs})
    if n_hosts <= 0 or len(devs) % n_hosts != 0:
        raise ValueError(
            f"{len(devs)} devices not divisible into {n_hosts} host groups"
        )
    # Group by process so the inner axis is genuinely intra-host when the
    # runtime is multi-process; a plain reshape would interleave hosts.
    ordered = sorted(devs, key=lambda d: (d.process_index, d.id))
    grid = np.asarray(ordered).reshape(n_hosts, len(devs) // n_hosts)
    if len({d.process_index for d in devs}) > 1:
        # The whole point of the mesh is that the ICI axis stays inside a
        # host; a row straddling processes (uneven per-host device counts,
        # or n_devices truncating mid-host) would silently run every
        # "intra-host" psum over DCN — and quantize the wrong stage.
        for i, row in enumerate(grid):
            procs = {d.process_index for d in row}
            if len(procs) != 1:
                raise ValueError(
                    f"hierarchical mesh row {i} spans processes "
                    f"{sorted(procs)}; the ici axis must be intra-host — "
                    "use one host group per process (or per same-host "
                    "process set) and per-host device counts divisible by "
                    "the group size"
                )
    return Mesh(grid, (DCN_AXIS, ICI_AXIS))


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axis names the points' leading dim shards over: ("dcn", "ici")
    for a hierarchical mesh, else the data axis. Reduction order is
    innermost-first (reversed), so hierarchical reduces run ICI before DCN.
    """
    names = tuple(mesh.axis_names)
    if DCN_AXIS in names and ICI_AXIS in names:
        return (DCN_AXIS, ICI_AXIS)
    if DATA_AXIS in names:
        return (DATA_AXIS,)
    return (names[0],)


def is_hierarchical(mesh: Mesh) -> bool:
    return len(data_axes(mesh)) > 1


def data_sharding(mesh: Mesh, axis_name: str | None = None) -> NamedSharding:
    """Shard leading (points) axis across the mesh (both host/device axes of
    a hierarchical mesh)."""
    if axis_name is not None:
        return NamedSharding(mesh, P(axis_name))
    axes = data_axes(mesh)
    return NamedSharding(mesh, P(axes if len(axes) > 1 else axes[0]))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (centroids and other model state)."""
    return NamedSharding(mesh, P())


def pad_to_multiple(x, multiple: int, fill_value=np.nan):
    """Pad the leading axis to a multiple of `multiple` (mesh size) so the
    array is evenly shardable. Returns (padded, n_valid)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_width = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(np.asarray(x), pad_width, constant_values=fill_value), n


def shard_points(x, mesh: Mesh, axis_name: str | None = None) -> jax.Array:
    """Place points on the mesh sharded along the data axis (or axes, for a
    hierarchical (dcn, ici) mesh).

    Replaces the reference's tf.split-on-CPU + per-tower Variables staged
    through a full-dataset feed_dict (scripts/distribuitedClustering.py:197,217,273).
    """
    return jax.device_put(x, data_sharding(mesh, axis_name))


def replicate(x, mesh: Mesh) -> jax.Array:
    """Place an array fully replicated on every device of the mesh.

    On a multi-process mesh (devices this process cannot address) the value
    is assembled per process via make_array_from_callback — every host holds
    the same value by SPMD contract, so the result is a consistent global
    replicated array.
    """
    sharding = replicated_sharding(mesh)
    if any(d.process_index != jax.process_index()
           for d in mesh.devices.ravel()):
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx]
        )
    return jax.device_put(x, sharding)
