"""Size-portable state redistribution: restore N-device state at M devices.

PR-3 fault tolerance is restart-shaped — a gang that dies at size N
relaunches at size N. Production TPU capacity is preemptible AND elastic:
a slice can disappear for good, or more capacity can be offered, and
either must be a recoverable event rather than a cold restart. This
module is the state half of that story (parallel/supervisor.py's resize
outcome is the control half): everything a checkpoint persists is kept
**layout-portable** (full host-side arrays), every checkpoint carries a
**layout manifest** recording the mesh it was saved under, and restore
routes placement through `redistribute`, which re-lays the state onto
whatever mesh the relaunched world actually has.

Redistribution strategy — per state kind:

- **Centroids / replicated accumulators** (the 1-D streamed fits): the
  checkpoint holds the full (K, d) fp32 array; placement at M devices is
  a broadcast. Bit-exact at any M by construction.
- **K-sharded state** (sharded_k's model-axis centroid and stats
  towers): persisted gathered (the _GatheringCheckpointer already
  assembles shards host-side); restore device_puts it under the NEW
  mesh's model sharding — the all-gather-then-slice form of portable
  collective redistribution (arXiv 2112.01075: any resharding is a
  sequence of gather/slice collectives; at checkpoint scale the gather
  already happened on the way to disk). Bit-exact: a slice of the same
  fp32 bytes. Requires K divisible by the new model extent — checked by
  the drivers with a clear error.
- **Deferred / error-feedback residual trees** (parallel/reduce's
  per-device partials, leading device axis): the semantic payload is the
  SUM over slots, so `redistribute_deferred` folds the N partials and
  re-expands onto M slots (total in slot 0, zeros elsewhere) — the
  invariant Σ_slots is preserved. NOTE: folding reorders the f32
  summation (exact in value-space only when the partials are exactly
  representable); that matches the EF contract, which is approximation
  state to begin with. This state is never checkpointed (quantized
  reduce rejects ckpt_dir) — the API serves in-process mesh swaps and
  the tests that pin the invariant.
- **The PR-5 HBM cache** is never persisted: a resized relaunch replans
  residency against the NEW per-device budget (device_cache.plan_residency
  with the new MeshSpec geometry) and either refills the cache during its
  first pass or degrades to streaming LOUDLY via the existing
  `residency_fallback` structlog event. Nothing to redistribute — by
  design the cache is derived state.

Observability: a restore whose manifest disagrees with the current
layout emits one `reshard_redistribute` structlog event (old → new) and
passes the `reshard.redistribute` fault point, so chaos specs can strike
exactly the resize-restore path; reading the manifest itself passes
`ckpt.restore.layout`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import numpy as np

from tdc_tpu.parallel.meshspec import MeshSpec
from tdc_tpu.testing.faults import fault_point

# Checkpoint-meta key prefix of the layout manifest (utils/checkpoint.py
# persists meta entries as plain npz arrays; the manifest is 5 ints).
LAYOUT_META_PREFIX = "layout_"


class LayoutManifest(NamedTuple):
    """The mesh layout a checkpoint was written under — enough to decide
    whether a restore is same-layout (plain placement) or a resize
    (redistribute + observability), and to explain either in logs."""

    n_devices: int
    n_processes: int
    n_data: int
    n_model: int
    hier: int  # 1 = hierarchical (dcn, ici) mesh, else 0

    def describe(self) -> str:
        return (f"{self.n_devices}dev/{self.n_processes}proc"
                f"(data={self.n_data},model={self.n_model}"
                f"{',hier' if self.hier else ''})")


def manifest_of(spec: MeshSpec) -> LayoutManifest:
    return LayoutManifest(
        n_devices=spec.n_devices,
        n_processes=spec.n_processes,
        n_data=spec.n_data,
        n_model=spec.n_model,
        hier=int(spec.kind == "hier"),
    )


def layout_meta(spec: MeshSpec) -> dict:
    """Checkpoint-meta entries for this layout (numeric, npz-safe)."""
    m = manifest_of(spec)
    return {LAYOUT_META_PREFIX + k: int(v) for k, v in m._asdict().items()}


def layout_from_meta(meta: dict) -> LayoutManifest | None:
    """Parse a checkpoint's layout manifest (None: pre-manifest
    checkpoint — restore then behaves as before, placement only). The
    `ckpt.restore.layout` fault point fires whenever a manifest is
    present, i.e. exactly when a resize-aware restore is in play."""
    key = LAYOUT_META_PREFIX + "n_devices"
    if meta is None or key not in meta:
        return None
    fault_point("ckpt.restore.layout")
    vals = {}
    for field in LayoutManifest._fields:
        v = meta.get(LAYOUT_META_PREFIX + field, 0)
        vals[field] = int(np.asarray(v))
    return LayoutManifest(**vals)


def redistribute(tree, old: LayoutManifest | None, spec: MeshSpec, place):
    """Place host-side checkpoint state onto `spec`'s mesh, redistributing
    from the layout it was saved under.

    `place(tree)` performs the actual mesh placement (driver-owned
    shardings: replicate for the 1-D fits, model-axis device_put for the
    K-sharded towers). This wrapper owns the resize semantics: when the
    saved layout differs from the current one it emits the
    `reshard_redistribute` event and passes the fault point, then places —
    the state is layout-portable host data, so redistribution IS
    placement under the new layout (see module docstring for why that is
    bit-exact per state kind).
    """
    cur = manifest_of(spec)
    if old is not None and old != cur:
        from tdc_tpu.utils.structlog import emit

        emit("reshard_redistribute",
             saved_layout=old.describe(), new_layout=cur.describe())
        fault_point("reshard.redistribute")
    return place(tree)


def redistribute_deferred(tree, n_slots: int, place=None):
    """Re-lay a deferred accumulator / error-feedback residual tree (per-
    device partials along a leading axis) from its current slot count to
    `n_slots`: fold the partials (their sum is the semantic payload) into
    slot 0 of a fresh zeros tree. `place(host_tree)` optionally puts the
    result onto the new mesh's deferred shardings; without it the host
    tree is returned (tests, or callers that place later).

    Invariant: sum over the leading axis is preserved (up to f32
    re-association of the fold — acceptable for EF state, whose contract
    is approximate; see module docstring)."""
    if n_slots < 1:
        raise ValueError(f"n_slots must be >= 1, got {n_slots}")

    def one(leaf):
        arr = np.asarray(leaf)
        if arr.ndim < 1:
            raise ValueError(
                "deferred leaves carry a leading device axis; got a scalar"
            )
        total = arr.sum(axis=0, dtype=arr.dtype)
        out = np.zeros((n_slots,) + arr.shape[1:], arr.dtype)
        out[0] = total
        return out

    host = jax.tree_util.tree_map(one, tree)
    return host if place is None else place(host)


def redistribute_gather_err(err, n_data: int, n_model: int, place=None):
    """Re-lay a sharded-finalize gather residual (parallel/gather.py EF
    state, `zero_finalize_err` layout: (slots, K, d) where slot i carries
    the residual rows of data-slice i within each model column, zeros
    elsewhere) onto a resized (n_data, n_model) mesh.

    A plain `redistribute_deferred` fold-to-slot-0 would preserve
    Σ_slots but ORPHAN re-injection: on the new mesh, device (i>0, j)
    reads only its own slice band of slot i, which the fold left zero.
    So: fold (Σ-preserving), then re-scatter the folded (K, d) residual
    map into the NEW slice partition — row r lands in the slot that owns
    r under the new (n_data, n_model) split, so every slice's next
    encode re-injects exactly its own rows' residual.

    Exact (no f32 re-association beyond the fold): slot bands are
    disjoint, so the fold is a permutation-free sum of non-overlapping
    rows."""
    folded = redistribute_deferred(err, 1)  # (1, K, d): slot 0 = Σ_slots
    full = folded[0]
    k = full.shape[0]
    if k % (n_model * n_data):
        raise ValueError(
            f"K={k} must divide over n_model={n_model} × n_data={n_data} "
            "to re-partition the gather residual"
        )
    rows = k // (n_model * n_data)
    out = np.zeros((n_data,) + full.shape, full.dtype)
    for j in range(n_model):
        base = j * (k // n_model)
        for i in range(n_data):
            lo = base + i * rows
            out[i, lo:lo + rows] = full[lo:lo + rows]
    return out if place is None else place(out)


__all__ = [
    "LAYOUT_META_PREFIX",
    "LayoutManifest",
    "layout_from_meta",
    "layout_meta",
    "manifest_of",
    "redistribute",
    "redistribute_deferred",
    "redistribute_gather_err",
]
