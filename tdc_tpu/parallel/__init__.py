"""Mesh construction, sharding helpers, explicit-collective steps, and
cross-device reduction strategies."""

from tdc_tpu.parallel.mesh import (
    make_mesh,
    make_hierarchical_mesh,
    shard_points,
    replicate,
    data_sharding,
    replicated_sharding,
)
from tdc_tpu.parallel.collectives import distributed_lloyd_stats, distributed_fuzzy_stats
from tdc_tpu.parallel.reduce import (
    GLOBAL_COMMS,
    CommsReport,
    ReduceStrategy,
    resolve_reduce,
)
from tdc_tpu.parallel.supervisor import (
    GangFailed,
    GangResult,
    align_checkpoints,
    run_gang,
)

__all__ = [
    "make_mesh",
    "make_hierarchical_mesh",
    "shard_points",
    "replicate",
    "data_sharding",
    "replicated_sharding",
    "distributed_lloyd_stats",
    "distributed_fuzzy_stats",
    "GLOBAL_COMMS",
    "CommsReport",
    "ReduceStrategy",
    "resolve_reduce",
    "GangFailed",
    "GangResult",
    "align_checkpoints",
    "run_gang",
]
