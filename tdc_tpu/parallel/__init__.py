"""Mesh construction, sharding helpers, and explicit-collective steps."""

from tdc_tpu.parallel.mesh import (
    make_mesh,
    shard_points,
    replicate,
    data_sharding,
    replicated_sharding,
)
from tdc_tpu.parallel.collectives import distributed_lloyd_stats, distributed_fuzzy_stats
from tdc_tpu.parallel.supervisor import (
    GangFailed,
    GangResult,
    align_checkpoints,
    run_gang,
)

__all__ = [
    "make_mesh",
    "shard_points",
    "replicate",
    "data_sharding",
    "replicated_sharding",
    "distributed_lloyd_stats",
    "distributed_fuzzy_stats",
    "GangFailed",
    "GangResult",
    "align_checkpoints",
    "run_gang",
]
