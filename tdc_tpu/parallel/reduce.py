"""Communication-efficient cross-device reduction of sufficient statistics.

Every streamed fit's inner loop ends in the same collective: a tree of
per-shard sufficient statistics — (K, d) sums, (K,) counts, a scalar cost —
all-reduced across the data-parallel mesh. At the flagship shape
(K=16,384, d=128) that payload is ~8.5 MB of f32, and the per-batch drivers
pay it once per streamed batch: a pass over B batches issues B cross-device
reduces where one would do. This module provides the three composable
levers that keep that reduction off the critical path (Mesh-TensorFlow's
hierarchy argument and EQuARX's quantized-allreduce argument, PAPERS.md):

1. **Deferred per-pass reduction** (`local_tree_stats` + `deferred_reduce`):
   accumulate stats device-locally across the whole pass — the accumulator
   grows a leading device axis and every per-batch add is shard-local —
   and cross-device-reduce ONCE per Lloyd/EM iteration. O(1) collectives
   per pass instead of O(num_batches). Off by default: it reorders f32
   summation (per-device-then-across-devices instead of
   per-batch-across-devices), so results match the per-batch path only to
   accumulation tolerance, not bitwise.

2. **Hierarchical reduction** (`tree_psum` over a (dcn, ici) mesh from
   `mesh.make_hierarchical_mesh`): psum the inner ICI axis first, then the
   outer DCN axis — each host's payload crosses the slow inter-host link
   once, already combined, instead of a flat ring dragging every device's
   partial across DCN. Numerically this is just a fixed two-level
   summation order; it composes with both per-batch and per-pass modes.

3. **Quantized reduce with error feedback** (`quantize="bf16"|"int8"`):
   encode the large rank-≥2 leaves (the (K, d) sums) on the wire — bf16,
   or int8 with a shared-per-row scale agreed via a pmax — and carry the
   per-device quantization residual in a persistent error-feedback
   accumulator that is re-injected into the NEXT pass's reduce, so the
   error is deferred, not lost (EF-SGD's trick applied to stats). Rank ≤1
   leaves (counts, scalars) always ride f32: they are tiny and the M-step
   divides by them. Per-pass mode only — the residual is defined per
   reduce, and one reduce per pass is what makes it cheap. On a
   hierarchical mesh only the DCN stage is quantized (ICI bandwidth is not
   the bottleneck; EQuARX makes the same split).

Instrumentation: `CommsCounter` tallies reduces issued and logical payload
bytes (the byte size of the reduced buffer per stage — a wire-format
model, not a link-level measurement). Drivers attach a `CommsReport` to
fit results and bump the process-wide `GLOBAL_COMMS`, which the serve
`/metrics` endpoint exposes.

The model-axis traffic of the K-sharded towers — the champion all_gathers
and the sharded-finalize centroid exchange (parallel/gather.py) — is
booked by the K-sharded streamed drivers into the SAME counters under
`axis="model"`, so `CommsReport.data_bytes`/`model_bytes` split the total
by mesh axis and `bench_comms` can price the gather= compression
independently of the reduce= compression.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tdc_tpu.parallel.compat import shard_map
from tdc_tpu.parallel.mesh import data_axes

_QUANT_MODES = (None, "bf16", "int8")
_MODES = ("per_batch", "per_pass")


@dataclass(frozen=True)
class ReduceStrategy:
    """How a streamed fit reduces its sufficient statistics across devices.

    mode: "per_batch" (the exact default — one reduce per streamed batch)
      or "per_pass" (device-local accumulation, one reduce per iteration).
    quantize: None | "bf16" | "int8" — wire encoding of the rank-≥2 stats
      leaves, per-pass mode only, with persistent error feedback.

    Hierarchical (ICI-then-DCN) reduction is not a flag here: it is derived
    from the mesh layout — pass a mesh from `make_hierarchical_mesh` and
    every strategy reduces in two stages.
    """

    mode: str = "per_batch"
    quantize: str | None = None

    def __post_init__(self):
        if self.mode not in _MODES:
            raise ValueError(
                f"reduce mode must be one of {_MODES}, got {self.mode!r}"
            )
        if self.quantize not in _QUANT_MODES:
            raise ValueError(
                f"quantize must be one of {_QUANT_MODES}, "
                f"got {self.quantize!r}"
            )
        if self.quantize is not None and self.mode != "per_pass":
            raise ValueError(
                "quantized stats reduce requires mode='per_pass' (the "
                "error-feedback residual is carried across passes; a "
                "per-batch residual would be meaningless)"
            )

    @property
    def deferred(self) -> bool:
        return self.mode == "per_pass"

    def label(self) -> str:
        return (
            self.mode if self.quantize is None
            else f"{self.mode}:{self.quantize}"
        )


def resolve_reduce(reduce) -> ReduceStrategy:
    """Accepts a ReduceStrategy, or the string shorthands "per_batch",
    "per_pass", "per_pass:bf16", "per_pass:int8"."""
    if isinstance(reduce, ReduceStrategy):
        return reduce
    if not isinstance(reduce, str):
        raise TypeError(
            f"reduce must be a str or ReduceStrategy, got {type(reduce)}"
        )
    mode, _, quant = reduce.partition(":")
    return ReduceStrategy(mode=mode, quantize=quant or None)


# ---------------------------------------------------------------------------
# Comms accounting
# ---------------------------------------------------------------------------


class CommsCounter:
    """Host-side tally of cross-device stats reduces issued and the logical
    payload bytes they moved (buffer size per reduce stage — each staged
    psum of a hierarchical reduce counts separately). Thread-safe: fits and
    the serve metrics scrape run on different threads."""

    def __init__(self, _mirror=None):
        self._lock = threading.Lock()
        self._mirror = _mirror
        self.reduces = 0
        self.gathers = 0
        self.logical_bytes = 0
        self.data_bytes = 0
        self.model_bytes = 0

    def add(self, reduces: int, nbytes: int, *, axis: str = "data",
            gathers: int = 0) -> None:
        """axis="data" books a stats reduce (the historical meaning);
        axis="model" books K-sharded gather traffic (champion all_gathers
        + the sharded-finalize exchange). logical_bytes stays the total
        across both axes."""
        with self._lock:
            self.reduces += int(reduces)
            self.gathers += int(gathers)
            self.logical_bytes += int(nbytes)
            if axis == "model":
                self.model_bytes += int(nbytes)
            else:
                self.data_bytes += int(nbytes)
        if self._mirror is not None:
            self._mirror.add(reduces, nbytes, axis=axis, gathers=gathers)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "reduces": self.reduces,
                "gathers": self.gathers,
                "logical_bytes": self.logical_bytes,
                "data_bytes": self.data_bytes,
                "model_bytes": self.model_bytes,
            }

    def reset(self) -> None:
        with self._lock:
            self.reduces = 0
            self.gathers = 0
            self.logical_bytes = 0
            self.data_bytes = 0
            self.model_bytes = 0


# Process-wide counter (mirrored into by every per-fit counter); surfaced
# by the serve /metrics endpoint as tdc_comms_stats_*.
GLOBAL_COMMS = CommsCounter()


class CommsReport(NamedTuple):
    """Per-fit communication summary attached to fit results.

    data_bytes/model_bytes split logical_bytes by mesh axis: data-axis
    stats reduces vs model-axis gathers (K-sharded champion all_gathers
    + the sharded-finalize centroid exchange; zero on 1-D fits). The
    trailing fields default so pre-split call sites keep working.
    """

    strategy: str  # ReduceStrategy.label()
    reduces: int  # cross-device stats reduces issued by this fit
    logical_bytes: int  # total logical payload bytes (both axes)
    passes: int  # full passes over the stream (iterations + final scoring)
    data_bytes: int = 0  # logical bytes of the data-axis stats reduces
    model_bytes: int = 0  # logical bytes of the model-axis gathers
    gathers: int = 0  # model-axis all_gathers issued by this fit

    @property
    def reduces_per_pass(self) -> float:
        return self.reduces / max(self.passes, 1)


def _quantized_leaf(t) -> bool:
    """Leaves that ride the quantized wire: the rank-≥2 float stats (the
    (K, d) sums and GMM second moments). Counts and scalars stay f32."""
    return t.ndim >= 2 and jnp.issubdtype(t.dtype, jnp.floating)


def tree_reduce_cost(tree, axes, quantize: str | None = None) -> tuple[int, int]:
    """(reduces, logical_bytes) for ONE reduce of a stats `tree` (LOGICAL
    reduced shapes, e.g. sums (K, d)) over mesh `axes`. Each staged psum
    counts as one reduce; int8 adds the per-row scale-agreement pmax."""
    leaves = jax.tree.leaves(tree)
    shapes = [t.shape for t in leaves]
    f32_payload = sum(4 * math.prod(s) for s in shapes)
    n_stages = len(axes)
    if quantize is None:
        return n_stages, n_stages * f32_payload
    # Hierarchical: only the LAST (DCN) stage is quantized.
    q_elem = 1 if quantize == "int8" else 2
    q_payload = 0
    for shp, t in zip(shapes, leaves):
        if len(shp) >= 2 and jnp.issubdtype(t.dtype, jnp.floating):
            rows = math.prod(shp[:-1])
            q_payload += q_elem * math.prod(shp)
            if quantize == "int8":
                q_payload += 4 * rows  # shared per-row f32 scales
        else:
            q_payload += 4 * math.prod(shp)
    reduces = n_stages
    nbytes = (n_stages - 1) * f32_payload + q_payload
    if quantize == "int8":
        # One scale-agreement pmax PER quantized leaf (tree_psum calls
        # _q_psum_leaf per leaf), each moving that leaf's f32 row maxes.
        q_leaves = [
            s for s, t in zip(shapes, leaves)
            if len(s) >= 2 and jnp.issubdtype(t.dtype, jnp.floating)
        ]
        reduces += len(q_leaves)
        nbytes += sum(4 * math.prod(s[:-1]) for s in q_leaves)
    return reduces, nbytes


# ---------------------------------------------------------------------------
# The reduction kernels (inside shard_map bodies)
# ---------------------------------------------------------------------------


def _q_psum_leaf(y, axis, quantize: str):
    """Quantized psum of one leaf over one mesh axis; returns (reduced f32,
    local residual) — the residual is this device's y − decode(encode(y)),
    the quantity error feedback carries to the next pass."""
    if quantize == "bf16":
        q = y.astype(jnp.bfloat16)
        out = jax.lax.psum(q, axis).astype(jnp.float32)
        return out, y - q.astype(jnp.float32)
    # int8: shared per-row scale agreed via pmax so every device's codes
    # decode identically; the sum itself is carried exactly (the codes are
    # small integers — f32 holds them losslessly; the wire format is int8).
    amax = jax.lax.pmax(jnp.max(jnp.abs(y), axis=-1, keepdims=True), axis)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(y / scale), -127.0, 127.0)
    return jax.lax.psum(q, axis) * scale, y - q * scale


def tree_psum(tree, axes, *, quantize: str | None = None, err=None):
    """Reduce a stats pytree over mesh `axes` inside a shard_map body.

    axes are reduced innermost-first (reversed), so a hierarchical
    (dcn, ici) mesh psums ICI then DCN. quantize encodes the rank-≥2 float
    leaves on the LAST (outermost / DCN) stage; `err` is the same-structure
    error-feedback tree added to those leaves before encoding. Returns
    (reduced_tree, new_err_tree) — new_err is None when quantize is None.

    Hierarchical + quantize ordering matters: the per-device residual is
    folded in BEFORE the ICI stage, so the DCN-stage encoder sees a value
    (and, for int8, agrees a scale) that is identical at every ICI
    position — otherwise each ICI position would quantize a different y
    and the "replicated" output would silently differ across the group.
    The new residual is then identical within each ICI group; it is stored
    scaled by 1/group_size so the NEXT pass's ICI psum reconstitutes
    exactly one copy of it.
    """
    order = tuple(reversed(axes))
    early, last = order[:-1], order[-1]
    if quantize is None:
        for ax in early:
            tree = jax.tree.map(lambda t: jax.lax.psum(t, ax), tree)
        return jax.tree.map(lambda t: jax.lax.psum(t, last), tree), None
    if err is None:
        err = jax.tree.map(jnp.zeros_like, tree)
    group = 1.0
    for ax in early:
        group = group * jax.lax.psum(1.0, ax)
    flat, treedef = jax.tree.flatten(tree)
    eflat = jax.tree.leaves(err)
    outs, resids = [], []
    for t, e in zip(flat, eflat):
        if _quantized_leaf(t):
            y = t + e
            for ax in early:
                y = jax.lax.psum(y, ax)
            out, resid = _q_psum_leaf(y, last, quantize)
            if early:
                resid = resid / group
        else:
            for ax in early:
                t = jax.lax.psum(t, ax)
            out, resid = jax.lax.psum(t, last), jnp.zeros_like(e)
        outs.append(out)
        resids.append(resid)
    return treedef.unflatten(outs), treedef.unflatten(resids)


def _data_spec(axes) -> P:
    return P(axes if len(axes) > 1 else axes[0])


def reduced_tree_stats(
    mesh, local_fn, n_data_args: int, n_args: int, axis_name=None
):
    """Per-batch reduced tower: the first `n_data_args` of `n_args` args are
    sharded on their leading axis over the mesh's data axes, the rest
    replicated; `local_fn(*args)`'s stats tree is psum'd over those axes
    (staged ICI-then-DCN on a hierarchical mesh) and returned replicated."""
    axes = (axis_name,) if axis_name is not None else data_axes(mesh)
    spec = _data_spec(axes)
    in_specs = tuple(
        spec if i < n_data_args else P() for i in range(n_args)
    )

    @partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=P(),
        check_vma=False,
    )
    def run(*args):
        return tree_psum(local_fn(*args), axes)[0]

    return run


# ---------------------------------------------------------------------------
# Deferred (per-pass) accumulation
# ---------------------------------------------------------------------------


def local_tree_stats(mesh, local_fn, n_data_args: int, n_args: int):
    """shard_map wrapper for deferred accumulation: the first `n_data_args`
    of `n_args` arguments are sharded on their leading axis over the mesh's
    data axes, the rest replicated. Runs `local_fn(*args)` per shard and
    returns its stats tree with a LEADING DEVICE AXIS (one slot per data
    shard) — no cross-device reduce anywhere; the per-batch accumulator add
    stays shard-local."""
    axes = data_axes(mesh)
    spec = _data_spec(axes)
    in_specs = tuple(
        spec if i < n_data_args else P() for i in range(n_args)
    )

    @partial(
        shard_map, mesh=mesh, in_specs=in_specs, out_specs=spec,
        check_vma=False,
    )
    def run(*args):
        local = local_fn(*args)
        return jax.tree.map(lambda t: t[None], local)

    return run


def deferred_reduce(mesh, quantize: str | None = None):
    """The ONE cross-device reduce of a deferred stats tree: returns a
    jit-able fn. Without quantize: fn(acc) → reduced tree (replicated).
    With quantize: fn(acc, err) → (reduced tree, new_err), err being the
    deferred-layout error-feedback tree (leading device axis)."""
    axes = data_axes(mesh)
    spec = _data_spec(axes)

    if quantize is None:

        @partial(
            shard_map, mesh=mesh, in_specs=(spec,), out_specs=P(),
            check_vma=False,
        )
        def run(acc):
            local = jax.tree.map(lambda t: t[0], acc)
            red, _ = tree_psum(local, axes)
            return red

        return run

    @partial(
        shard_map, mesh=mesh, in_specs=(spec, spec), out_specs=(P(), spec),
        check_vma=False,
    )
    def run_q(acc, err):
        local = jax.tree.map(lambda t: t[0], acc)
        e = jax.tree.map(lambda t: t[0], err)
        red, new_err = tree_psum(local, axes, quantize=quantize, err=e)
        return red, jax.tree.map(lambda t: t[None], new_err)

    return run_q


def make_deferred_fns(mesh, example_tree, tower, quantize: str | None):
    """The (zero_acc, acc_add, reduce) triple every deferred streamed
    driver shares — built from its stats `tower` (a local_tree_stats
    wrapper) and LOGICAL-shape `example_tree`: acc_add(acc, *tower_args)
    adds one batch's shard-local stats (zero collectives), reduce is the
    jitted once-per-pass cross-device reduce (with error feedback when
    quantized). Callers lru_cache per configuration (fresh jit closures
    per fit would re-trace every invocation)."""
    reducer = deferred_reduce(mesh, quantize)

    # Donate the accumulator: without it XLA keeps the old n_dev-times-
    # larger accumulator live while allocating the new one on EVERY batch
    # step — the same transient spike zero_deferred's sharding-first
    # allocation exists to avoid. (No caller reads an acc after passing it
    # back in; CPU backends ignore donation with a benign warning.)
    @partial(jax.jit, donate_argnums=(0,))
    def acc_add(acc, *args):
        return jax.tree.map(jnp.add, acc, tower(*args))

    zero_acc = lambda: zero_deferred(mesh, example_tree)
    return zero_acc, acc_add, jax.jit(reducer)


def zero_deferred(mesh, example_tree):
    """Deferred-layout zeros for `example_tree` (a stats tree of LOGICAL
    shapes, e.g. sums (K, d)): each leaf gains a leading device axis and is
    sharded over the mesh's data axes — the per-pass accumulator (and the
    quantized modes' error-feedback state) start here.

    Allocated sharding-first (jnp.zeros(device=sharding)) — this runs once
    per pass, and materializing the n_dev-times-larger accumulator on one
    device before resharding would cost n_dev× the steady-state per-device
    budget at exactly the large-K shapes per-pass mode targets."""
    axes = data_axes(mesh)
    n_dev = int(math.prod(mesh.devices.shape))
    sharding = NamedSharding(mesh, _data_spec(axes))

    def zero(t):
        return jnp.zeros((n_dev,) + tuple(t.shape), t.dtype, device=sharding)

    return jax.tree.map(zero, example_tree)
