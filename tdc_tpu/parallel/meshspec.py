"""MeshSpec: the one sharding-spec object the streamed drivers consult.

Before this module every driver re-derived its own mesh geometry — the
1-D streamed fits carried a cached `_mesh_layout(mesh)` tuple, the
K-sharded drivers read `mesh.devices.shape` directly, the residency
planners re-computed padding multiples and process scales from scratch,
and the CLI approximated all three. Size-portable state (checkpoint at N
devices, restore at M — parallel/reshard.py) makes that duplication a
correctness hazard: each copy is one more place a resize can disagree
about what the layout *is*.

MeshSpec generalizes the SNIPPETS.md sharding-utility pattern into the
single source of truth: built once per mesh (`MeshSpec.of`, cached — a
mesh is hashable and the lookup sits in streaming hot paths), it answers
every layout question the host-side driver code asks:

- **kind** — "single" (no mesh), "data1d" (1-D data-parallel), "hier"
  (the (dcn, ici) hierarchical mesh), "data_model" (the 2-D K-sharded
  layout);
- **batch staging geometry** — `pad_multiple` (the row multiple batches
  are zero-padded to before placement) and `process_scale` (how many
  global rows one local row represents: multi-process 1-D meshes stream
  per-host slices, the K-sharded drivers stream identical global
  batches);
- **placement** — `replicate` / `named(...)` shardings, mesh-aware so a
  single-device fit and an 8-way pod take the same code path.

The jit/lru-cached compute functions keep taking the raw `Mesh` (it is
the natural hashable static argument); MeshSpec is the HOST-side layout
algebra in the spirit of Mesh-TensorFlow's named-dimension layouts
(arXiv 1811.02084).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tdc_tpu.parallel import mesh as mesh_lib
from tdc_tpu.parallel.mesh import DATA_AXIS, DCN_AXIS, ICI_AXIS

MODEL_AXIS = "model"  # the K-sharded drivers' centroid axis (sharded_k)

KIND_SINGLE = "single"
KIND_DATA1D = "data1d"
KIND_HIER = "hier"
KIND_DATA_MODEL = "data_model"


@dataclass(frozen=True)
class MeshSpec:
    """Layout facts of one mesh (or of the no-mesh single-device path)."""

    mesh: Mesh | None
    kind: str
    n_devices: int
    n_processes: int
    n_local: int  # this process's devices in the mesh
    n_data: int  # data-axis extent (== n_devices off the 2-D layout)
    n_model: int  # model-axis extent (1 off the 2-D layout)

    # -- construction -----------------------------------------------------

    @staticmethod
    def of(mesh: Mesh | None) -> "MeshSpec":
        """The spec for `mesh` (None = the single-device path). Cached per
        mesh: this sits under the streamed per-batch staging loop, and
        scanning thousands of pod devices per batch would be real
        host-side overhead (the old _mesh_layout rationale)."""
        if mesh is None:
            return _SINGLE
        return _spec_of(mesh)

    # -- derived layout facts ---------------------------------------------

    @property
    def gang(self) -> bool:
        """Does the FIT span processes? (Then checkpoints run the gang
        single-writer protocol and preemption drains need gang
        agreement.)"""
        return self.n_processes > 1

    @property
    def pad_multiple(self) -> int:
        """Row multiple one staged batch is zero-padded to. Multi-process
        1-D meshes stage per-host slices (pad to the local device count);
        single-process meshes pad the global batch to the data extent.
        The K-sharded drivers additionally multiply by their block_rows."""
        if self.mesh is None:
            return 1
        if self.kind == KIND_DATA_MODEL:
            return self.n_data
        return max(self.n_local, 1) if self.gang else self.n_devices

    @property
    def process_scale(self) -> int:
        """Global rows one local batch row becomes: nproc when the 1-D
        drivers stream per-host slices; 1 when batches are already global
        (single process, or the K-sharded identical-global-batch
        contract)."""
        if self.gang and self.kind != KIND_DATA_MODEL:
            return self.n_processes
        return 1

    @property
    def data_axes(self) -> tuple[str, ...]:
        if self.mesh is None:
            return ()
        return mesh_lib.data_axes(self.mesh)

    def manifest_batches(self, n_batches: int) -> range:
        """This process's batch range of an `n_batches`-batch dataset
        manifest (data/manifest.py): disjoint contiguous per-process
        ranges exactly when the drivers stream PER-HOST slices
        (`process_scale > 1` — the 1-D gang contract), the full range
        when batches are already global (single process, or the
        K-sharded identical-global-batch contract). The same
        process_scale rule the staging geometry uses, so manifest
        assignment can never disagree with how the batch is staged."""
        from tdc_tpu.data.manifest import assign_batches

        if self.process_scale <= 1:
            return range(int(n_batches))
        return assign_batches(n_batches, self.n_processes,
                              jax.process_index())

    # -- placement --------------------------------------------------------

    def named(self, spec: P) -> NamedSharding:
        """A NamedSharding on this mesh (mesh required)."""
        if self.mesh is None:
            raise ValueError("named sharding needs a mesh (kind='single')")
        return NamedSharding(self.mesh, spec)

    def replicate(self, x):
        """Place `x` fully replicated (mesh-aware; plain device array on
        the single-device path)."""
        if self.mesh is None:
            return jax.numpy.asarray(x)
        return mesh_lib.replicate(x, self.mesh)


def _local_count(mesh: Mesh) -> int:
    pidx = jax.process_index()  # tdclint: disable=TDC101 membership count only: every host of a JAX mesh holds the same number of its own devices, so n_local is gang-uniform even though pidx is not
    return sum(d.process_index == pidx for d in mesh.devices.ravel())


@lru_cache(maxsize=64)
def _spec_of(mesh: Mesh) -> MeshSpec:
    names = tuple(mesh.axis_names)
    shape = tuple(mesh.devices.shape)
    n_devices = int(np.prod(shape))
    n_processes = len({d.process_index for d in mesh.devices.ravel()})
    n_local = _local_count(mesh)
    if MODEL_AXIS in names and DATA_AXIS in names:
        kind = KIND_DATA_MODEL
        n_data = int(shape[names.index(DATA_AXIS)])
        n_model = int(shape[names.index(MODEL_AXIS)])
    elif DCN_AXIS in names and ICI_AXIS in names:
        kind, n_data, n_model = KIND_HIER, n_devices, 1
    else:
        kind, n_data, n_model = KIND_DATA1D, n_devices, 1
    return MeshSpec(
        mesh=mesh, kind=kind, n_devices=n_devices, n_processes=n_processes,
        n_local=n_local, n_data=n_data, n_model=n_model,
    )


_SINGLE = MeshSpec(
    mesh=None, kind=KIND_SINGLE, n_devices=1, n_processes=1, n_local=1,
    n_data=1, n_model=1,
)


__all__ = [
    "KIND_DATA1D",
    "KIND_DATA_MODEL",
    "KIND_HIER",
    "KIND_SINGLE",
    "MODEL_AXIS",
    "MeshSpec",
]
