"""jax version compatibility for the explicit-collective towers.

`shard_map` has moved twice across the jax versions this repo meets in the
wild: `jax.experimental.shard_map.shard_map(check_rep=...)` (≤0.4.x),
`jax.shard_map(check_vma=...)` (≥0.6). The towers are written against the
new surface (check_vma); this shim presents exactly that surface on every
version, translating the replication-check kwarg when the installed jax
still calls it check_rep.
"""

from __future__ import annotations

import inspect

try:  # jax ≥ 0.6: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map
except ImportError:  # jax ≤ 0.4/0.5: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
_HAS_VMA = "check_vma" in _PARAMS


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True, **kw):
    """`jax.shard_map` with the modern kwarg surface on any jax version."""
    if not _HAS_VMA:
        kw["check_rep"] = check_vma
    else:
        kw["check_vma"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )
