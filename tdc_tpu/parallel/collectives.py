"""Explicit-collective (shard_map + psum) sufficient-statistics steps.

Two equivalent distributed paths exist in tdc_tpu:

1. **Auto-sharded jit** (default, models/kmeans.py): ops on globally-sharded
   arrays; the one-hot matmul contracts over the sharded N axis, so XLA inserts
   the all-reduce itself.
2. **Explicit shard_map** (this module): per-shard tower body + `jax.lax.psum`,
   mirroring the reference's tower/aggregate split
   (scripts/distribuitedClustering.py:207-263) but device-resident — the add_n
   on /cpu:0 becomes a psum over ICI.

Both produce bitwise-identical centroid updates in f32 (psum and XLA's
all-reduce use the same deterministic reduction order on TPU); the explicit path
exists for clarity, for tests of the collective math, and as the template for
multi-host DCN meshes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tdc_tpu.parallel.compat import shard_map

from tdc_tpu.ops.assign import SufficientStats, FuzzyStats, lloyd_stats, fuzzy_stats
from tdc_tpu.parallel.mesh import DATA_AXIS


def distributed_lloyd_stats(
    x: jax.Array,
    centroids: jax.Array,
    mesh: Mesh,
    axis_name: str = DATA_AXIS,
    kernel: str = "xla",
) -> SufficientStats:
    """Globally-reduced Lloyd stats: per-shard tower + psum.

    x must be sharded (axis_name) on its leading axis; centroids replicated.
    kernel='pallas' runs the fused single-pass VMEM kernel *inside* each
    shard_map body — per-device compute identical to the single-chip fast
    path, with only the (K, d) stats crossing ICI.
    """
    if kernel == "pallas":
        from tdc_tpu.ops.pallas_kernels import lloyd_stats_auto

        local_fn = lloyd_stats_auto
    else:
        local_fn = lloyd_stats

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    def step(x_shard, c):
        local = local_fn(x_shard, c)
        return jax.tree.map(lambda t: jax.lax.psum(t, axis_name), local)

    return step(x, centroids)


def distributed_fuzzy_stats(
    x: jax.Array,
    centroids: jax.Array,
    mesh: Mesh,
    m: float = 2.0,
    axis_name: str = DATA_AXIS,
    kernel: str = "xla",
) -> FuzzyStats:
    """Globally-reduced fuzzy c-means stats: per-shard tower + psum.
    kernel='pallas' runs the fused single-pass VMEM fuzzy kernel per shard
    (no (N, K) membership matrix anywhere)."""
    if kernel == "pallas":
        from tdc_tpu.ops.pallas_kernels import fuzzy_stats_auto

        local_fn = lambda x, c: fuzzy_stats_auto(x, c, m=m)
    else:
        local_fn = lambda x, c: fuzzy_stats(x, c, m=m)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        check_vma=False,
    )
    def step(x_shard, c):
        local = local_fn(x_shard, c)
        return jax.tree.map(lambda t: jax.lax.psum(t, axis_name), local)

    return step(x, centroids)
