"""Explicit-collective (shard_map + psum) sufficient-statistics steps.

Two equivalent distributed paths exist in tdc_tpu:

1. **Auto-sharded jit** (default, models/kmeans.py): ops on globally-sharded
   arrays; the one-hot matmul contracts over the sharded N axis, so XLA inserts
   the all-reduce itself.
2. **Explicit shard_map** (this module): per-shard tower body + `jax.lax.psum`,
   mirroring the reference's tower/aggregate split
   (scripts/distribuitedClustering.py:207-263) but device-resident — the add_n
   on /cpu:0 becomes a psum over ICI.

Both produce bitwise-identical centroid updates in f32 (psum and XLA's
all-reduce use the same deterministic reduction order on TPU); the explicit path
exists for clarity, for tests of the collective math, and as the template for
multi-host DCN meshes. That template is now concrete: pass a hierarchical
(dcn, ici) mesh (parallel/mesh.make_hierarchical_mesh) and the reduce runs
in two stages — intra-host ICI psum first, then one inter-host psum of the
already-combined per-host payload (parallel/reduce.tree_psum).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh

from tdc_tpu.ops.assign import SufficientStats, FuzzyStats, lloyd_stats, fuzzy_stats


def _reduced_tower(mesh: Mesh, local_fn, axis_name: str | None):
    """Shared shard_map wrapper: per-shard `local_fn(x_shard, c)` tower +
    staged psum over the mesh's data axes (ICI-first on a hierarchical
    mesh — parallel/reduce.tree_psum)."""
    from tdc_tpu.parallel.reduce import reduced_tree_stats

    return reduced_tree_stats(mesh, local_fn, 1, 2, axis_name=axis_name)


def distributed_lloyd_stats(
    x: jax.Array,
    centroids: jax.Array,
    mesh: Mesh,
    axis_name: str | None = None,
    kernel: str = "xla",
) -> SufficientStats:
    """Globally-reduced Lloyd stats: per-shard tower + psum.

    x must be sharded on its leading axis over the mesh's data axes
    (axis_name overrides; None derives them, including the hierarchical
    (dcn, ici) two-stage reduce); centroids replicated.
    kernel='pallas' runs the fused single-pass VMEM kernel *inside* each
    shard_map body — per-device compute identical to the single-chip fast
    path, with only the (K, d) stats crossing ICI.
    """
    if kernel == "pallas":
        from tdc_tpu.ops.pallas_kernels import lloyd_stats_auto

        local_fn = lloyd_stats_auto
    else:
        local_fn = lloyd_stats

    return _reduced_tower(mesh, local_fn, axis_name)(x, centroids)


def distributed_fuzzy_stats(
    x: jax.Array,
    centroids: jax.Array,
    mesh: Mesh,
    m: float = 2.0,
    axis_name: str | None = None,
    kernel: str = "xla",
) -> FuzzyStats:
    """Globally-reduced fuzzy c-means stats: per-shard tower + psum (staged
    ICI-then-DCN on a hierarchical mesh). kernel='pallas' runs the fused
    single-pass VMEM fuzzy kernel per shard (no (N, K) membership matrix
    anywhere)."""
    if kernel == "pallas":
        from tdc_tpu.ops.pallas_kernels import fuzzy_stats_auto

        local_fn = lambda x, c: fuzzy_stats_auto(x, c, m=m)
    else:
        local_fn = lambda x, c: fuzzy_stats(x, c, m=m)

    return _reduced_tower(mesh, local_fn, axis_name)(x, centroids)
