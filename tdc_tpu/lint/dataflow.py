"""Intraprocedural taint dataflow for the TDC1xx gang-divergence family.

The lexical TDC001 rule sees a collective *under* a `process_index()`
branch. The PR-18 padding-correction bug had no such shape: a host-local
quarantine verdict flowed through plain assignments into a replicated
scalar that fed the in-graph correction — the taint travelled through
*dataflow*, and the gang forked silently. This module is the
value-tracking half of the analyzer that catches that class: a
per-function control-flow graph built from `ast`, and a worklist taint
analysis over it. The whole-program half (call graph, per-function
summaries, fixpoint, finding emission) lives in
`tdc_tpu.lint.callgraph`; the rule surface is
`tdc_tpu.lint.rules_taint` (TDC101-TDC104).

Taint domain — a value's taint is a frozenset of *tokens*:

- a `str` source tag, one of the `SOURCE_*` families below: the value
  observably differs across gang processes (host identity, rank-like
  env reads, clocks, randomness, quarantine verdicts and retry
  counters, addressable-shard fetches);
- a `("param", i)` token: the value derives from the enclosing
  function's i-th parameter — the ingredient of the interprocedural
  param→return / param→sink summaries;
- a `("free", name)` token: the value derives from a free (closure)
  variable — resolved against the enclosing function's environment at
  the call site, so taint flows through closures.

What deliberately does NOT taint (the TDC001/TDC002 allowances,
preserved — pinned by tests/test_lint_dataflow.py):

- `process_count()` / `device_count()` / `axis_size(...)`: gang-uniform
  by definition;
- `len(x)`, `.shape`/`.ndim`/`.dtype` metadata: host metadata the
  drivers' equal-rows contract makes uniform;
- results of collectives: a psum/all_gather/process_allgather output is
  gang-AGREED — feeding a host-local value *into* a host-level
  agreement collective is the PR-18 *fix*, so those calls sanitize;
- `jax.make_array_from_process_local_data(...)`: the explicit
  "per-host slices, divergence is the point" staging constructor —
  the `_valid_arg` fix's shape;
- batch *data* reads (`read_batch` & co.): the data plane is sharded by
  design — every host's rows are supposed to differ, and they enter the
  graph through the staging constructors above. Divergence taint tracks
  *control* values derived from I/O (verdicts, counters, identity),
  which is exactly the PR-18 class.
"""

from __future__ import annotations

import ast
from collections import Counter
from dataclasses import dataclass, field

from tdc_tpu.lint.engine import call_name, dotted_name, last_seg, str_const

EMPTY: frozenset = frozenset()

# --------------------------------------------------------------------------
# Collectives (sinks for TDC101, content for TDC102/TDC103)
# --------------------------------------------------------------------------

# In-graph (traced) collectives: operands must be gang-uniform-or-sharded
# device values. A *tainted* (host-divergent, replicated) operand is the
# TDC101 sink: each process traces the same program over different
# "replicated" bytes and the state forks.
IN_GRAPH_COLLECTIVES = frozenset({
    "psum", "psum_scatter", "pmax", "pmin", "pmean",
    "all_gather", "allgather", "ppermute", "all_to_all", "pshuffle",
    "tree_psum",
})

# Host-level agreement collectives: feeding a host-local value in is the
# FIX (every process contributes, the collective agrees) — they sanitize
# and are never TDC101 sinks. They still count as collectives for
# TDC102/TDC103: reaching them divergently deadlocks the gang.
HOST_COLLECTIVES = frozenset({
    "process_allgather", "barrier", "sync_global_devices",
    "broadcast_one_to_all",
})

ALL_COLLECTIVES = IN_GRAPH_COLLECTIVES | HOST_COLLECTIVES

# --------------------------------------------------------------------------
# Sources
# --------------------------------------------------------------------------

# call last-segment -> tag
SOURCE_CALLS = {
    "process_index": "process_identity",
    "getpid": "process_identity",
    "gethostname": "process_identity",
    "getfqdn": "process_identity",
    # host-sharded device state pulled back to THIS host (PR-18's
    # "device_get of host-sharded data"): the addressable-shard
    # accessors are per-host by construction. Plain device_get is
    # pass-through — fetching a collective-agreed scalar is uniform.
    "addressable_data": "host_shard",
    # ingest / object-store *verdicts*: transient-failure
    # classification and integrity screens run on host-local reads.
    "screen_batch": "quarantine",
    "classify_error": "quarantine",
}

# exact dotted names -> tag (time.monotonic, random.random, ...).
# Matched EXACTLY, not by suffix: `jax.random.choice(key, ...)` is
# explicit-key PRNG (gang-uniform when the key is) and `np.random.*`
# generators are seeded — only the stdlib modules under their canonical
# names are host-divergence sources.
SOURCE_CALL_TAILS = {
    "time.time": "clock", "time.time_ns": "clock",
    "time.monotonic": "clock", "time.monotonic_ns": "clock",
    "time.perf_counter": "clock", "time.perf_counter_ns": "clock",
    "datetime.now": "clock", "datetime.utcnow": "clock",
    "datetime.today": "clock",
    "random.random": "random", "random.randint": "random",
    "random.randrange": "random", "random.choice": "random",
    "random.shuffle": "random", "random.getrandbits": "random",
    "uuid.uuid1": "random", "uuid.uuid4": "random",
    "os.urandom": "random",
    "secrets.token_hex": "random", "secrets.token_urlsafe": "random",
}

# attribute read -> tag: quarantine verdicts and retry counters are the
# host-local control outcomes PR-18's bug flowed into the graph.
SOURCE_ATTRS = {
    "quarantined": "quarantine",
    "quarantined_rows": "quarantine",
    "quarantined_batches": "quarantine",
    "crc_failures": "quarantine",
    "retries": "quarantine",
    "addressable_shards": "host_shard",
}

# $RANK-style env reads (the TDC001 hint list)
RANK_ENV_HINTS = ("PROCESS", "RANK", "HOST", "WORKER")

TAG_HELP = {
    "process_identity": "process_index()/host identity",
    "clock": "wall-clock reads",
    "random": "random/uuid",
    "quarantine": "quarantine verdicts / retry counters",
    "host_shard": "addressable-shard fetches",
    "env_rank": "rank-like environment reads",
}

# --------------------------------------------------------------------------
# Sanitizers
# --------------------------------------------------------------------------

SANITIZER_CALLS = frozenset({
    # gang-uniform by definition (the TDC001 process_count allowance)
    "process_count", "device_count", "local_device_count",
    "axis_size", "axis_index_groups",
    # host metadata (the TDC002 len/.shape allowance)
    "len",
    # the explicit per-host-sharded staging constructors: divergence is
    # declared and the downstream collective agrees it (the _valid_arg
    # fix)
    "make_array_from_process_local_data",
    "host_local_array_to_global_array",
}) | ALL_COLLECTIVES

METADATA_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "itemsize", "nbytes",
})


def real_tags(taint: frozenset) -> frozenset:
    return frozenset(t for t in taint if isinstance(t, str))


def param_ids(taint: frozenset) -> frozenset:
    return frozenset(
        t[1] for t in taint if isinstance(t, tuple) and t[0] == "param")


def free_names(taint: frozenset) -> frozenset:
    return frozenset(
        t[1] for t in taint if isinstance(t, tuple) and t[0] == "free")


def describe_tags(tags) -> str:
    return ", ".join(sorted(TAG_HELP.get(t, t) for t in tags))


# --------------------------------------------------------------------------
# Function summaries (the interprocedural currency)
# --------------------------------------------------------------------------


@dataclass
class Summary:
    """What a caller needs to know about a function, computed to fixpoint
    by callgraph.TaintProgram."""

    params: tuple = ()          # parameter names, index order
    ret: frozenset = EMPTY      # tokens flowing to return/yield
    sink_params: frozenset = EMPTY  # param indices reaching an in-graph
    #                               collective operand (transitively)
    sink_frees: frozenset = EMPTY   # free (closure) names reaching one —
    #                               resolved in the CALLER's environment
    collectives: tuple = ()     # sorted ((name, capped-count)) multiset,
    #                           callee-inclusive
    jitted: bool = False        # wrapped by jax.jit (decorator)
    static_params: frozenset = EMPTY   # declared-static positions
    static_names: frozenset = EMPTY    # declared-static kwarg names

    def key(self):
        return (self.ret, self.sink_params, self.sink_frees,
                self.collectives)

    def param_index(self, kw: str) -> int | None:
        try:
            return self.params.index(kw)
        except ValueError:
            return None

    def has_collective(self) -> bool:
        return bool(self.collectives)


_COUNT_CAP = 8  # recursion-safe multiset cap; arm comparison only needs
#                 "differs", not exact counts past this


def merge_collectives(*multisets) -> tuple:
    c: Counter = Counter()
    for m in multisets:
        for name, n in m:
            c[name] = min(_COUNT_CAP, c[name] + n)
    return tuple(sorted(c.items()))


def format_collectives(multiset: tuple) -> str:
    if not multiset:
        return "none"
    return ", ".join(f"{name} x{n}" if n > 1 else name
                     for name, n in multiset)


# --------------------------------------------------------------------------
# Per-function CFG
# --------------------------------------------------------------------------


class CFG:
    """Control-flow graph over one function body (or module body).

    Nodes are SIMPLE statements plus compound-statement *headers* (the
    ast.If/While/For node itself — its transfer evaluates the test/iter
    expression). Edges follow if/else joins, loop back-edges,
    break/continue, and return/raise exits; try-handlers are entered
    from every statement of the protected body (coarse but sound for a
    may-taint analysis)."""

    def __init__(self, body: list):
        self.nodes: list[ast.AST] = []
        self.preds: list[set[int]] = []
        self._loop_stack: list[tuple[list[int], list[int]]] = []
        # (continue-targets' pred-sets get the ids, break collectors)
        exits = self._seq(body, {-1})  # -1: virtual entry
        self.exit_preds = exits

    def _new(self, node: ast.AST, preds: set[int]) -> int:
        nid = len(self.nodes)
        self.nodes.append(node)
        self.preds.append(set(preds))
        return nid

    def _seq(self, stmts: list, preds: set[int]) -> set[int]:
        for stmt in stmts:
            preds = self._stmt(stmt, preds)
        return preds

    def _stmt(self, stmt: ast.stmt, preds: set[int]) -> set[int]:
        if isinstance(stmt, (ast.If,)):
            nid = self._new(stmt, preds)
            body_exits = self._seq(stmt.body, {nid})
            else_exits = self._seq(stmt.orelse, {nid})
            return body_exits | else_exits
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            nid = self._new(stmt, preds)
            self._loop_stack.append(([nid], []))
            body_exits = self._seq(stmt.body, {nid})
            _, breaks = self._loop_stack.pop()
            # back-edge: body exit (and continues) re-reach the header
            for p in body_exits:
                self.preds[nid].add(p)
            else_exits = self._seq(stmt.orelse, {nid})
            return {nid} | else_exits | set(breaks)
        if isinstance(stmt, (ast.Try,)):
            entry = set(preds)
            body_start = len(self.nodes)
            body_exits = self._seq(stmt.body, preds)
            body_ids = set(range(body_start, len(self.nodes)))
            handler_exits: set[int] = set()
            for handler in stmt.handlers:
                h_preds = entry | body_ids
                if handler.name:
                    nid = self._new(handler, h_preds)
                    h_preds = {nid}
                handler_exits |= self._seq(handler.body, h_preds)
            else_exits = self._seq(stmt.orelse, body_exits)
            out = (body_exits if not stmt.orelse else else_exits) \
                | handler_exits
            if stmt.finalbody:
                out = self._seq(stmt.finalbody, out)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            nid = self._new(stmt, preds)
            return self._seq(stmt.body, {nid})
        if isinstance(stmt, (ast.Break,)):
            if self._loop_stack:
                self._loop_stack[-1][1].extend(preds)
            return set()
        if isinstance(stmt, (ast.Continue,)):
            if self._loop_stack:
                header = self._loop_stack[-1][0][0]
                self.preds[header] |= preds
            return set()
        if isinstance(stmt, (ast.Return, ast.Raise)):
            nid = self._new(stmt, preds)
            self.exit_like = getattr(self, "exit_like", set())
            self.exit_like.add(nid)
            return set()
        # simple statement (Assign, Expr, FunctionDef, ...)
        nid = self._new(stmt, preds)
        return {nid}


# --------------------------------------------------------------------------
# The analysis
# --------------------------------------------------------------------------


class FunctionAnalysis:
    """One function's (or module body's) taint dataflow.

    `resolver(call) -> (Summary|None, info)` is provided by the call
    graph; `report_finding(code, node, message)` is set only on the final
    reporting pass — summary-fixpoint passes run with emission off.
    """

    def __init__(self, body: list, params: tuple = (),
                 base_env: dict | None = None, resolver=None,
                 local_names: frozenset = EMPTY,
                 uniform_lines: frozenset = EMPTY):
        self.body = body
        self.params = params
        self.base_env = dict(base_env or {})
        self.resolver = resolver or (lambda call: None)
        self.report_finding = None
        # names assigned anywhere in this scope — reads of anything else
        # are free/global (closure tokens)
        self.local_names = local_names
        # lines covered by a JUSTIFIED TDC10x waiver comment: the author
        # declares values produced there host-uniform-by-construction,
        # so source tags are cleared (an unjustified waiver clears
        # nothing — TDC100 flags it instead)
        self.uniform_lines = uniform_lines
        self.ret: frozenset = EMPTY
        self.sink_params: set = set()
        self.sink_frees: set = set()
        self.direct_collectives: Counter = Counter()
        self.callee_collective_sets: list = []
        self._env_in: list[dict] = []
        self.cfg: CFG | None = None

    # -- driving ----------------------------------------------------------

    def run(self) -> None:
        self.cfg = CFG(self.body)
        n = len(self.cfg.nodes)
        self._env_in = [dict() for _ in range(n)]
        self._env_out: list[dict | None] = [None] * n
        succs: list[list[int]] = [[] for _ in range(n)]
        for nid in range(n):
            for p in self.cfg.preds[nid]:
                if p != -1:
                    succs[p].append(nid)
        entry_env = dict(self.base_env)
        for i, name in enumerate(self.params):
            entry_env[name] = entry_env.get(name, EMPTY) | {("param", i)}
        work = list(range(n))
        queued = set(work)
        while work:
            nid = work.pop(0)
            queued.discard(nid)
            env = dict(entry_env)
            for p in self.cfg.preds[nid]:
                if p == -1:
                    continue
                prev = self._env_out[p]
                if prev:
                    for k, v in prev.items():
                        env[k] = env.get(k, EMPTY) | v
            self._env_in[nid] = env
            out = dict(env)
            self._transfer(self.cfg.nodes[nid], out)
            if self._env_out[nid] != out:
                self._env_out[nid] = out
                for succ in succs[nid]:
                    if succ not in queued:
                        queued.add(succ)
                        work.append(succ)

    def exit_env(self) -> dict:
        """Union of OUT-envs over every node — for module bodies, the
        global-name environment functions of that module inherit."""
        env: dict = dict(self.base_env)
        for out in self._env_out:
            if out:
                for k, v in out.items():
                    env[k] = env.get(k, EMPTY) | v
        return env

    def report(self, report_finding) -> None:
        """Re-run transfers over the solved envs with finding emission."""
        self.report_finding = report_finding
        try:
            for nid, node in enumerate(self.cfg.nodes):
                self._transfer(node, dict(self._env_in[nid]))
        finally:
            self.report_finding = None

    def env_at(self, node: ast.AST) -> dict:
        for nid, n in enumerate(self.cfg.nodes):
            if n is node:
                return self._env_in[nid]
        return {}

    # -- transfer ---------------------------------------------------------

    def _transfer(self, node: ast.AST, env: dict) -> None:
        if isinstance(node, (ast.Assign,)):
            taint = self.eval(node.value, env)
            for tgt in node.targets:
                self._bind(tgt, taint, env, value=node.value)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._bind(node.target, self.eval(node.value, env), env,
                           value=node.value)
        elif isinstance(node, ast.AugAssign):
            taint = self.eval(node.value, env)
            if isinstance(node.target, ast.Name):
                env[node.target.id] = (
                    env.get(node.target.id, EMPTY)
                    | self._read(node.target.id, env) | taint)
            else:
                self._bind(node.target, taint, env, augment=True)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            taint = self.eval(node.iter, env)
            self._bind(node.target, taint, env)
        elif isinstance(node, ast.While):
            self.eval(node.test, env)
        elif isinstance(node, ast.If):
            self.eval(node.test, env)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                taint = self.eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, taint, env)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.ret |= self.eval(node.value, env)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self.eval(node.exc, env)
        elif isinstance(node, ast.ExceptHandler):
            if node.name:
                env[node.name] = EMPTY
        elif isinstance(node, (ast.Expr,)):
            self.eval(node.value, env)
        elif isinstance(node, ast.Assert):
            self.eval(node.test, env)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    env.pop(tgt.id, None)
        # FunctionDef/ClassDef/Import/Global/Pass: no taint effect here
        # (nested defs are summarized by the call graph).

    def _bind(self, target: ast.AST, taint: frozenset, env: dict,
              value: ast.AST | None = None, augment: bool = False) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = (env.get(target.id, EMPTY) | taint
                              if augment else taint)
        elif isinstance(target, (ast.Tuple, ast.List)):
            elems = None
            if isinstance(value, (ast.Tuple, ast.List)) and \
                    len(value.elts) == len(target.elts) and \
                    not any(isinstance(e, ast.Starred) for e in target.elts):
                elems = [self.eval(e, env) for e in value.elts]
            for i, elt in enumerate(target.elts):
                if isinstance(elt, ast.Starred):
                    elt = elt.value
                self._bind(elt, elems[i] if elems is not None else taint,
                           env)
        elif isinstance(target, (ast.Attribute, ast.Subscript)):
            # obj.x = tainted / obj[k] = tainted: taint the whole object
            # (coarse, monotone)
            root = target
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name):
                env[root.id] = env.get(root.id, EMPTY) \
                    | self._read(root.id, env) | taint
        elif isinstance(target, ast.Starred):
            self._bind(target.value, taint, env)

    def _read(self, name: str, env: dict) -> frozenset:
        if name in env:
            return env[name]
        if name in self.base_env:
            return self.base_env[name]
        if name not in self.local_names:
            # free/global variable: a closure token the caller resolves
            return frozenset({("free", name)})
        return EMPTY

    # -- expressions ------------------------------------------------------

    def eval(self, node: ast.AST, env: dict) -> frozenset:
        out = self._eval(node, env)
        if out and self.uniform_lines and \
                getattr(node, "lineno", None) in self.uniform_lines:
            # declared host-uniform-by-construction: drop source tags,
            # keep the symbolic param/free tokens (they only encode
            # caller dependence, not divergence)
            out = frozenset(t for t in out if not isinstance(t, str))
        return out

    def _eval(self, node: ast.AST, env: dict) -> frozenset:
        if node is None:
            return EMPTY
        if isinstance(node, ast.Constant):
            return EMPTY
        if isinstance(node, ast.Name):
            return self._read(node.id, env)
        if isinstance(node, ast.NamedExpr):  # walrus
            taint = self.eval(node.value, env)
            self._bind(node.target, taint, env)
            return taint
        if isinstance(node, ast.Call):
            return self._call(node, env)
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value, env)
            if node.attr in METADATA_ATTRS:
                return EMPTY
            if node.attr in SOURCE_ATTRS:
                return base | {SOURCE_ATTRS[node.attr]}
            return base
        if isinstance(node, ast.Subscript):
            out = self.eval(node.value, env) | self.eval(node.slice, env)
            # os.environ["RANK"]-style reads are rank-hint sources too
            base_name = dotted_name(node.value)
            key = str_const(node.slice)
            if base_name and base_name.endswith("environ") and key and \
                    any(h in key.upper() for h in RANK_ENV_HINTS):
                out |= {"env_rank"}
            return out
        if isinstance(node, ast.BinOp):
            return self.eval(node.left, env) | self.eval(node.right, env)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.BoolOp):
            out = EMPTY
            for v in node.values:
                out |= self.eval(v, env)
            return out
        if isinstance(node, ast.Compare):
            out = self.eval(node.left, env)
            for c in node.comparators:
                out |= self.eval(c, env)
            return out
        if isinstance(node, ast.IfExp):
            return (self.eval(node.test, env) | self.eval(node.body, env)
                    | self.eval(node.orelse, env))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = EMPTY
            for e in node.elts:
                out |= self.eval(e, env)
            return out
        if isinstance(node, ast.Dict):
            out = EMPTY
            for k in node.keys:
                out |= self.eval(k, env)
            for v in node.values:
                out |= self.eval(v, env)
            return out
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            comp_env = dict(env)
            for gen in node.generators:
                src = self.eval(gen.iter, comp_env)
                self._bind(gen.target, src, comp_env)
                for cond in gen.ifs:
                    self.eval(cond, comp_env)
            if isinstance(node, ast.DictComp):
                return (self.eval(node.key, comp_env)
                        | self.eval(node.value, comp_env))
            return self.eval(node.elt, comp_env)
        if isinstance(node, ast.JoinedStr):
            out = EMPTY
            for v in node.values:
                out |= self.eval(v, env)
            return out
        if isinstance(node, ast.FormattedValue):
            return self.eval(node.value, env)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self.eval(node.value, env)
        if isinstance(node, ast.Yield):
            if node.value is not None:
                self.ret |= self.eval(node.value, env)
            return EMPTY
        if isinstance(node, ast.Lambda):
            return EMPTY
        if isinstance(node, ast.Slice):
            return (self.eval(node.lower, env) | self.eval(node.upper, env)
                    | self.eval(node.step, env))
        # anything else: union over children (sound default)
        out = EMPTY
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                out |= self.eval(child, env)
        return out

    # -- calls (sources, sanitizers, sinks, summaries) --------------------

    def _call(self, call: ast.Call, env: dict) -> frozenset:
        name = call_name(call)
        seg = last_seg(name)
        arg_taints = [self.eval(a, env) for a in call.args]
        kw_taints = {kw.arg: self.eval(kw.value, env)
                     for kw in call.keywords}
        all_args = EMPTY
        for t in arg_taints:
            all_args |= t
        for t in kw_taints.values():
            all_args |= t

        # env reads with rank-like hints are sources
        if (name or "").endswith("environ.get") or seg == "getenv":
            key = str_const(call.args[0]) if call.args else None
            if key and any(h in key.upper() for h in RANK_ENV_HINTS):
                return all_args | {"env_rank"}

        # intrinsic sources
        if seg in SOURCE_CALLS:
            return all_args | {SOURCE_CALLS[seg]}
        if name is not None and name in SOURCE_CALL_TAILS:
            return all_args | {SOURCE_CALL_TAILS[name]}

        # in-graph collective: tainted operand is THE TDC101 sink
        if seg in IN_GRAPH_COLLECTIVES:
            self.direct_collectives[seg] = min(
                _COUNT_CAP, self.direct_collectives[seg] + 1)
            for t in arg_taints[:1] + list(kw_taints.values()):
                # operand is arg 0 (axis names et al. carry no taint)
                self._sink_collective_operand(call, seg, t)
            return EMPTY  # the reduced/gathered result is gang-agreed
        if seg in HOST_COLLECTIVES:
            self.direct_collectives[seg] = min(
                _COUNT_CAP, self.direct_collectives[seg] + 1)
            return EMPTY  # host-level agreement: the fix, not the bug

        # sanitizers
        if seg in SANITIZER_CALLS:
            return EMPTY

        # resolved callee: apply its summary (shift=1 for bound-method
        # calls, whose param 0 is `self`)
        resolved = self.resolver(call)
        if resolved is not None:
            summary, shift = resolved
            return self._apply_summary(call, summary, arg_taints,
                                       kw_taints, env, shift)

        # unknown call: pure-function assumption — taint of the result is
        # the union of the inputs (and of the callee expression itself,
        # which makes functools.partial/tainted-closures compose for
        # free: `partial(f, tainted)` taints the partial object, calling
        # it taints the result).
        return all_args | self.eval(call.func, env)

    def _apply_summary(self, call: ast.Call, summary: Summary,
                       arg_taints: list, kw_taints: dict,
                       env: dict, shift: int = 0) -> frozenset:
        if summary.collectives:
            self.callee_collective_sets.append(summary.collectives)
        # TDC104: tainted value in a declared-static jit position
        if summary.static_params or summary.static_names:
            for i, t in enumerate(arg_taints):
                if (i + shift) in summary.static_params and real_tags(t):
                    self._emit_static(call, summary, t)
            for kw, t in kw_taints.items():
                if kw is None or not real_tags(t):
                    continue
                idx = summary.param_index(kw)
                if kw in summary.static_names or \
                        (idx is not None and idx in summary.static_params):
                    self._emit_static(call, summary, t)

        # param->sink: tainted value handed to a param that reaches an
        # in-graph collective operand inside the callee (the PR-18 bug's
        # interprocedural shape)
        for i, t in enumerate(arg_taints):
            if (i + shift) in summary.sink_params:
                self._sink_collective_operand(
                    call, f"(via parameter {i} of the callee)", t,
                    via=summary)
        for kw, t in kw_taints.items():
            idx = summary.param_index(kw) if kw else None
            if idx is not None and idx in summary.sink_params:
                self._sink_collective_operand(
                    call, f"(via parameter {kw!r} of the callee)", t,
                    via=summary)
        # closure->sink: a nested def's collective operand reads a free
        # variable — the variable lives in THIS scope, so its taint is
        # only knowable here
        for free in summary.sink_frees:
            self._sink_collective_operand(
                call, f"(via closed-over {free!r} of the callee)",
                self._read(free, env), via=summary)

        # param->return + closure->return
        out = frozenset(real_tags(summary.ret))
        for i in param_ids(summary.ret):
            if 0 <= i - shift < len(arg_taints):
                out |= arg_taints[i - shift]
        for kw, t in kw_taints.items():
            idx = summary.param_index(kw) if kw else None
            if idx is not None and idx in param_ids(summary.ret):
                out |= t
        for free in free_names(summary.ret):
            out |= self._read(free, env)
        return out

    # -- sink plumbing ----------------------------------------------------

    def _sink_collective_operand(self, call: ast.Call, what: str,
                                 taint: frozenset, via=None) -> None:
        tags = real_tags(taint)
        self.sink_params |= param_ids(taint)
        self.sink_frees |= free_names(taint)
        if tags and self.report_finding is not None:
            if via is None:
                msg = (
                    f"value derived from host-local state "
                    f"({describe_tags(tags)}) is an operand of in-graph "
                    f"collective '{what}' — each process contributes "
                    "different bytes to a nominally replicated value and "
                    "the gang state forks silently (the PR-18 "
                    "padding-correction bug class); agree it through a "
                    "host-level collective (process_allgather) or stage "
                    "it explicitly sharded "
                    "(make_array_from_process_local_data)"
                )
            else:
                msg = (
                    f"host-local value ({describe_tags(tags)}) flows "
                    f"into '{call_name(call)}' {what}, which reaches an "
                    "in-graph collective operand — a replicated scalar "
                    "fed from per-host state forks the gang's centroid "
                    "state (the PR-18 bug, interprocedurally); sum it "
                    "through the device collective instead (see "
                    "models/streaming._valid_arg)"
                )
            self.report_finding("TDC101", call, msg)

    def _emit_static(self, call: ast.Call, summary: Summary,
                     taint: frozenset) -> None:
        if self.report_finding is not None:
            self.report_finding(
                "TDC104", call,
                f"host-local value ({describe_tags(real_tags(taint))}) "
                f"flows into a declared-static argument of jitted "
                f"'{call_name(call)}' — each process specializes a "
                "DIFFERENT compiled program (per-host recompile fork): "
                "static args must be gang-uniform; derive them from "
                "process_count()/geometry, or make the argument traced",
            )

    # -- summary export ---------------------------------------------------

    def summary(self, jitted=False, static_params=EMPTY,
                static_names=EMPTY, callee_collectives=()) -> Summary:
        return Summary(
            params=tuple(self.params),
            ret=self.ret,
            sink_params=frozenset(self.sink_params),
            sink_frees=frozenset(self.sink_frees),
            collectives=merge_collectives(
                tuple(self.direct_collectives.items()),
                *callee_collectives),
            jitted=jitted,
            static_params=static_params,
            static_names=static_names,
        )


# --------------------------------------------------------------------------
# Helpers shared with callgraph/rules
# --------------------------------------------------------------------------


def param_names(func) -> tuple:
    """Positional(+kwonly) parameter names of a def, index order."""
    a = func.args
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    names += [p.arg for p in a.kwonlyargs]
    return tuple(names)


def assigned_names(body: list) -> frozenset:
    """Every name bound anywhere in a scope (assignments, loop targets,
    withitems, defs, imports, comprehension-free) — reads of anything
    else are free variables."""
    out: set[str] = set()

    def visit(stmts):
        for stmt in stmts:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    out.add(node.name)
                elif isinstance(node, ast.Name) and isinstance(
                        node.ctx, (ast.Store, ast.Del)):
                    out.add(node.id)
                elif isinstance(node, ast.ExceptHandler) and node.name:
                    out.add(node.name)
                elif isinstance(node, ast.alias):
                    out.add((node.asname or node.name).split(".")[0])
                elif isinstance(node, ast.arg):
                    out.add(node.arg)
    visit(body)
    return frozenset(out)
