"""TDC001 collective-divergence and TDC008 axis-name-mismatch.

SPMD correctness is a *sequence* property: every process must execute the
same collectives in the same order (Mesh-TensorFlow, arXiv:1811.02084).
The two rules here catch the lexical versions of breaking it; the
compile-time version (trace the jaxpr, compare the emitted collective
sequence) lives in tdc_tpu.lint.jaxpr_check.
"""

from __future__ import annotations

import ast

from tdc_tpu.lint.engine import (
    FileContext, call_name, last_seg, str_const, walk_calls,
)

# Collective operations — reaching any of these on a subset of processes
# deadlocks the rest (PR 3's mid-pass-stop bug: one worker stopped at a
# batch boundary the others sailed past, and the next pass's psum hung
# the gang). Matched on the final attribute segment so jax.lax.psum,
# lax.psum and bare psum all count.
COLLECTIVE_CALLS = frozenset({
    "psum", "psum_scatter", "pmax", "pmin", "pmean",
    "all_gather", "allgather", "ppermute", "all_to_all", "pshuffle",
    "tree_psum", "process_allgather", "barrier", "sync_global_devices",
})

# Condition ingredients that differ per process. jax.process_count() is
# deliberately absent: it is uniform across the gang, so branching on it
# is SPMD-safe (every process takes the same arm).
_HOST_LOCAL_CALLS = frozenset({"process_index", "gethostname", "getpid"})
_HOST_LOCAL_NAMES = frozenset({
    "process_index", "process_id", "proc_id", "host_id", "rank",
})
_HOST_LOCAL_ENV_HINTS = ("PROCESS", "RANK", "HOST", "WORKER")


def _is_host_local_cond(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            seg = last_seg(call_name(node))
            if seg in _HOST_LOCAL_CALLS:
                return True
            # os.environ.get("TDC_PROCESS_ID") and friends
            name = call_name(node) or ""
            if name.endswith("environ.get") or seg == "getenv":
                arg = str_const(node.args[0]) if node.args else None
                if arg and any(h in arg.upper()
                               for h in _HOST_LOCAL_ENV_HINTS):
                    return True
        elif isinstance(node, (ast.Name, ast.Attribute)):
            seg = node.id if isinstance(node, ast.Name) else node.attr
            if seg in _HOST_LOCAL_NAMES:
                return True
    return False


class CollectiveDivergence:
    code = "TDC001"
    name = "collective-divergence"
    description = (
        "a collective (psum/all_gather/barrier/...) is reached under a "
        "branch whose condition derives from process_index or other "
        "host-local state — only some processes arrive, the rest of the "
        "gang deadlocks"
    )

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.If, ast.IfExp)):
                test, bodies = node.test, []
                if isinstance(node, ast.If):
                    bodies = node.body + node.orelse
                else:
                    bodies = [node.body, node.orelse]
            elif isinstance(node, ast.While):
                test, bodies = node.test, node.body
            else:
                continue
            if not _is_host_local_cond(test):
                continue
            for sub in bodies:
                for call in walk_calls(sub):
                    seg = last_seg(call_name(call))
                    if seg in COLLECTIVE_CALLS:
                        yield ctx.finding(
                            self, call,
                            f"collective '{seg}' under a host-local branch "
                            f"(condition at line {test.lineno} derives from "
                            "process_index/host identity): processes that "
                            "skip this arm never join the collective and "
                            "the gang deadlocks; hoist the collective out "
                            "of the branch or gate on gang-uniform state",
                        )

    def finalize(self):
        return ()


# Collectives that NAME their axis -> positional index of the axis arg
# (axis_name= kwarg overrides either way).
_AXIS_USING = {
    "psum": 1, "psum_scatter": 1, "pmax": 1, "pmin": 1, "pmean": 1,
    "all_gather": 1, "ppermute": 1, "all_to_all": 1, "pshuffle": 1,
    "axis_index": 0, "axis_size": 0,
}
# Calls whose axis_name/axis_names kwarg DECLARES axes for a mapped region.
_AXIS_DECLARING = frozenset({
    "pmap", "shard_map", "smap", "xmap", "Mesh", "make_mesh",
    "AbstractMesh",
})


class AxisNameMismatch:
    code = "TDC008"
    name = "axis-name-mismatch"
    description = (
        "a collective names a mesh axis that no pmap/shard_map/Mesh/"
        "PartitionSpec in the file declares — the classic copy-paste "
        "between the flat and hierarchical (dcn, ici) towers"
    )

    def check(self, ctx: FileContext):
        declared: set[str] = set()
        bindings: dict[str, str] = {}  # NAME = "axis" constants

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                val = str_const(node.value)
                if isinstance(tgt, ast.Name) and val is not None:
                    bindings[tgt.id] = val
        for call in walk_calls(ctx.tree):
            seg = last_seg(call_name(call))
            if seg in _AXIS_USING:
                continue  # uses are checked in the second sweep
            for kw in call.keywords:
                if kw.arg in ("axis_name", "axis_names"):
                    declared.update(self._axis_strings(kw.value, bindings))
            if seg in ("Mesh", "AbstractMesh", "make_mesh") and \
                    len(call.args) >= 2:
                declared.update(self._axis_strings(call.args[1], bindings))
            if seg in ("P", "PartitionSpec"):
                for a in call.args:
                    declared.update(self._axis_strings(a, bindings))

        if not declared:
            return  # no declarations in scope — callers own the axes

        for call in walk_calls(ctx.tree):
            seg = last_seg(call_name(call))
            if seg not in _AXIS_USING:
                continue
            pos = _AXIS_USING[seg]
            axis_arg = None
            if len(call.args) > pos:
                axis_arg = call.args[pos]
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    axis_arg = kw.value
            if axis_arg is None:
                continue
            for axis in self._axis_strings(axis_arg, bindings):
                if axis not in declared:
                    yield ctx.finding(
                        self, call,
                        f"collective '{seg}' names axis {axis!r} but this "
                        f"file only declares axes "
                        f"{sorted(declared)} (pmap/shard_map/Mesh/"
                        "PartitionSpec) — a mismatched axis name fails at "
                        "trace time on the real mesh or, worse, binds to "
                        "the wrong axis of a reshaped hierarchical mesh",
                    )

    @staticmethod
    def _axis_strings(node: ast.AST, bindings: dict[str, str]):
        """Axis-name strings in an expression; Name nodes resolve through
        NAME = "axis" constants. An unresolvable Name contributes nothing
        — we cannot judge an axis we cannot see."""
        out = []
        for sub in ast.walk(node):
            s = str_const(sub)
            if s is not None:
                out.append(s)
            elif isinstance(sub, ast.Name) and sub.id in bindings:
                out.append(bindings[sub.id])
        return out

    def finalize(self):
        return ()
