"""TDC005 fault-point-drift, TDC006 structlog-event-drift, TDC007
nondeterministic-ckpt-path, TDC009 metric-name-drift, TDC010
span-name-drift.

All of these (except lexical TDC007) are *registry* rules: the value of
a fault-point name, a structlog event name, a checkpoint path, a
Prometheus series name, or a trace-span name lies entirely in other
code (and other people's greps/dashboards/merged timelines) finding it
later. Drift — a renamed point the chaos spec still targets, two
spellings of one event, a timestamp in a path a resume must re-derive,
a test asserting a metric the registry never exports, a span
merge_trace's phase attribution will never group — never fails a unit
test; it fails the 3 am postmortem. The registry rules are
whole-program checks (finalize())."""

from __future__ import annotations

import ast
import re

from tdc_tpu.lint.engine import (
    FileContext, Finding, call_name, dotted_name, last_seg, str_const,
    walk_calls,
)


class FaultPointDrift:
    code = "TDC005"
    name = "fault-point-drift"
    description = (
        "fault_point(...) call-site names must match the KNOWN_POINTS "
        "registry in testing/faults.py exactly, in both directions — a "
        "drifted name makes $TDC_FAULTS target nothing and the chaos "
        "test passes vacuously"
    )

    def __init__(self):
        self._calls: list[tuple[str, Finding]] = []  # (point, finding-at)
        self._registry: dict[str, Finding] | None = None
        self._registry_seen = False

    def check(self, ctx: FileContext):
        for call in walk_calls(ctx.tree):
            if last_seg(call_name(call)) != "fault_point" or not call.args:
                continue
            point = str_const(call.args[0])
            f = ctx.finding(self, call, "")
            if point is None:
                yield ctx.finding(
                    self, call.args[0],
                    "fault_point name must be a string literal — a "
                    "computed name cannot be cross-checked against the "
                    "registry (or grepped for in a chaos postmortem)",
                )
            else:
                self._calls.append((point, f))
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == "KNOWN_POINTS":
                self._registry_seen = True
                self._registry = {}
                for sub in ast.walk(node.value):
                    s = str_const(sub)
                    if s is not None:
                        self._registry[s] = ctx.finding(self, sub, "")

    def finalize(self):
        if not self._registry_seen:
            # Registry not in the linted file set (e.g. linting one file):
            # the cross-check cannot run; literal-ness was still enforced.
            return
        known = set(self._registry)
        called = {p for p, _ in self._calls}
        for point, at in self._calls:
            if point not in known:
                yield Finding(
                    self.code, self.name, at.path, at.line, at.col,
                    f"fault point {point!r} is not in testing/faults."
                    f"KNOWN_POINTS {sorted(known)} — add it to the "
                    "registry (and the module docstring) or fix the typo; "
                    "a $TDC_FAULTS spec targeting the registry name would "
                    "inject nothing here",
                    at.snippet,
                )
        # The uncalled-entry direction is only sound when the run
        # plausibly covers the call sites. Spot-checking faults.py alone
        # (scripts/lint.sh path/to/file.py) sees the registry but none of
        # the instrumented modules — every entry would falsely read as
        # uncalled. Heuristic: sweep only when call sites were seen in
        # >= 2 files (a tree-wide run) or in the registry's own file (the
        # self-contained single-file case).
        registry_paths = {at.path for at in self._registry.values()}
        call_paths = {at.path for _, at in self._calls}
        if len(call_paths) < 2 and not (call_paths & registry_paths):
            return
        for point in sorted(known - called):
            at = self._registry[point]
            yield Finding(
                self.code, self.name, at.path, at.line, at.col,
                f"registry entry {point!r} has no fault_point() call site "
                "anywhere in the linted tree — the instrumentation was "
                "removed or renamed; chaos specs targeting it pass "
                "vacuously",
                at.snippet,
            )


_EVENT_OK = re.compile(r"^[a-z][a-z0-9_.]*$")
_LOGGY_RECV = re.compile(r"log", re.IGNORECASE)


class StructlogEventDrift:
    code = "TDC006"
    name = "structlog-event-drift"
    description = (
        "structlog emit()/RunLog.event() names must be lowercase_snake "
        "string literals, with no near-duplicate spellings — the run log "
        "is an interface for greps and dashboards, and 'ckpt-restore' "
        "next to 'ckpt_restore' silently halves every query"
    )

    def __init__(self):
        self._names: dict[str, list[Finding]] = {}

    def check(self, ctx: FileContext):
        for call in walk_calls(ctx.tree):
            name = call_name(call)
            seg = last_seg(name)
            is_emit = seg == "emit" and (
                isinstance(call.func, ast.Name) or
                (name or "").startswith("structlog."))
            is_event = False
            if seg == "event" and isinstance(call.func, ast.Attribute):
                recv = dotted_name(call.func.value)
                is_event = bool(recv and _LOGGY_RECV.search(recv))
            if not (is_emit or is_event) or not call.args:
                continue
            ev = str_const(call.args[0])
            if ev is None:
                yield ctx.finding(
                    self, call.args[0],
                    "structlog event name must be a string literal "
                    "(f-strings/variables defeat grep and cardinality-"
                    "bound dashboards); put variability in fields, not "
                    "the event name",
                )
                continue
            if not _EVENT_OK.match(ev):
                yield ctx.finding(
                    self, call.args[0],
                    f"event name {ev!r} is not lowercase_snake "
                    "([a-z][a-z0-9_.]*) — mixed case/hyphens/spaces "
                    "fragment the run-log namespace",
                )
                continue
            self._names.setdefault(ev, []).append(
                ctx.finding(self, call.args[0], ""))

    def finalize(self):
        norm: dict[str, dict[str, list[Finding]]] = {}
        for ev, sites in self._names.items():
            norm.setdefault(
                ev.replace(".", "_"), {}
            )[ev] = sites
        for variants in norm.values():
            if len(variants) < 2:
                continue
            spellings = sorted(variants)
            for ev in spellings:
                for at in variants[ev]:
                    yield Finding(
                        self.code, self.name, at.path, at.line, at.col,
                        f"event name {ev!r} collides with "
                        f"{[s for s in spellings if s != ev]} after "
                        "normalization — one event, one spelling",
                        at.snippet,
                    )


_NONDET = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "uuid.uuid1", "uuid.uuid4", "os.urandom",
    "random.random", "random.randint", "random.randrange", "random.choice",
    "random.getrandbits", "secrets.token_hex", "secrets.token_urlsafe",
}
_CKPT_HINT = re.compile(r"ckpt|checkpoint|resume", re.IGNORECASE)


class NondeterministicCkptPath:
    code = "TDC007"
    name = "nondeterministic-ckpt-path"
    description = (
        "time/random/uuid feeding checkpoint filenames or resume logic — "
        "a path the writer derives from a clock is a path the resumer "
        "can never re-derive, and retention/scan logic silently skips it"
    )

    def check(self, ctx: FileContext):
        # Context = a checkpoint-named file, an enclosing function whose
        # name smells of checkpointing, or a SIMPLE statement that also
        # mentions a ckpt-ish string/identifier. For a compound statement
        # (while/if/for...) only its header counts — `while
        # time.monotonic() < deadline:` must not inherit checkpoint
        # context from an unrelated statement in its body.
        parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def enclosing(node, types):
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, types):
                    return cur
                cur = parents.get(cur)
            return None

        file_ckptish = bool(
            _CKPT_HINT.search(ctx.path.rsplit("/", 1)[-1].rsplit("\\", 1)[-1])
        )

        for call in walk_calls(ctx.tree):
            name = call_name(call)
            if name is None:
                continue
            tail = ".".join(name.split(".")[-2:])
            if tail not in _NONDET and name not in _NONDET:
                continue
            func = enclosing(
                call, (ast.FunctionDef, ast.AsyncFunctionDef))
            in_ckpt_func = bool(func and _CKPT_HINT.search(func.name))
            stmt = enclosing(call, (ast.stmt,))
            scan_root: ast.AST | None = stmt
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan_root = stmt.iter
            elif isinstance(stmt, (ast.While, ast.If)):
                scan_root = stmt.test
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef, ast.Try, ast.With)):
                scan_root = None  # header carries no expression of interest
            stmt_ckptish = False
            if scan_root is not None:
                for sub in ast.walk(scan_root):
                    s = str_const(sub)
                    ident = (
                        sub.id if isinstance(sub, ast.Name)
                        else sub.attr if isinstance(sub, ast.Attribute)
                        else None
                    )
                    if (s and _CKPT_HINT.search(s)) or \
                            (ident and _CKPT_HINT.search(ident)):
                        stmt_ckptish = True
                        break
            if in_ckpt_func or stmt_ckptish or \
                    (file_ckptish and func is not None):
                where = (
                    f"function {func.name}" if in_ckpt_func
                    else "statement touches checkpoint state"
                    if stmt_ckptish else "checkpoint module"
                )
                yield ctx.finding(
                    self, call,
                    f"nondeterministic '{tail}' in checkpoint context "
                    f"({where}): "
                    "a clock/random value flowing into a checkpoint path "
                    "or resume decision cannot be re-derived after a "
                    "crash — derive names from the step number; if this "
                    "value never reaches a persisted name (e.g. a tmp "
                    "suffix replaced atomically), annotate with "
                    "`# tdclint: disable=TDC007` and say why",
                )

    def finalize(self):
        return ()


# No trailing underscore: a "tdc_online_" literal is a PREFIX (string
# matching), not a series name.
_METRIC_NAME_OK = re.compile(r"^tdc_[a-z0-9_]*[a-z0-9]$")
_METRIC_SERIES_SUFFIX = re.compile(r"_(bucket|sum|count)$")
# Non-metric tdc_ string literals the codebase legitimately uses: the
# package's own name and the exit-barrier tag (parallel/multihost.barrier).
_NON_METRIC_LITERALS = frozenset({"tdc_tpu", "tdc_exit"})


class MetricNameDrift:
    code = "TDC009"
    name = "metric-name-drift"
    description = (
        "literal tdc_* metric names referenced anywhere must match the "
        "CATALOG registry in obs/metrics.py — a drifted name makes a "
        "dashboard query (or a /metrics test assertion) silently match "
        "nothing, the TDC006 structlog-event discipline applied to the "
        "Prometheus namespace"
    )

    def __init__(self):
        self._refs: list[tuple[str, Finding]] = []
        self._catalog: dict[str, Finding] | None = None
        self._catalog_seen = False

    def check(self, ctx: FileContext):
        # Any linted file assigning a CATALOG dict is treated as the
        # registry (the TDC005 KNOWN_POINTS approach — obs/metrics.py in
        # the real tree, a self-contained file in the fixtures). The
        # registry file's other literals are still collected as
        # references — definitions match `known` trivially, and a typo'd
        # literal inside the registry module deserves the same finding.
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "CATALOG"
                    and isinstance(node.value, ast.Dict)):
                continue
            self._catalog_seen = True
            self._catalog = {}
            for key in node.value.keys:
                s = str_const(key)
                if s is None:
                    yield ctx.finding(
                        self, key,
                        "CATALOG keys must be string literals — a "
                        "computed family name cannot be cross-checked "
                        "against references (or grepped for from a "
                        "dashboard)",
                    )
                    continue
                if not _METRIC_NAME_OK.match(s):
                    yield ctx.finding(
                        self, key,
                        f"metric family {s!r} is not tdc_-prefixed "
                        "lowercase_snake (tdc_[a-z0-9_]+) — one "
                        "namespace, one convention",
                    )
                    continue
                self._catalog[s] = ctx.finding(self, key, "")
        for node in ast.walk(ctx.tree):
            s = str_const(node)
            if (s is None or not s.startswith("tdc_")
                    or s in _NON_METRIC_LITERALS
                    or not _METRIC_NAME_OK.match(s)):
                continue
            self._refs.append((s, ctx.finding(self, node, "")))

    def finalize(self):
        if not self._catalog_seen:
            # Registry not in the linted file set (e.g. spot-checking one
            # file): the cross-check cannot run.
            return
        known = set(self._catalog or ())
        for ref, at in self._refs:
            base = _METRIC_SERIES_SUFFIX.sub("", ref)
            if ref in known or base in known:
                continue
            yield Finding(
                self.code, self.name, at.path, at.line, at.col,
                f"metric name {ref!r} is not registered in "
                "obs/metrics.CATALOG — register the family there (and in "
                "docs/OBSERVABILITY.md) or fix the typo; a dashboard or "
                "test referencing it matches no exported series",
                at.snippet,
            )


_SPAN_NAME_OK = re.compile(r"^[a-z][a-z0-9_]*$")
# obs.trace call shapes carrying a span/instant name: the name is arg 0
# for span()/instant(), arg 1 for timed_iter(it, name).
_SPAN_CALLS = {"span": 0, "instant": 0, "timed_iter": 1}


class SpanNameDrift:
    code = "TDC010"
    name = "span-name-drift"
    description = (
        "literal span names passed to obs.trace span()/instant()/"
        "timed_iter() must match the KNOWN_SPANS registry in obs/trace.py "
        "— a drifted name breaks merge_trace's phase grouping and the "
        "timeline column mapping silently (the TDC009 discipline applied "
        "to the trace namespace)"
    )

    def __init__(self):
        self._refs: list[tuple[str, Finding]] = []
        self._registry: dict[str, Finding] | None = None
        self._registry_seen = False

    def check(self, ctx: FileContext):
        # Any linted file assigning KNOWN_SPANS is treated as the registry
        # (obs/trace.py in the real tree, a self-contained file in the
        # fixtures) — the TDC005/TDC009 approach, charset-checked like
        # TDC009's catalog keys.
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "KNOWN_SPANS"):
                continue
            self._registry_seen = True
            self._registry = {}
            for sub in ast.walk(node.value):
                s = str_const(sub)
                if s is None:
                    continue
                if not _SPAN_NAME_OK.match(s):
                    yield ctx.finding(
                        self, sub,
                        f"span name {s!r} is not lowercase_snake "
                        "([a-z][a-z0-9_]*) — one trace namespace, one "
                        "convention",
                    )
                    continue
                self._registry[s] = ctx.finding(self, sub, "")
        for call in walk_calls(ctx.tree):
            seg = last_seg(call_name(call))
            if seg not in _SPAN_CALLS:
                continue
            # Only the obs.trace module's calls: a dotted receiver whose
            # path mentions `trace` (trace.span, obs.trace.instant).
            # trace.py's own bare internal calls (`span(name)` inside
            # timed_iter) pass a variable by design and are not call
            # sites of the literal interface.
            if not isinstance(call.func, ast.Attribute):
                continue
            recv = dotted_name(call.func.value) or ""
            if "trace" not in recv.split("."):
                continue
            pos = _SPAN_CALLS[seg]
            if len(call.args) <= pos:
                continue
            s = str_const(call.args[pos])
            if s is None:
                yield ctx.finding(
                    self, call.args[pos],
                    "span name must be a string literal — a computed name "
                    "cannot be cross-checked against KNOWN_SPANS, grouped "
                    "by merge_trace, or grepped from a timeline; put "
                    "variability in span args, not the name",
                )
                continue
            self._refs.append((s, ctx.finding(self, call.args[pos], "")))

    def finalize(self):
        if not self._registry_seen:
            # Registry not in the linted file set (spot-checking one
            # file): the cross-check cannot run; literal-ness was still
            # enforced.
            return
        known = set(self._registry or ())
        for ref, at in self._refs:
            if ref in known:
                continue
            yield Finding(
                self.code, self.name, at.path, at.line, at.col,
                f"span name {ref!r} is not registered in obs/trace."
                "KNOWN_SPANS — add it there (and to docs/OBSERVABILITY.md;"
                " the drift test pins the doc) or fix the typo; "
                "merge_trace and the timeline phase mapping will never "
                "see this span",
                at.snippet,
            )
