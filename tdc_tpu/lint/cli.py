"""`python -m tdc_tpu.lint` — the CLI over engine + baseline.

Exit codes: 0 clean (or fully grandfathered/suppressed), 1 findings —
or stale baseline entries on a gated full run (fix them with
--prune-baseline), 2 usage error. `--format=github` emits
workflow-command annotations; `--format=json` is the machine interface
(schema tested in tests/test_lint.py::test_json_schema).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from tdc_tpu.lint import baseline as baseline_mod
from tdc_tpu.lint.engine import Finding, all_rules, run_paths


def _fmt_text(findings: list[Finding]) -> str:
    return "\n".join(
        f"{f.location()}: {f.rule} {f.name}: {f.message}" for f in findings
    )


def _fmt_github(findings: list[Finding]) -> str:
    out = []
    for f in findings:
        # Workflow-command escaping: %0A etc. per GitHub's spec.
        msg = (f.message.replace("%", "%25").replace("\r", "%0D")
               .replace("\n", "%0A"))
        out.append(
            f"::error file={f.path},line={f.line},col={f.col},"
            f"title={f.rule} {f.name}::{msg}"
        )
    return "\n".join(out)


def _fmt_json(findings, result, base_res, elapsed) -> str:
    return json.dumps({
        "version": 1,
        "files": result.files,
        "elapsed_seconds": round(elapsed, 3),
        "counts": {
            "new": len(findings),
            "grandfathered": base_res.grandfathered if base_res else 0,
            "suppressed": result.suppressed,
            "stale_baseline": len(base_res.stale) if base_res else 0,
        },
        "findings": [
            {
                "rule": f.rule,
                "name": f.name,
                "path": f.path.replace("\\", "/"),
                "line": f.line,
                "col": f.col,
                "message": f.message,
                "snippet": f.snippet,
                "fingerprint": baseline_mod.fingerprint(f),
            }
            for f in findings
        ],
    }, indent=2)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tdc_tpu.lint",
        description="tdclint: zero-dependency SPMD static analysis "
                    "(docs/LINTING.md)",
    )
    p.add_argument("paths", nargs="*", help="files and/or directories")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text")
    p.add_argument("--baseline", metavar="PATH",
                   help="grandfathered-findings file (JSON)")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite --baseline from the current findings "
                        "(the ratchet: regenerate after fixing, never to "
                        "admit new findings)")
    p.add_argument("--prune-baseline", action="store_true",
                   help="drop stale --baseline entries (fingerprints no "
                        "longer matching any finding) and rewrite the "
                        "file; never admits new findings")
    p.add_argument("--select", metavar="CODES",
                   help="comma-separated rule codes to run (default: all)")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.code}  {r.name}\n    {r.description}")
        return 0
    if not args.paths:
        p.error("no paths given (try: python -m tdc_tpu.lint tdc_tpu/ tests/)")
    if args.write_baseline and not args.baseline:
        p.error("--write-baseline requires --baseline=PATH")
    if args.prune_baseline and not args.baseline:
        p.error("--prune-baseline requires --baseline=PATH")
    if args.prune_baseline and args.write_baseline:
        p.error("--prune-baseline and --write-baseline are exclusive "
                "(prune is the shrink-only subset of write)")
    if (args.write_baseline or args.prune_baseline) and args.select:
        # A baseline written from a rule subset's findings drops every
        # other rule's grandfathered entries — the rule-selection twin of
        # the partial-path wipe refused below.
        flag = "--write-baseline" if args.write_baseline \
            else "--prune-baseline"
        p.error(f"{flag} cannot be combined with --select "
                "(it would drop every unselected rule's baseline entries)")

    select = None
    if args.select:
        select = {c.strip().upper() for c in args.select.split(",")
                  if c.strip()}
        known = {r.code for r in all_rules()}
        bad = select - known - {"TDC000"}
        if bad:
            p.error(f"unknown rule codes: {sorted(bad)}")

    t0 = time.monotonic()
    try:
        result = run_paths(args.paths, select=select)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    elapsed = time.monotonic() - t0

    if args.write_baseline:
        # Partial-path guard: regenerating from a subset of the recorded
        # paths would rewrite the baseline with only that subset's
        # findings — silently wiping the ratchet for everything else.
        try:
            existing = baseline_mod.load(args.baseline)
        except FileNotFoundError:
            existing = None
        if existing is not None and \
                not baseline_mod.covers_run(existing, args.paths):
            print(
                f"tdclint: refusing --write-baseline: {args.baseline} was "
                f"generated from paths {existing.get('paths')} but this "
                f"run lints "
                f"{baseline_mod.normalize_paths(args.paths)} — a partial "
                "regeneration would drop every grandfathered finding "
                "outside this run. Re-run with the recorded paths (or "
                "delete the baseline file to rebase deliberately).",
                file=sys.stderr,
            )
            return 2
        baseline_mod.write(args.baseline, result.findings, args.paths)
        print(
            f"tdclint: baseline {args.baseline} written with "
            f"{len(result.findings)} grandfathered finding(s) across "
            f"{result.files} file(s)"
        )
        return 0

    base_res = None
    findings = result.findings
    full_run = True
    if args.baseline:
        try:
            base = baseline_mod.load(args.baseline)
        except FileNotFoundError:
            if args.prune_baseline:
                print(f"tdclint: --prune-baseline: {args.baseline} not "
                      "found — nothing to prune", file=sys.stderr)
                return 2
            print(
                f"tdclint: baseline {args.baseline} not found — treating "
                "every finding as new (generate it with --write-baseline)",
                file=sys.stderr,
            )
        else:
            base_res = baseline_mod.apply(findings, base)
            findings = base_res.new
            full_run = (baseline_mod.covers_run(base, args.paths)
                        and select is None)
            if not full_run:
                # Partial run (path subset OR rule subset): unmatched
                # baseline entries are expected, not stale — reporting
                # them (in any format), or letting --prune-baseline act
                # on them, steers the user into a ratchet-wiping partial
                # shrink.
                base_res.stale = []
                if args.prune_baseline:
                    print(
                        f"tdclint: refusing --prune-baseline: "
                        f"{args.baseline} was generated from paths "
                        f"{base.get('paths')} but this run lints "
                        f"{baseline_mod.normalize_paths(args.paths)} — "
                        "on a partial run most entries trivially match "
                        "nothing, and pruning them would wipe the "
                        "ratchet. Re-run with the recorded paths.",
                        file=sys.stderr,
                    )
                    return 2
            elif args.prune_baseline:
                removed = len(base_res.stale)
                baseline_mod.write(args.baseline, base_res.matched,
                                   args.paths)
                print(
                    f"tdclint: baseline {args.baseline} pruned — "
                    f"{removed} stale fingerprint(s) dropped or shrunk, "
                    f"{base_res.grandfathered} matched finding(s) kept",
                    file=sys.stderr,
                )
                base_res.stale = []

    if args.format == "json":
        print(_fmt_json(findings, result, base_res, elapsed))
    elif args.format == "github":
        if findings:
            print(_fmt_github(findings))
    else:
        if findings:
            print(_fmt_text(findings))
        gf = base_res.grandfathered if base_res else 0
        stale = len(base_res.stale) if base_res else 0
        summary = (
            f"tdclint: {len(findings)} new finding(s) in {result.files} "
            f"file(s) ({gf} grandfathered, {result.suppressed} suppressed"
            f"{', ' + str(stale) + ' STALE baseline entries' if stale else ''}"
            f") in {elapsed:.2f}s"
        )
        print(summary, file=sys.stderr)
        if stale:
            print(
                "tdclint: FAIL — stale baseline entries mean findings "
                "were fixed but their grandfathered budget lingers "
                "(headroom a regression could silently spend); shrink "
                "the file with --prune-baseline",
                file=sys.stderr,
            )
    # Stale entries gate exactly like findings, but only on a full run —
    # partial runs cleared base_res.stale above.
    stale_gate = bool(base_res and base_res.stale)
    return 1 if findings or stale_gate else 0


if __name__ == "__main__":
    sys.exit(main())
