"""The tdclint engine: file walking, suppression comments, rule driving.

Stdlib only (ast + tokenize) — see the package docstring for why that is
a hard requirement, not a style choice.

Rule protocol: a rule object carries `code`/`name`/`description`, a
per-file `check(ctx)` yielding Findings, and an optional whole-program
`finalize()` yielding Findings after every file was checked (the drift
rules cross-reference call sites against a registry that lives in a
different module, so they cannot judge file-by-file). Rule objects are
instantiated fresh per run; accumulating state on `self` during check()
is the supported idiom.

Suppressions (tokenize-driven, so strings that merely *contain* the
marker text never count):

    x = float(dev_val)        # tdclint: disable=TDC002
    # tdclint: disable-next-line=TDC001,TDC004
    offending_line()
    # tdclint: disable-file=TDC007     (anywhere in the file)

`disable=all` works in every position. Suppressed findings are counted
but never reported or gated on.

Directory walking skips `__pycache__`, hidden dirs, and any directory
containing a `.tdclint-exclude` marker file (the golden-fixture corpus
under tests/lint_fixtures/ is deliberate rule violations — it must not
fail the repo-wide run). Files passed explicitly on the command line are
always linted, marker or not: that is how the fixture tests lint the
fixtures.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass

EXCLUDE_MARKER = ".tdclint-exclude"

# Engine-level pseudo-rule: a file that does not parse cannot be analyzed
# — and a syntax error reaching CI is exactly what the old degraded
# `compileall` warning path let through. Gates like any other finding.
SYNTAX_ERROR_CODE = "TDC000"

# The codes group is anchored to CODE-shaped tokens (TDCnnn / all) so a
# trailing justification — "disable=TDC002 host-only row count", the form
# the rule messages tell users to write — is prose, not part of the list.
_SUPPRESS_RE = re.compile(
    r"#\s*tdclint:\s*(disable|disable-next-line|disable-file)\s*=\s*"
    r"((?:[A-Za-z]+\d+|all)(?:\s*,\s*(?:[A-Za-z]+\d+|all))*)",
    re.IGNORECASE,
)


@dataclass(frozen=True)
class Finding:
    rule: str  # "TDC001"
    name: str  # "collective-divergence"
    path: str  # as passed/walked (relative paths stay relative)
    line: int  # 1-based
    col: int  # 1-based (ast col_offset + 1)
    message: str
    snippet: str  # stripped source line — the baseline fingerprint input

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


@dataclass
class LintResult:
    findings: list[Finding]  # reported (post-suppression, pre-baseline)
    suppressed: int  # count silenced by tdclint: disable comments
    files: int  # files analyzed

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))


class FileContext:
    """One parsed file handed to each rule's check()."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        self.source = source
        self.tree = tree
        self.lines = source.splitlines()

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) + 1
        return Finding(rule.code, rule.name, self.path, line, col, message,
                       self.snippet(line))


# --------------------------------------------------------------------------
# Shared AST helpers (used by every rule module)
# --------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """'jax.lax.psum' for an Attribute chain, 'psum' for a Name, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def last_seg(name: str | None) -> str | None:
    return None if name is None else name.rsplit(".", 1)[-1]


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_calls(root: ast.AST):
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            yield node


# --------------------------------------------------------------------------
# Suppression comments
# --------------------------------------------------------------------------


class Suppressions:
    def __init__(self, source: str):
        self.file_codes: set[str] = set()  # 'ALL' sentinel or TDCnnn
        self.line_codes: dict[int, set[str]] = {}
        try:
            # stmt_start tracks the first line of the current LOGICAL
            # line: a trailing `# tdclint: disable=` on a black-wrapped
            # multi-line statement must cover the whole statement, whose
            # findings anchor to its first physical line.
            stmt_start: int | None = None
            tokens = tokenize.generate_tokens(io.StringIO(source).readline)
            for tok in tokens:
                if tok.type == tokenize.NEWLINE:
                    stmt_start = None
                    continue
                if tok.type != tokenize.COMMENT:
                    if stmt_start is None and tok.type not in (
                            tokenize.NL, tokenize.INDENT, tokenize.DEDENT,
                            tokenize.ENCODING, tokenize.ENDMARKER):
                        stmt_start = tok.start[0]
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                kind = m.group(1)
                codes = {
                    c.strip().upper() for c in m.group(2).split(",")
                    if c.strip()
                }
                if "ALL" in codes:
                    codes = {"ALL"}
                if kind == "disable-file":
                    self.file_codes |= codes
                elif kind == "disable-next-line":
                    self.line_codes.setdefault(
                        tok.start[0] + 1, set()
                    ).update(codes)
                else:  # disable — every line of the logical statement
                    for line in range(stmt_start or tok.start[0],
                                      tok.start[0] + 1):
                        self.line_codes.setdefault(
                            line, set()
                        ).update(codes)
        except (tokenize.TokenError, IndentationError):
            pass  # the parse error is reported separately

    def suppressed(self, finding: Finding) -> bool:
        if "ALL" in self.file_codes or finding.rule in self.file_codes:
            return True
        codes = self.line_codes.get(finding.line, ())
        return "ALL" in codes or finding.rule in codes


# --------------------------------------------------------------------------
# File collection
# --------------------------------------------------------------------------


def collect_files(paths: list[str]) -> list[str]:
    """Explicit files always included; directories walked recursively for
    .py, skipping __pycache__/hidden/.tdclint-exclude-marked dirs."""
    out: list[str] = []
    seen: set[str] = set()

    def add(p: str) -> None:
        key = os.path.abspath(p)
        if key not in seen:
            seen.add(key)
            out.append(p)

    for p in paths:
        if os.path.isfile(p):
            add(p)
            continue
        if not os.path.isdir(p):
            raise FileNotFoundError(f"tdclint: no such file or directory: {p}")
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs
                if not d.startswith(".") and d != "__pycache__"
                and not os.path.exists(os.path.join(root, d, EXCLUDE_MARKER))
            )
            if os.path.exists(os.path.join(root, EXCLUDE_MARKER)):
                dirs[:] = []
                continue
            for name in sorted(files):
                if name.endswith(".py"):
                    add(os.path.join(root, name))
    return out


# --------------------------------------------------------------------------
# The run
# --------------------------------------------------------------------------


def all_rules() -> list:
    """Fresh rule instances (rules keep per-run state on self)."""
    from tdc_tpu.lint.rules_collective import (
        CollectiveDivergence, AxisNameMismatch,
    )
    from tdc_tpu.lint.rules_hostsync import HostSyncInHotLoop, RecompileHazard
    from tdc_tpu.lint.rules_signal import SignalUnsafeHandler
    from tdc_tpu.lint.rules_drift import (
        FaultPointDrift, MetricNameDrift, NondeterministicCkptPath,
        SpanNameDrift, StructlogEventDrift,
    )
    from tdc_tpu.lint.rules_taint import taint_rules

    return [
        CollectiveDivergence(),
        HostSyncInHotLoop(),
        RecompileHazard(),
        SignalUnsafeHandler(),
        FaultPointDrift(),
        StructlogEventDrift(),
        NondeterministicCkptPath(),
        AxisNameMismatch(),
        MetricNameDrift(),
        SpanNameDrift(),
        # TDC1xx: the gang-divergence dataflow family — five rules
        # sharing ONE whole-program taint analysis per run.
        *taint_rules(),
    ]


def run_paths(paths: list[str], select: set[str] | None = None) -> LintResult:
    """Lint `paths` (files and/or directories) with every rule (or the
    `select` subset of codes). Returns reported findings with suppression
    comments already applied; baseline filtering is the caller's layer
    (tdc_tpu.lint.baseline)."""
    files = collect_files(paths)
    rules = [r for r in all_rules()
             if select is None or r.code in select]
    reported: list[Finding] = []
    suppressed = 0
    sups: dict[str, Suppressions] = {}

    for path in files:
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                source = f.read()
        except OSError as e:
            reported.append(Finding(
                SYNTAX_ERROR_CODE, "unreadable-file", path, 1, 1,
                f"cannot read file: {e}", ""))
            continue
        sups[path] = Suppressions(source)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            reported.append(Finding(
                SYNTAX_ERROR_CODE, "syntax-error", path, e.lineno or 1,
                (e.offset or 0) + 1, f"syntax error: {e.msg}",
                (e.text or "").strip()))
            continue
        ctx = FileContext(path, source, tree)
        for rule in rules:
            for finding in rule.check(ctx):
                if sups[path].suppressed(finding):
                    suppressed += 1
                else:
                    reported.append(finding)

    for rule in rules:
        for finding in rule.finalize():
            sup = sups.get(finding.path)
            if sup is not None and sup.suppressed(finding):
                suppressed += 1
            else:
                reported.append(finding)

    reported.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    # Identical (rule, location) duplicates collapse — nested control flow
    # can reach the same offending call twice (e.g. an if inside an if,
    # both with host-local conditions).
    deduped: list[Finding] = []
    seen_keys: set[tuple] = set()
    for f in reported:
        key = (f.rule, f.path, f.line, f.col)
        if key not in seen_keys:
            seen_keys.add(key)
            deduped.append(f)
    return LintResult(deduped, suppressed, len(files))
