"""Whole-program half of the TDC1xx gang-divergence analyzer.

`tdc_tpu.lint.dataflow` solves one function at a time; this module makes
those solutions compose across the package:

1. **Index**: every linted file is walked once — module-level functions,
   class methods, nested defs, import aliases, `@jax.jit` /
   `@partial(jax.jit, static_arg*)` decorations, and module-level
   `g_jit = jax.jit(g, static_arg*)` wrapper assignments.
2. **Summaries to fixpoint**: each function (and each module body, as a
   pseudo-function providing its module's global environment) is
   analyzed with the *current* summaries of its resolved callees; when a
   summary changes, its callers re-queue. Counters are capped, joins are
   unions — monotone, so the worklist terminates (recursion included).
3. **Report**: one final pass re-runs transfers over the solved
   environments with emission on (TDC101 sinks, TDC104 static-arg
   forks), then walks loop and branch headers for the control-flow
   sinks: TDC102 (tainted trip count / break guard of a
   collective-bearing loop) and TDC103 (tainted branch whose arms issue
   different collective multisets, callee-inclusive).

Call resolution is deliberately conservative: lexical scope (nested
defs), `self.`/`cls.` within the enclosing class, module-level names,
and import aliases — never a global "same last segment" match. An
unresolved call degrades to the pure-function assumption (result taint =
union of input taints), which keeps the analysis sound for
value-tracking without inventing edges.
"""

from __future__ import annotations

import ast
from collections import Counter, deque
from dataclasses import dataclass, field, replace

from tdc_tpu.lint import dataflow as df
from tdc_tpu.lint.engine import call_name, dotted_name, last_seg, str_const

EMPTY = df.EMPTY

_AMBIGUOUS = object()  # two indexed modules share a dotted suffix


# --------------------------------------------------------------------------
# Index records
# --------------------------------------------------------------------------


@dataclass
class FunctionInfo:
    qual: str               # unique id: "<modname>:<Outer.inner>"
    path: str
    node: object            # FunctionDef/AsyncFunctionDef; None = module body
    body: list
    params: tuple
    jitted: bool = False
    static_params: frozenset = EMPTY
    static_names: frozenset = EMPTY
    parent: str | None = None   # enclosing function's qual (nested defs)
    cls: str | None = None      # enclosing class name (methods + their nested)
    nested: dict = field(default_factory=dict)  # name -> qual
    local_names: frozenset = EMPTY
    summary: df.Summary = field(default_factory=df.Summary)
    analysis: object = None
    is_module: bool = False


@dataclass
class ModuleInfo:
    modname: str
    path: str
    tree: ast.AST
    alias: dict = field(default_factory=dict)    # local name -> dotted target
    top: dict = field(default_factory=dict)      # name -> qual
    classes: dict = field(default_factory=dict)  # cls -> {method -> qual}
    overlays: dict = field(default_factory=dict)
    # name -> (target_qual, static_params, static_names): jit wrappers
    env: dict = field(default_factory=dict)      # solved global taint env
    body_qual: str = ""


def _modname_for(path: str) -> list[str]:
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [x for x in p.split("/") if x not in ("", ".", "..")]
    if parts and parts[-1] == "__init__":
        parts.pop()
    return parts


def _static_kwargs(keywords) -> tuple[frozenset, frozenset]:
    nums: set[int] = set()
    names: set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
        elif kw.arg == "static_argnames":
            v = kw.value
            elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in elts:
                s = str_const(e)
                if s:
                    names.add(s)
    return frozenset(nums), frozenset(names)


def _jit_decoration(func) -> tuple[bool, frozenset, frozenset]:
    """(jitted, static positions, static names) from the decorator list."""
    jitted = False
    nums: frozenset = frozenset()
    names: frozenset = frozenset()
    for dec in func.decorator_list:
        if isinstance(dec, ast.Call):
            seg = last_seg(call_name(dec))
            if seg == "jit":
                jitted = True
                n2, s2 = _static_kwargs(dec.keywords)
                nums, names = nums | n2, names | s2
            elif seg == "partial" and dec.args and \
                    last_seg(dotted_name(dec.args[0])) == "jit":
                jitted = True
                n2, s2 = _static_kwargs(dec.keywords)
                nums, names = nums | n2, names | s2
        elif last_seg(dotted_name(dec)) == "jit":
            jitted = True
    return jitted, nums, names


# --------------------------------------------------------------------------
# The program
# --------------------------------------------------------------------------

_MAX_PASS_FACTOR = 12  # fixpoint safety valve: N functions get 12N analyses


class Program:
    """Index + summary fixpoint + reporting over a set of parsed files."""

    def __init__(self, files):
        """files: iterable of (path, ast.Module[, uniform_lines]) —
        uniform_lines are the justified-TDC10x-waiver lines where source
        tags are cleared (see rules_taint.uniform_lines)."""
        self.funcs: dict[str, FunctionInfo] = {}
        self.modules: dict[str, ModuleInfo] = {}       # keyed by path
        self.modules_by_name: dict[str, object] = {}   # dotted suffix -> mod
        self.callers: dict[str, set] = {}
        self.uniform: dict[str, frozenset] = {}
        for entry in files:
            path, tree = entry[0], entry[1]
            self.uniform[path] = frozenset(
                entry[2]) if len(entry) > 2 else frozenset()
            self._index_module(path, tree)

    # -- indexing ---------------------------------------------------------

    def _index_module(self, path: str, tree: ast.AST) -> None:
        parts = _modname_for(path)
        modname = ".".join(parts)
        mod = ModuleInfo(modname=modname, path=path, tree=tree,
                         body_qual=f"{modname}:<module>")
        self.modules[path] = mod
        for i in range(len(parts)):
            suffix = ".".join(parts[i:])
            if suffix in self.modules_by_name and \
                    self.modules_by_name[suffix] is not mod:
                self.modules_by_name[suffix] = _AMBIGUOUS
            else:
                self.modules_by_name[suffix] = mod

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.alias[alias.asname] = alias.name
                    else:
                        first = alias.name.split(".")[0]
                        mod.alias[first] = first
            elif isinstance(node, ast.ImportFrom):
                base = parts[:-node.level] if node.level else []
                if node.module:
                    base = base + node.module.split(".")
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    mod.alias[alias.asname or alias.name] = \
                        ".".join(base + [alias.name])

        # module body pseudo-function
        self.funcs[mod.body_qual] = FunctionInfo(
            qual=mod.body_qual, path=path, node=None, body=list(tree.body),
            params=(), local_names=df.assigned_names(tree.body),
            is_module=True)

        def walk(body, prefix, parent_qual, cls_name, register):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{modname}:{prefix}{stmt.name}"
                    jitted, nums, names = _jit_decoration(stmt)
                    fi = FunctionInfo(
                        qual=qual, path=path, node=stmt,
                        body=list(stmt.body),
                        params=df.param_names(stmt),
                        jitted=jitted, static_params=nums,
                        static_names=names, parent=parent_qual,
                        cls=cls_name,
                        local_names=df.assigned_names(stmt.body))
                    self.funcs[qual] = fi
                    register(stmt.name, qual)
                    walk(stmt.body, prefix + stmt.name + ".", qual,
                         cls_name, lambda n, q, fi=fi: fi.nested.update(
                             {n: q}))
                elif isinstance(stmt, ast.ClassDef) and parent_qual is None:
                    methods = mod.classes.setdefault(stmt.name, {})
                    walk(stmt.body, prefix + stmt.name + ".", None,
                         stmt.name, lambda n, q, m=methods: m.update(
                             {n: q}))

        walk(tree.body, "", None, None,
             lambda n, q: mod.top.update({n: q}))

        # module-level jit wrapper assignments: g_jit = jax.jit(g, ...)
        for stmt in tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and isinstance(stmt.value, ast.Call)
                    and last_seg(call_name(stmt.value)) == "jit"
                    and stmt.value.args
                    and isinstance(stmt.value.args[0], ast.Name)):
                continue
            target_qual = mod.top.get(stmt.value.args[0].id)
            if target_qual is None:
                continue
            nums, names = _static_kwargs(stmt.value.keywords)
            mod.overlays[stmt.targets[0].id] = (target_qual, nums, names)

    # -- call resolution --------------------------------------------------

    def _find_by_dotted(self, dotted: str):
        """'pkg.mod.func' or 'pkg.mod.Cls.meth' -> qual, via the longest
        indexed module prefix."""
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.modules_by_name.get(".".join(parts[:cut]))
            if mod is None or mod is _AMBIGUOUS:
                continue
            rest = parts[cut:]
            if len(rest) == 1:
                if rest[0] in mod.overlays:
                    return ("overlay", mod.overlays[rest[0]])
                if rest[0] in mod.top:
                    return ("fn", mod.top[rest[0]], 0)
            if len(rest) == 2:
                q = mod.classes.get(rest[0], {}).get(rest[1])
                if q:
                    return ("fn", q, 0)  # unbound Cls.meth(obj, ...): no shift
            return None
        return None

    def _resolve(self, call: ast.Call, finfo: FunctionInfo):
        """-> ('fn', qual, shift) | ('overlay', (qual, nums, names)) | None"""
        name = call_name(call)
        if name is None:
            return None
        parts = name.split(".")
        mod = self.modules.get(finfo.path)
        if mod is None:
            return None

        if parts[0] in ("self", "cls") and len(parts) == 2 and finfo.cls:
            q = mod.classes.get(finfo.cls, {}).get(parts[1])
            return ("fn", q, 1) if q else None

        if len(parts) == 1:
            n = parts[0]
            f = finfo
            while f is not None:  # lexical chain of nested defs
                if n in f.nested:
                    return ("fn", f.nested[n], 0)
                f = self.funcs.get(f.parent) if f.parent else None
            if n in mod.overlays:
                return ("overlay", mod.overlays[n])
            if n in mod.top:
                return ("fn", mod.top[n], 0)
            target = mod.alias.get(n)
            if target:
                return self._find_by_dotted(target)
            return None

        if parts[0] in mod.classes and len(parts) == 2:
            q = mod.classes[parts[0]].get(parts[1])
            return ("fn", q, 0) if q else None

        target = mod.alias.get(parts[0])
        if target:
            return self._find_by_dotted(".".join([target] + parts[1:]))
        return None

    def _summary_for(self, resolved) -> tuple[df.Summary, int] | None:
        if resolved is None:
            return None
        if resolved[0] == "overlay":
            qual, nums, names = resolved[1]
            base = self.funcs[qual].summary
            return (replace(base, jitted=True,
                            static_params=base.static_params | nums,
                            static_names=base.static_names | names), 0)
        _, qual, shift = resolved
        return (self.funcs[qual].summary, shift)

    # -- fixpoint ---------------------------------------------------------

    def solve(self) -> None:
        order = ([q for q, f in self.funcs.items() if f.is_module]
                 + [q for q, f in self.funcs.items() if not f.is_module])
        work = deque(order)
        queued = set(order)
        budget = _MAX_PASS_FACTOR * max(1, len(order))
        while work and budget > 0:
            budget -= 1
            qual = work.popleft()
            queued.discard(qual)
            finfo = self.funcs[qual]
            changed = self._analyze(finfo)
            if changed:
                for caller in sorted(self.callers.get(qual, ())):
                    if caller not in queued:
                        queued.add(caller)
                        work.append(caller)

    def _analyze(self, finfo: FunctionInfo) -> bool:
        mod = self.modules[finfo.path]

        def resolver(call):
            resolved = self._resolve(call, finfo)
            if resolved is None:
                return None
            qual = resolved[1][0] if resolved[0] == "overlay" \
                else resolved[1]
            self.callers.setdefault(qual, set()).add(finfo.qual)
            return self._summary_for(resolved)

        analysis = df.FunctionAnalysis(
            finfo.body, params=finfo.params,
            base_env={} if finfo.is_module else mod.env,
            resolver=resolver, local_names=finfo.local_names,
            uniform_lines=self.uniform.get(finfo.path, frozenset()))
        analysis.run()
        finfo.analysis = analysis

        if finfo.is_module:
            new_env = analysis.exit_env()
            if new_env != mod.env:
                mod.env = new_env
                # every function in this module inherits the global env
                for qual, f in self.funcs.items():
                    if f.path == finfo.path and not f.is_module:
                        self.callers.setdefault(
                            finfo.qual, set()).add(qual)
                return True
            return False

        new_summary = analysis.summary(
            jitted=finfo.jitted, static_params=finfo.static_params,
            static_names=finfo.static_names,
            callee_collectives=analysis.callee_collective_sets)
        if new_summary.key() != finfo.summary.key():
            finfo.summary = new_summary
            return True
        finfo.summary = new_summary
        return False

    # -- reporting --------------------------------------------------------

    def report(self) -> list:
        """-> [(code, path, node, message)] after solve()."""
        out: list = []
        for finfo in self.funcs.values():
            if finfo.analysis is None:
                continue

            def report_finding(code, node, message, _f=finfo):
                out.append((code, _f.path, node, message))

            finfo.analysis.report(report_finding)
            self._control_sinks(finfo, out)
        return out

    # -- TDC102 / TDC103 --------------------------------------------------

    def _stmts_collectives(self, finfo: FunctionInfo, stmts: list) -> tuple:
        c: Counter = Counter()
        sets: list = []

        def visit(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                    continue  # not executed here
                if isinstance(child, ast.Call):
                    seg = last_seg(call_name(child))
                    if seg in df.ALL_COLLECTIVES:
                        c[seg] = min(8, c[seg] + 1)
                    else:
                        r = self._summary_for(self._resolve(child, finfo))
                        if r is not None and r[0].collectives:
                            sets.append(r[0].collectives)
                visit(child)

        for stmt in stmts:
            visit(stmt)
        return df.merge_collectives(tuple(c.items()), *sets)

    @staticmethod
    def _has_break(stmts: list) -> bool:
        def visit(node) -> bool:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.While, ast.For, ast.AsyncFor,
                                      ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # break binds to the inner loop
                if isinstance(child, ast.Break):
                    return True
                if visit(child):
                    return True
            return False
        for stmt in stmts:
            if isinstance(stmt, ast.Break):
                return True
            if not isinstance(stmt, (ast.While, ast.For, ast.AsyncFor,
                                     ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)) and visit(stmt):
                return True
        return False

    def _break_guards(self, loop) -> list:
        """If-headers inside `loop` (not inside nested loops) whose
        subtree contains a break of THIS loop."""
        guards: list = []

        def scan(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor,
                                     ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.If):
                    if self._has_break(stmt.body) or \
                            self._has_break(stmt.orelse):
                        guards.append(stmt)
                    scan(stmt.body)
                    scan(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    scan(stmt.body)
                elif isinstance(stmt, ast.Try):
                    scan(stmt.body)
                    for h in stmt.handlers:
                        scan(h.body)
                    scan(stmt.orelse)
                    scan(stmt.finalbody)

        scan(loop.body)
        return guards

    def _control_sinks(self, finfo: FunctionInfo, out: list) -> None:
        analysis = finfo.analysis
        idx = {id(n): i for i, n in enumerate(analysis.cfg.nodes)}

        def taint_of(expr, anchor) -> frozenset:
            nid = idx.get(id(anchor))
            env = analysis._env_in[nid] if nid is not None else {}
            return df.real_tags(analysis.eval(expr, dict(env)))

        for node in analysis.cfg.nodes:
            if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                coll = self._stmts_collectives(finfo, node.body)
                if not coll:
                    continue
                header = node.test if isinstance(node, ast.While) \
                    else node.iter
                tags = taint_of(header, node)
                what = "trip count" if not isinstance(node, ast.While) \
                    else "loop condition"
                if tags:
                    out.append((
                        "TDC102", finfo.path, node,
                        f"host-local state ({df.describe_tags(tags)}) "
                        f"controls the {what} of a loop that issues "
                        f"collectives ({df.format_collectives(coll)}) — "
                        "processes disagree on the iteration count and "
                        "the gang deadlocks mid-collective; agree the "
                        "count first (process_allgather/psum the "
                        "driver value)"))
                    continue
                for guard in self._break_guards(node):
                    gtags = taint_of(guard.test, guard)
                    if gtags:
                        out.append((
                            "TDC102", finfo.path, guard,
                            "host-local state "
                            f"({df.describe_tags(gtags)}) controls a "
                            "break out of a loop that issues collectives "
                            f"({df.format_collectives(coll)}) — one "
                            "process exits while the rest wait in the "
                            "collective (gang deadlock); make the exit "
                            "decision collectively (psum/process_"
                            "allgather the stop flag, as the drivers' "
                            "shift-convergence loops do)"))
            elif isinstance(node, ast.If):
                tags = taint_of(node.test, node)
                if not tags:
                    continue
                body_c = self._stmts_collectives(finfo, node.body)
                else_c = self._stmts_collectives(finfo, node.orelse)
                if body_c != else_c:
                    out.append((
                        "TDC103", finfo.path, node,
                        f"branch condition is host-local "
                        f"({df.describe_tags(tags)}) and the arms issue "
                        f"different collectives (then: "
                        f"{df.format_collectives(body_c)}; else: "
                        f"{df.format_collectives(else_c)}) — processes "
                        "take different paths and the collective "
                        "schedules diverge (the invariant tdcverify "
                        "proves per golden at the IR level); hoist the "
                        "collectives out of the branch or agree the "
                        "condition first"))


# --------------------------------------------------------------------------
# Entry point for the rules
# --------------------------------------------------------------------------


def analyze_program(files) -> list:
    """files: [(path, tree)] -> [(code, path, node, message)]."""
    prog = Program(files)
    prog.solve()
    return prog.report()
