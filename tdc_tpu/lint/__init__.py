"""tdclint — the repo's own SPMD static-analysis suite (docs/LINTING.md).

Zero third-party imports by design: the CI image ships no ruff, and the
lint gate must never again silently degrade to a warning because a linter
is missing (scripts/ci_tier1.sh pre-PR-4). Everything here is stdlib
`ast` + `tokenize`; `python -m tdc_tpu.lint tdc_tpu/ tests/` runs on a
bare Python 3.10.

The rules are not generic style checks — each codifies a bug CLASS this
repo has already paid for once (see docs/LINTING.md for the ancestry):

    TDC001  collective-divergence        gang deadlock (PR 3 mid-pass stop)
    TDC002  host-sync-in-hot-loop        erased comms wins (PR 2)
    TDC003  recompile-hazard             serve zero-recompile contract
    TDC004  signal-unsafe-handler        reentrant print in SIGTERM (PR 3)
    TDC005  fault-point-drift            vacuously-green chaos tests
    TDC006  structlog-event-drift        ungreppable run logs
    TDC007  nondeterministic-ckpt-path   bit-identical resume contract
    TDC008  axis-name-mismatch           hierarchical-mesh psum axes (PR 2)

`jaxpr_check` (the compile-time companion) lives in this package but is
imported only by tests and explicit callers — it needs jax; the CLI and
the engine never touch it.
"""

from tdc_tpu.lint.engine import Finding, LintResult, all_rules, run_paths

__all__ = ["Finding", "LintResult", "all_rules", "run_paths"]
