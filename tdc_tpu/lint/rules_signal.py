"""TDC004 signal-unsafe-handler.

PR 3's chaos soak found the exact crash this rule now catches statically:
`structlog.emit`/`print` inside the SIGTERM handler writes to a buffered
stderr stream — if the signal interrupted a write already in progress,
Python raises RuntimeError('reentrant call inside <_io.BufferedWriter>')
*inside the handler*, killing the very worker the handler was draining.
The contract (utils/preempt._on_signal documents it): a signal handler
may set flags and do ONE raw `os.write`; everything else waits until the
drain path acts on the flag outside async-signal context.

Scope: handlers are resolved per module (a function passed to
`signal.signal` by name or as a lambda), and the call graph is followed
transitively through same-module function definitions. Cross-module
calls are not followed — a helper imported from another module that
prints will be caught when that module's own handler registration is
linted, or by review; the rule stays zero-false-positive on the common
shape.
"""

from __future__ import annotations

import ast

from tdc_tpu.lint.engine import FileContext, call_name, last_seg, walk_calls

# Buffered/allocating calls that are unsafe in async-signal context.
_BANNED_NAMES = frozenset({"print", "open"})
_BANNED_LAST = frozenset({"emit", "warn"})  # structlog.emit, warnings.warn
# NB: a bare ".log" method is NOT here — math.log/np.log would false-
# positive; loggers reached via .info/.warning/... already identify it.
_BANNED_METHODS = frozenset({
    "info", "warning", "error", "debug", "exception", "critical",
    "event",  # RunLog.event — buffered file append
})
_BANNED_DOTTED_SUFFIX = ("stderr.write", "stdout.write")
_LOGGING_ROOTS = ("logging.",)


def _banned(call: ast.Call) -> str | None:
    name = call_name(call)
    if name is None:
        return None
    seg = last_seg(name)
    if name == "os.write":
        return None  # THE async-signal-safe way to leave a breadcrumb
    if name in _BANNED_NAMES or seg in _BANNED_NAMES:
        return f"'{name}' (buffered/allocating I/O)"
    if seg in _BANNED_LAST:
        return f"'{name}' (buffered logging)"
    if name.startswith(_LOGGING_ROOTS):
        return f"'{name}' (the logging module allocates and locks)"
    if any(name.endswith(s) for s in _BANNED_DOTTED_SUFFIX):
        return f"'{name}' (buffered stream write — reentrant-call hazard)"
    if isinstance(call.func, ast.Attribute) and seg in _BANNED_METHODS:
        return f"'{name}' (logger/file method — buffered I/O)"
    return None


class SignalUnsafeHandler:
    code = "TDC004"
    name = "signal-unsafe-handler"
    description = (
        "a function registered with signal.signal transitively calls "
        "print/logging/structlog/buffered writes — reentrant-call "
        "RuntimeError inside the handler kills the worker mid-drain; "
        "use one raw os.write and act on a flag outside the handler"
    )

    def check(self, ctx: FileContext):
        defs: dict[str, ast.AST] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node

        handlers: list[tuple[str, ast.AST, int]] = []  # (label, body, reg line)
        for call in walk_calls(ctx.tree):
            if call_name(call) not in ("signal.signal", "signal") or \
                    len(call.args) < 2:
                continue
            target = call.args[1]
            if isinstance(target, ast.Name) and target.id in defs:
                handlers.append((target.id, defs[target.id], call.lineno))
            elif isinstance(target, ast.Lambda):
                handlers.append(("<lambda>", target, call.lineno))
            # anything else (restoring a saved handler, SIG_DFL/SIG_IGN,
            # attributes) is unresolvable here — skip silently

        reported: set[tuple[int, int]] = set()
        for label, body, reg_line in handlers:
            yield from self._scan(ctx, label, body, reg_line, defs,
                                  visited={id(body)}, depth=0,
                                  reported=reported)

    def _scan(self, ctx, label, body, reg_line, defs, visited, depth,
              reported):
        if depth > 8:  # recursion guard; real handler chains are shallow
            return
        for call in walk_calls(body):
            why = _banned(call)
            if why is not None:
                key = (call.lineno, call.col_offset)
                if key not in reported:
                    reported.add(key)
                    yield ctx.finding(
                        self, call,
                        f"{why} reached from signal handler '{label}' "
                        f"(registered at line {reg_line}): buffered I/O in "
                        "async-signal context raises reentrant-call "
                        "RuntimeError; write one raw os.write(2, ...) "
                        "line and do the real logging from the drain path",
                    )
                continue
            seg = last_seg(call_name(call))
            callee = defs.get(seg) if isinstance(call.func, ast.Name) \
                else None
            if callee is not None and id(callee) not in visited:
                visited.add(id(callee))
                yield from self._scan(
                    ctx, f"{label} -> {seg}", callee, reg_line, defs,
                    visited, depth + 1, reported)

    def finalize(self):
        return ()
