"""TDC1xx: the gang-divergence dataflow family.

The TDC00x rules are lexical — TDC001 sees a collective *under* a
`process_index()` branch. PR 18's bug had no such shape: host-local
quarantine verdicts flowed through ordinary assignments into a
replicated scalar feeding the in-graph padding correction, and the
centroid state forked silently across workers. These rules track the
*value*: `tdc_tpu.lint.dataflow` solves per-function taint over a CFG,
`tdc_tpu.lint.callgraph` composes the solutions package-wide, and the
four sink rules report where host-divergent values meet the gang:

- **TDC101** tainted operand of an in-graph collective (or a parameter
  that transitively reaches one) — the PR-18 bug, verbatim;
- **TDC102** tainted trip count / break guard of a collective-bearing
  loop — gang deadlock;
- **TDC103** tainted branch whose arms issue different collective
  multisets — schedule divergence, the static shadow of `tdcverify`'s
  IR-level schedule goldens;
- **TDC104** tainted value in a declared-static jit argument — per-host
  recompile fork.

**TDC100** guards the waiver budget: every `# tdclint: disable=TDC1xx`
must carry a trailing prose justification — a gang-uniformity invariant
is waived with a reason or not at all.

All five share ONE whole-program analysis per run: each rule's check()
registers the file; the first finalize() solves the program once and the
rules split the findings by code (so `--select=TDC101` still sees the
whole program — interprocedural findings need every file indexed).
"""

from __future__ import annotations

import io
import re
import tokenize

from tdc_tpu.lint.engine import Finding, _SUPPRESS_RE

_JUSTIFIED_RE = re.compile(r"[A-Za-z]{3,}")

_FAMILY = frozenset({"TDC101", "TDC102", "TDC103", "TDC104"})


def uniform_lines(source: str) -> set:
    """Lines covered by a JUSTIFIED `# tdclint: disable=TDC10x` comment.

    The dataflow layer treats values produced on these lines as
    host-uniform-by-construction (source tags cleared): a justified
    waiver placed where a value is *born* declares the whole value
    clean, instead of needing one suppression at every downstream sink.
    Unjustified waivers clear nothing — TDC100 flags them, and their
    findings still fire. Mirrors engine.Suppressions' logical-statement
    coverage so a trailing comment on a wrapped statement covers every
    physical line the statement's AST nodes anchor to.
    """
    out: set = set()
    try:
        stmt_start = None
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.NEWLINE:
                stmt_start = None
                continue
            if tok.type != tokenize.COMMENT:
                if stmt_start is None and tok.type not in (
                        tokenize.NL, tokenize.INDENT, tokenize.DEDENT,
                        tokenize.ENCODING, tokenize.ENDMARKER):
                    stmt_start = tok.start[0]
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            codes = {c.strip().upper() for c in m.group(2).split(",")}
            if not (codes & _FAMILY):
                continue
            if not _JUSTIFIED_RE.search(tok.string[m.end():]):
                continue  # bare waiver: no effect (and a TDC100 finding)
            kind = m.group(1).lower()
            if kind == "disable-file":
                out.update(range(1, source.count("\n") + 2))
            elif kind == "disable-next-line":
                out.add(tok.start[0] + 1)
            else:
                out.update(range(stmt_start or tok.start[0],
                                 tok.start[0] + 1))
    except (tokenize.TokenError, IndentationError):
        pass
    return out


class TaintProgram:
    """Shared per-run state: files registered by check(), solved once."""

    def __init__(self):
        self.ctxs: dict = {}
        self._findings: list | None = None

    def add(self, ctx) -> None:
        self.ctxs[ctx.path] = ctx

    def findings(self) -> list:
        if self._findings is None:
            from tdc_tpu.lint.callgraph import analyze_program
            files = [(path, ctx.tree, uniform_lines(ctx.source))
                     for path, ctx in sorted(self.ctxs.items())]
            self._findings = analyze_program(files)
        return self._findings


class _TaintRule:
    """One code of the shared-program family."""

    def __init__(self, program: TaintProgram):
        self.program = program

    def check(self, ctx):
        self.program.add(ctx)
        return ()

    def finalize(self):
        for code, path, node, message in self.program.findings():
            if code != self.code:
                continue
            ctx = self.program.ctxs.get(path)
            if ctx is None:
                continue
            yield ctx.finding(self, node, message)


class TaintedCollectiveOperand(_TaintRule):
    code = "TDC101"
    name = "tainted-collective-operand"
    description = (
        "A value derived from host-local state (process_index, rank-like "
        "env reads, clocks, random/uuid, quarantine verdicts, retry "
        "counters, addressable-shard fetches) becomes an operand of an "
        "in-graph collective, directly or through a callee parameter "
        "that reaches one. Each process contributes different bytes to a "
        "nominally replicated value and the gang's state forks silently "
        "— the PR-18 padding-correction bug. Fix: agree the value first "
        "(process_allgather / psum) or stage it explicitly sharded "
        "(make_array_from_process_local_data), as "
        "models/streaming._valid_arg and _agreed_pad do."
    )


class TaintedCollectiveLoop(_TaintRule):
    code = "TDC102"
    name = "tainted-collective-loop"
    description = (
        "Host-local state controls the trip count or a break guard of a "
        "loop that issues collectives. Processes disagree on how many "
        "iterations run, so one side enters a collective the other never "
        "reaches: the gang deadlocks (or worse, mismatched collectives "
        "pair up). Fix: make the loop-exit decision collectively — psum "
        "or process_allgather the driver value/stop flag, as the "
        "drivers' shift-convergence loops do."
    )


class UnbalancedCollectivePaths(_TaintRule):
    code = "TDC103"
    name = "unbalanced-collective-paths"
    description = (
        "A branch on host-local state has arms that issue different "
        "collective multisets — processes take different paths and the "
        "collective schedules diverge (the invariant tdcverify proves "
        "per golden entry at the compiled-IR level; this is its static, "
        "whole-codebase shadow). Branches on gang-uniform values "
        "(process_count(), config) are fine. Fix: hoist the collectives "
        "out of the branch, or agree the condition first."
    )


class TaintedStaticJitArg(_TaintRule):
    code = "TDC104"
    name = "tainted-static-jit-arg"
    description = (
        "Host-local state flows into a declared-static argument "
        "(static_argnums/static_argnames) of a jitted function. Statics "
        "are compile-time constants: each process specializes a "
        "DIFFERENT compiled program, forking compilation caches and — "
        "if the static steers collective layout — the gang schedule. "
        "Fix: derive statics from gang-uniform geometry "
        "(process_count(), mesh shape) or make the argument traced."
    )


class UnjustifiedGangWaiver:
    """TDC100: a TDC1xx suppression without a trailing prose reason.

    The engine's `_SUPPRESS_RE` anchors the codes group to CODE-shaped
    tokens precisely so trailing prose reads as justification — this
    rule makes that prose mandatory for the gang-uniformity family:
    waiving a divergence finding is a reviewed decision, and the reason
    belongs next to the waiver, not in a PR description that history
    forgets.
    """

    code = "TDC100"
    name = "unjustified-gang-waiver"
    description = (
        "A `# tdclint: disable=TDC1xx` suppression with no trailing "
        "justification. Gang-uniformity waivers assert a value is "
        "host-uniform for a reason the analyzer cannot prove — write "
        "the reason after the code list (e.g. `# tdclint: "
        "disable=TDC101 mesh geometry, identical on every host`)."
    )

    def check(self, ctx):
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(ctx.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m is None:
                    continue
                codes = {c.strip().upper() for c in m.group(2).split(",")}
                if not any(c.startswith("TDC1") for c in codes):
                    continue
                rest = tok.string[m.end():]
                if _JUSTIFIED_RE.search(rest):
                    continue
                gang = sorted(c for c in codes if c.startswith("TDC1"))
                yield Finding(
                    self.code, self.name, ctx.path, tok.start[0],
                    tok.start[1] + 1,
                    f"suppression of {', '.join(gang)} carries no "
                    "justification — a gang-uniformity waiver asserts "
                    "host-uniformity the analyzer cannot prove; state "
                    "the reason after the code list "
                    "(`# tdclint: disable=TDC101 <why this value is "
                    "identical on every host>`)",
                    ctx.snippet(tok.start[0]))
        except (tokenize.TokenError, IndentationError):
            return

    def finalize(self):
        return ()


def taint_rules() -> list:
    """The TDC1xx family, sharing one whole-program analysis per run."""
    program = TaintProgram()
    return [
        UnjustifiedGangWaiver(),
        TaintedCollectiveOperand(program),
        TaintedCollectiveLoop(program),
        UnbalancedCollectivePaths(program),
        TaintedStaticJitArg(program),
    ]
