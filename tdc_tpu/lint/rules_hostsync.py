"""TDC002 host-sync-in-hot-loop and TDC003 recompile-hazard.

PR 2 bought one cross-device reduce per pass; a single stray `.item()`
inside the streamed batch loop silently pays a device round-trip per
batch and erases the win without failing any test. Recompiles are the
same shape of silent loss: the serve engine's zero-recompile contract
(jit_cache_size assertions) only covers serving — a `jax.jit` created
inside a loop, or an f-string flowing into a static argument, retraces
on every call anywhere else.
"""

from __future__ import annotations

import ast
import re

from tdc_tpu.lint.engine import (
    FileContext, call_name, dotted_name, last_seg, walk_calls,
)

# A loop is "hot" when it is recognizably the streamed batch loop: its
# DIRECT body (nested loops excluded) contains a maybe_beat liveness
# marker or a stream/data fault point — those are placed exactly in the
# per-batch loops — or it iterates something batch-shaped. Nested-loop
# exclusion encodes the issue's finalization allowlist: a `float(shift)`
# after the inner batch loop is per-pass finalization (one sync per
# iteration, the PR-2 contract), not a per-batch sync.
#
# A `fault_point("resident.*")` marker OVERRIDES all of that: it names a
# chunk-boundary loop (models/resident.run_resident_loop), where each trip
# dispatches R compiled on-device iterations and the boundary fetch of
# (n_done, shift, history) is the design — one sync per R iterations, with
# the zero-transfer interior enforced by jax.transfer_guard — not a
# per-batch round trip.
_HOT_FAULT_PREFIXES = ("stream.", "data.")
_CHUNK_BOUNDARY_PREFIXES = ("resident.",)
_HOT_ITER_HINT = re.compile(
    r"batch|stream|loader|prefetch|minibatch", re.IGNORECASE
)

# Calls that force a device→host value sync (or a full D2H copy).
_SYNC_ATTRS = frozenset({"item"})
_SYNC_CALLS = frozenset({"device_get"})
_NP_COPY = frozenset({"asarray", "array"})
_NP_ROOTS = frozenset({"np", "numpy", "onp"})
_BUILTIN_SYNCS = frozenset({"float", "int", "bool"})


def _names_in(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _region_nodes(loop) -> list[ast.AST]:
    """Nodes whose NEAREST enclosing loop is `loop`: the loop's body with
    nested For/While subtrees cut out (a nested For's iter/target still
    belong to this region — they are evaluated per outer iteration)."""
    roots: list[ast.AST] = []
    if isinstance(loop, ast.For):
        roots = list(loop.body) + list(loop.orelse)
    else:  # While: the test re-evaluates every iteration
        roots = [loop.test] + list(loop.body) + list(loop.orelse)
    out: list[ast.AST] = []
    stack = list(roots)
    while stack:
        n = stack.pop()
        out.append(n)
        if isinstance(n, ast.For):
            stack.extend([n.iter, n.target])
            continue
        if isinstance(n, ast.While):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def _loop_is_hot(loop, region: list[ast.AST]) -> bool:
    hot = False
    for n in region:
        if not isinstance(n, ast.Call):
            continue
        seg = last_seg(call_name(n))
        if seg == "maybe_beat":
            hot = True
        elif seg == "fault_point" and n.args:
            arg = n.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                if arg.value.startswith(_CHUNK_BOUNDARY_PREFIXES):
                    return False  # chunk-boundary loop: fetches are by design
                if arg.value.startswith(_HOT_FAULT_PREFIXES):
                    hot = True
    if hot:
        return True
    if isinstance(loop, ast.For):
        for name in list(_names_in(loop.iter)) + list(_names_in(loop.target)):
            if _HOT_ITER_HINT.search(name):
                return True
    return False


def _shape_only(arg: ast.AST) -> bool:
    """float()/int() of shapes, lengths and dtypes never syncs — shape
    metadata is host-resident on jax arrays."""
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Attribute) and \
                sub.attr in ("shape", "ndim", "size", "itemsize"):
            return True
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return True
    return False


class HostSyncInHotLoop:
    code = "TDC002"
    name = "host-sync-in-hot-loop"
    description = (
        ".item()/float()/int()/np.asarray/jax.device_get inside a streamed "
        "batch loop — each is a blocking device round-trip per batch that "
        "silently erases the deferred-reduce comms wins"
    )

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            region = _region_nodes(node)
            if not _loop_is_hot(node, region):
                continue
            yield from self._check_region(ctx, region)

    def _check_region(self, ctx: FileContext, region):
        for call in region:
            if not isinstance(call, ast.Call):
                continue
            name = call_name(call)
            seg = last_seg(name)
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr in _SYNC_ATTRS and not call.args:
                yield ctx.finding(
                    self, call,
                    ".item() in a hot loop blocks on the device value "
                    "every batch; accumulate on device and fetch once "
                    "after the loop",
                )
            elif seg in _SYNC_CALLS:
                yield ctx.finding(
                    self, call,
                    f"{name or seg}() in a hot loop is a full D2H transfer "
                    "per batch; keep the value device-resident until the "
                    "pass ends",
                )
            elif seg in _NP_COPY and name and \
                    name.rsplit(".", 1)[0] in _NP_ROOTS:
                yield ctx.finding(
                    self, call,
                    f"{name}() in a hot loop copies the array to host "
                    "every batch (and re-uploads it if used on device); "
                    "operate on the jax.Array directly",
                )
            elif isinstance(call.func, ast.Name) and \
                    call.func.id in _BUILTIN_SYNCS and len(call.args) == 1 \
                    and not isinstance(call.args[0], ast.Constant) \
                    and not _shape_only(call.args[0]):
                yield ctx.finding(
                    self, call,
                    f"{call.func.id}(...) in a hot loop forces the value "
                    "to host every batch if its argument is a traced/"
                    "device value; if the argument is host-only, annotate "
                    "with `# tdclint: disable=TDC002` and say why",
                )

    def finalize(self):
        return ()


class RecompileHazard:
    code = "TDC003"
    name = "recompile-hazard"
    description = (
        "jit closures created inside loops, malformed static_argnums/"
        "static_argnames, and unhashable or per-call-fresh values flowing "
        "into static positions — every one retraces/recompiles per call"
    )

    _UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                   ast.SetComp, ast.GeneratorExp)

    def check(self, ctx: FileContext):
        yield from self._jit_in_loop(ctx)
        jitted = {}
        for node in ast.walk(ctx.tree):
            yield from self._bad_static_spec(ctx, node)
            self._collect_jitted(node, jitted)
        yield from self._bad_static_args(ctx, jitted)

    # -- sub-check (a): jax.jit(...) inside a loop ------------------------
    def _jit_in_loop(self, ctx: FileContext):
        # Lexical scan with a function boundary: a jit inside a nested
        # function that happens to be *defined* in a loop traces once per
        # fit (the factory idiom, e.g. make_deferred_fns) — only a jit
        # CALL directly under a loop in the same function body retraces
        # per iteration.
        rule = self

        class V(ast.NodeVisitor):
            def __init__(self):
                self.loop_depth = 0
                self.found = []

            def visit_For(self, node):
                self._loop(node)

            def visit_While(self, node):
                self._loop(node)

            def _loop(self, node):
                self.loop_depth += 1
                self.generic_visit(node)
                self.loop_depth -= 1

            def visit_FunctionDef(self, node):
                saved, self.loop_depth = self.loop_depth, 0
                self.generic_visit(node)
                self.loop_depth = saved

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Lambda(self, node):
                saved, self.loop_depth = self.loop_depth, 0
                self.generic_visit(node)
                self.loop_depth = saved

            def visit_Call(self, node):
                if self.loop_depth > 0 and \
                        last_seg(call_name(node)) == "jit":
                    self.found.append(node)
                self.generic_visit(node)

        v = V()
        v.visit(ctx.tree)
        for call in v.found:
            yield ctx.finding(
                rule, call,
                "jax.jit called inside a loop creates a fresh compiled "
                "callable (and a fresh trace cache) every iteration; "
                "hoist the jitted function out of the loop",
            )

    # -- sub-check (b): malformed static specs ----------------------------
    def _bad_static_spec(self, ctx: FileContext, node: ast.AST):
        if not (isinstance(node, ast.Call) and
                last_seg(call_name(node)) == "jit"):
            return
        for kw in node.keywords:
            if kw.arg == "static_argnums":
                for sub in ast.walk(kw.value):
                    if not isinstance(sub, ast.Constant):
                        continue
                    bad = isinstance(sub.value, bool) or \
                        not isinstance(sub.value, (int, type(None)))
                    if bad:
                        yield ctx.finding(
                            self, kw.value,
                            f"static_argnums takes integer positions, got "
                            f"{sub.value!r} — a string here silently "
                            "matches nothing and the argument is traced "
                            "(recompiling per shape) instead of static",
                        )
                        break
            elif kw.arg == "static_argnames":
                for sub in ast.walk(kw.value):
                    if isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str) and "," in sub.value:
                        yield ctx.finding(
                            self, kw.value,
                            f"static_argnames={sub.value!r} is ONE name "
                            "containing a comma, not two names — pass a "
                            "tuple of strings",
                        )

    # -- sub-check (c): unhashable/fresh values into static positions -----
    def _collect_jitted(self, node: ast.AST, jitted: dict):
        """Map local name -> (static positions, static names) for
        `f = jax.jit(g, static_argnums=..., static_argnames=...)` and the
        decorator forms."""
        def spec_of(call: ast.Call):
            nums, names = set(), set()
            for kw in call.keywords:
                if kw.arg == "static_argnums":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, int) and \
                                not isinstance(sub.value, bool):
                            nums.add(sub.value)
                elif kw.arg == "static_argnames":
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and \
                                isinstance(sub.value, str):
                            names.add(sub.value)
            return nums, names

        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call) and \
                last_seg(call_name(node.value)) == "jit":
            jitted[node.targets[0].id] = spec_of(node.value)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    seg = last_seg(call_name(dec))
                    if seg == "jit":
                        jitted[node.name] = spec_of(dec)
                    elif seg == "partial" and dec.args and \
                            last_seg(dotted_name(dec.args[0])) == "jit":
                        jitted[node.name] = spec_of(dec)
                elif last_seg(dotted_name(dec)) == "jit":
                    jitted[node.name] = (set(), set())

    def _bad_static_args(self, ctx: FileContext, jitted: dict):
        if not jitted:
            return
        for call in walk_calls(ctx.tree):
            if not (isinstance(call.func, ast.Name) and
                    call.func.id in jitted):
                continue
            nums, names = jitted[call.func.id]
            static_args = [
                (i, a) for i, a in enumerate(call.args) if i in nums
            ] + [
                (kw.arg, kw.value) for kw in call.keywords
                if kw.arg in names
            ]
            for pos, arg in static_args:
                if isinstance(arg, self._UNHASHABLE):
                    yield ctx.finding(
                        self, arg,
                        f"unhashable value (list/dict/set) passed to "
                        f"static position {pos!r} of jitted "
                        f"'{call.func.id}' — jit raises TypeError on "
                        "unhashable statics; pass a tuple or hashable "
                        "dataclass",
                    )
                elif isinstance(arg, ast.JoinedStr):
                    yield ctx.finding(
                        self, arg,
                        f"f-string passed to static position {pos!r} of "
                        f"jitted '{call.func.id}' — a fresh string per "
                        "call means a fresh compile per call",
                    )

    def finalize(self):
        return ()
