"""Grandfathered-findings baseline: the ratchet that lets tdclint gate CI
on a codebase that predates it.

A baseline entry fingerprints a finding by (rule, path, source-line text)
— deliberately NOT the line number, so unrelated edits above a
grandfathered finding don't resurrect it. Multiplicity is kept: two
identical `float(x)` lines in one file need a count of 2, and fixing one
of them makes the run fail until the baseline is regenerated smaller —
the count only goes down.

Workflow (docs/LINTING.md):

    python -m tdc_tpu.lint --baseline=scripts/tdclint_baseline.json tdc_tpu/ tests/
    # fix findings, then shrink the baseline:
    python -m tdc_tpu.lint --baseline=... --write-baseline tdc_tpu/ tests/

Stale entries (fingerprints no longer matching any finding) FAIL the
gated full run: a fixed finding whose baseline entry lingers is headroom
a regression could silently spend — `--prune-baseline` rewrites the file
down to the entries that still match, and CI stays red until someone
does. Partial runs (path or rule subsets) never judge staleness: most
entries trivially match nothing there.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

from tdc_tpu.lint.engine import Finding

BASELINE_VERSION = 1


def fingerprint(f: Finding) -> str:
    # normpath BEFORE hashing: findings walked from `./tdc_tpu/` carry
    # "./"-prefixed paths, and a fingerprint keyed on the raw spelling
    # fails to match the baseline generated from `tdc_tpu/` — every
    # grandfathered finding then leaks as "new" (the CI annotation job's
    # `--format=github` run sprayed the whole baseline onto PRs; see
    # tests/test_lint.py::test_github_format_respects_baseline_dot_paths).
    path = os.path.normpath(f.path).replace(os.sep, "/")
    key = f"{f.rule}|{path}|{f.snippet}"
    return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]


@dataclass
class BaselineResult:
    new: list[Finding]  # findings NOT covered by the baseline — these gate
    grandfathered: int  # findings absorbed by the baseline
    stale: list[str]  # fingerprints with unspent budget — gate on full runs
    # findings the baseline absorbed — exactly what --prune-baseline
    # rewrites the file from (multiplicity preserved by construction)
    matched: list[Finding] = field(default_factory=list)


def normalize_paths(paths: list[str]) -> list[str]:
    return sorted(os.path.normpath(p).replace(os.sep, "/") for p in paths)


def load(path: str) -> dict:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')!r} "
            f"(want {BASELINE_VERSION})"
        )
    return data


def covers_run(baseline: dict, paths: list[str]) -> bool:
    """Does this run lint the same path set the baseline was generated
    from? On a partial run (spot-checking one file) most baseline entries
    trivially match nothing — reporting them as stale, or letting
    --write-baseline rewrite the file from the partial findings, would
    wipe the ratchet."""
    recorded = baseline.get("paths")
    if recorded is None:  # pre-paths baseline: assume covered (legacy)
        return True
    return normalize_paths(paths) == list(recorded)


def apply(findings: list[Finding], baseline: dict) -> BaselineResult:
    budget = {
        fp: int(meta.get("count", 1))
        for fp, meta in baseline.get("fingerprints", {}).items()
    }
    used: dict[str, int] = {}
    new: list[Finding] = []
    matched: list[Finding] = []
    grandfathered = 0
    for f in findings:
        fp = fingerprint(f)
        if used.get(fp, 0) < budget.get(fp, 0):
            used[fp] = used.get(fp, 0) + 1
            grandfathered += 1
            matched.append(f)
        else:
            new.append(f)
    stale = sorted(fp for fp, n in budget.items() if used.get(fp, 0) < n)
    return BaselineResult(new, grandfathered, stale, matched)


def write(path: str, findings: list[Finding],
          paths: list[str] | None = None) -> dict:
    """Serialize `findings` as the new baseline (human-reviewable: each
    fingerprint carries rule/path/snippet so diffs of the committed file
    read as a findings ledger, not hash soup). `paths` records the linted
    path set so partial runs can be refused at the next regeneration."""
    fps: dict[str, dict] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        fp = fingerprint(f)
        if fp in fps:
            fps[fp]["count"] += 1
        else:
            fps[fp] = {
                "count": 1,
                "rule": f.rule,
                "path": f.path.replace(os.sep, "/"),
                "snippet": f.snippet,
                "message": f.message,
            }
    data = {
        "version": BASELINE_VERSION,
        "paths": normalize_paths(paths or []),
        "note": (
            "tdclint grandfathered findings — regenerate with "
            "`python -m tdc_tpu.lint --baseline=<this file> "
            "--write-baseline <paths>`; the total count must only go down."
        ),
        "fingerprints": fps,
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data
