import sys

from tdc_tpu.lint.cli import main

sys.exit(main())
