"""Compile-time collective-trace checking — moved to tdc_tpu.verify.ir.

This module grew into the IR layer of tdcverify (PR 13): collective
extraction now lives beside the transfer walk, donation inspection, and
recompile proof in `tdc_tpu/verify/ir.py`, driven by the gating
`python -m tdc_tpu.verify` CI stage (docs/VERIFICATION.md). The public
names are re-exported here so existing imports keep working; new code
should import from `tdc_tpu.verify` directly.

Like the original: uses jax, imported by tests and explicit callers
only, never by the `python -m tdc_tpu.lint` CLI (which must run with
zero third-party imports).
"""

from __future__ import annotations

from tdc_tpu.verify.ir import (  # noqa: F401
    COLLECTIVE_PRIMITIVES,
    CollectiveDivergenceError,
    TraceReport,
    assert_uniform_collectives,
    collective_trace,
)

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "CollectiveDivergenceError",
    "TraceReport",
    "assert_uniform_collectives",
    "collective_trace",
]
