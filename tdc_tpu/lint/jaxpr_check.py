"""Compile-time collective-trace checking — the layer past source AST.

TDC001 catches `if process_index: psum(...)` lexically; this module
catches the same divergence class where it actually becomes binding: in
the traced program. It walks a function's jaxpr and extracts the ordered
sequence of collective primitives (psum / all_gather / ppermute / ...),
then asserts two SPMD invariants:

1. **Branch uniformity** — under SPMD, one program runs on every shard,
   so shards can only execute different collective sequences through
   value-dependent control flow: `lax.cond`/`lax.switch` branches that
   emit different collectives (asserted identical here), or a
   `lax.while_loop` whose trip count varies per shard (undecidable
   statically — such collectives are surfaced in
   TraceReport.while_collectives and can be hard-rejected with
   forbid_while_collectives=True). With uniform branches and no
   while-body collectives, the emitted sequence is identical across
   shards by construction — the static companion to test_reduce's
   compiled-HLO no-collective proof.
2. **Trace stability** — tracing twice yields the same sequence. A trace
   that consults ambient state (a global counter, dict ordering, an RNG)
   can emit different reduction orders per compile; with per-process jit
   caches that means two processes that compiled at different times run
   different programs — the quantized-reduce towers (int8 pmax + psum
   pairs) fail *numerically*, not loudly, when that happens.

Uses jax — imported by tests and explicit callers only, never by the
`python -m tdc_tpu.lint` CLI (which must run with zero third-party
imports).
"""

from __future__ import annotations

from dataclasses import dataclass, field

# The collective primitive names as they appear in jaxpr eqns. pmean is
# absent on purpose: it decomposes to psum + div before it reaches a
# jaxpr.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "psum_scatter", "reduce_scatter", "pgather", "pbroadcast",
})


class CollectiveDivergenceError(AssertionError):
    """A cond/switch emits different collective sequences per branch, or
    two traces of the same function disagree — some shard/process can
    execute a collective sequence its peers don't, which deadlocks the
    gang (or silently corrupts a quantized reduce)."""


@dataclass
class TraceReport:
    sequence: list[str]  # e.g. ["psum[axes=('data',)]", ...]
    divergences: list[str] = field(default_factory=list)
    # Collectives inside lax.while_loop bodies (entries also appear in
    # `sequence` with a "while:" prefix). A while loop's trip count is
    # value-dependent: if the predicate consults shard-local values, the
    # shards issue these collectives DIFFERENT numbers of times and the
    # gang deadlocks — a divergence this static walk cannot prove or
    # refute (the repo's in-jit Lloyd loops are safe because their
    # predicate derives from the globally-psum'd shift, but that is a
    # data-flow property). Callers wanting a hard guarantee pass
    # forbid_while_collectives=True.
    while_collectives: list[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.divergences


def _axes_of(params: dict) -> str:
    for key in ("axes", "axis_name", "axis_index_groups"):
        if key in params and params[key] is not None and \
                key != "axis_index_groups":
            val = params[key]
            if not isinstance(val, tuple):
                val = (val,)
            named = tuple(str(a) for a in val)
            return f"axes={named}"
    return "axes=?"


def _subjaxprs(params: dict):
    """Every Jaxpr/ClosedJaxpr reachable from an eqn's params — covers
    pjit, shard_map, scan, while, cond, remat, custom_* generically."""
    import jax.core as core

    closed = getattr(core, "ClosedJaxpr", None)
    open_ = getattr(core, "Jaxpr", None)

    def visit(val):
        if closed is not None and isinstance(val, closed):
            yield val.jaxpr
        elif open_ is not None and isinstance(val, open_):
            yield val
        elif isinstance(val, (tuple, list)):
            for v in val:
                yield from visit(v)

    for key, val in params.items():
        if key in ("branches",):
            continue  # cond branches are compared, not inlined, below
        yield from visit(val)


def _walk(jaxpr, out: list[str], divergences: list[str],
          while_out: list[str], in_while: bool = False) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMITIVES:
            entry = f"{prim}[{_axes_of(eqn.params)}]"
            if in_while:
                entry = f"while:{entry}"
                while_out.append(entry)
            out.append(entry)
            continue
        if prim == "while":
            # Value-dependent trip count: body collectives repeat an
            # unknowable number of times — recorded separately (see
            # TraceReport.while_collectives) instead of silently inlined
            # as if they ran once.
            for key in ("cond_jaxpr", "body_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    _walk(sub.jaxpr, out, divergences, while_out,
                          in_while=True)
            continue
        if prim in ("cond", "switch"):
            branch_seqs = []
            for br in eqn.params.get("branches", ()):
                seq: list[str] = []
                _walk(br.jaxpr, seq, divergences, while_out, in_while)
                branch_seqs.append(seq)
            if branch_seqs and any(s != branch_seqs[0]
                                   for s in branch_seqs[1:]):
                divergences.append(
                    f"cond branches emit different collective sequences "
                    f"{branch_seqs} — a shard-varying predicate here "
                    "desyncs the gang"
                )
            # Executed exactly once whichever branch wins; with uniform
            # branches the subsequence is unconditionally part of the
            # program order.
            if branch_seqs:
                out.extend(branch_seqs[0])
            continue
        for sub in _subjaxprs(eqn.params):
            _walk(sub, out, divergences, while_out, in_while)


def collective_trace(fn, *args, **kwargs) -> TraceReport:
    """Trace fn(*args, **kwargs) and return its ordered collective
    sequence plus any branch-divergence findings."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    out: list[str] = []
    divergences: list[str] = []
    while_out: list[str] = []
    _walk(closed.jaxpr, out, divergences, while_out)
    return TraceReport(sequence=out, divergences=divergences,
                       while_collectives=while_out)


def assert_uniform_collectives(fn, *args, n_traces: int = 2,
                               require_collectives: bool = False,
                               forbid_while_collectives: bool = False,
                               **kwargs) -> TraceReport:
    """The whole contract in one call: trace `fn` `n_traces` times,
    assert (a) no divergent cond branches, (b) the sequence is identical
    across traces, and optionally (c) at least one collective is present
    (a tower that silently lost its psum 'passes' any divergence check).
    Returns the report of the first trace.

    Caveat (see TraceReport.while_collectives): collectives inside
    lax.while_loop bodies run trip-count-many times, and trip-count
    uniformity across shards is a data-flow property this static walk
    cannot decide — a convergence loop whose predicate derives from a
    globally-reduced value is safe; one consulting shard-local state is
    a deadlock. Such collectives are reported, and hard-rejected with
    forbid_while_collectives=True."""
    reports = [collective_trace(fn, *args, **kwargs)
               for _ in range(max(n_traces, 1))]
    first = reports[0]
    if first.divergences:
        raise CollectiveDivergenceError("\n".join(first.divergences))
    if forbid_while_collectives and first.while_collectives:
        raise CollectiveDivergenceError(
            f"collectives inside while-loop bodies "
            f"{first.while_collectives}: the trip count is value-"
            "dependent, so per-shard uniformity of these collectives "
            "cannot be statically guaranteed — prove the predicate is "
            "derived from globally-reduced values, or restructure with "
            "a static-length lax.scan"
        )
    for i, rep in enumerate(reports[1:], start=2):
        if rep.sequence != first.sequence:
            raise CollectiveDivergenceError(
                f"collective sequence is not stable across traces: trace 1 "
                f"emitted {first.sequence} but trace {i} emitted "
                f"{rep.sequence} — the trace consults ambient state, and "
                "processes compiling at different times would run "
                "different programs"
            )
    if require_collectives and not first.sequence:
        raise CollectiveDivergenceError(
            "no collective primitive found in the trace — the cross-shard "
            "reduce was lost (or the wrong tower was checked)"
        )
    return first


__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "CollectiveDivergenceError",
    "TraceReport",
    "assert_uniform_collectives",
    "collective_trace",
]
