"""Experiment CLI — flag parity with the reference plus TPU extensions.

Reference: scripts/distribuitedClustering.py:411-491 — flags --n_obs --n_dim
--K --n_GPUs --n_max_iters --seed --log_file --method_name --data_file with
validating type= lambdas (:18-70: file-exists, positive-int, enumerated method
names; parser.error on violation). Preserved here verbatim, plus:
--backend/--n_devices (TPU mesh), --tol (real convergence, reference had none),
--init, --fuzzifier (explicit m, fixing defect 7), --num_batches /--streamed
(exact out-of-core), and the OOM-adaptive retry loop (:357-360 semantics).

Run: python -m tdc_tpu.cli.main --method_name=distributedKMeans --n_obs=100000
     --n_dim=8 --K=16 --n_max_iters=50 --seed=0 --log_file=executions_log.csv
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

METHOD_NAMES = ("distributedKMeans", "distributedFuzzyCMeans",
                "gaussianMixture", "bisectingKMeans")


def _valid_int(parser, name, value, minimum=1):
    try:
        v = int(value)
    except ValueError:
        parser.error(f"{name} must be an integer, got {value!r}")
    if v < minimum:
        parser.error(f"{name} must be >= {minimum}, got {v}")
    return v


def _sharded_reduce(args) -> str:
    """--reduce for the K-sharded drivers: quantized encodings are wired
    for the 1-D streamed fits only — fail in the CLI's vocabulary instead
    of a deep driver ValueError."""
    if args.reduce.startswith("per_pass:"):
        raise SystemExit(
            "--reduce=per_pass:bf16|int8 applies to the 1-D streamed fits; "
            "--shard_k supports --reduce=per_batch|per_pass"
        )
    return args.reduce


def _sharded_gather(args) -> str:
    """--gather for the K-sharded kmeans/fuzzy drivers: surface the
    plan_gather guard rails in the CLI's vocabulary (loud SystemExit, the
    --reduce convention) instead of a deep driver ValueError."""
    if args.gather == "fp32":
        return args.gather
    if args.gather in ("bf16", "int8"):
        if args.ckpt_dir or args.ckpt_every_batches:
            raise SystemExit(
                f"--gather={args.gather} does not support checkpointing "
                "(--ckpt_dir/--ckpt_every_batches): a resume would restart "
                "the finalize error-feedback residual, breaking the "
                "bit-identical-resume contract"
            )
        if args.residency not in (None, "stream"):
            raise SystemExit(
                f"--gather={args.gather} requires --residency stream: the "
                "compiled resident chunk traces the centroid update once "
                "and cannot carry the gather error-feedback state"
            )
        if args.assign == "bounded":
            raise SystemExit(
                f"--gather={args.gather} cannot combine with --assign "
                "bounded (quantized champion mins would invalidate the "
                "triangle-inequality certificates); use --gather fp32"
            )
    return args.gather


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tdc_tpu",
        description="TPU-native distributed clustering experiments",
    )
    # --- reference flag surface (scripts/distribuitedClustering.py:411-477) ---
    p.add_argument("--n_obs", type=int, default=None,
                   help="number of observations (generates synthetic data "
                        "unless --data_file is given)")
    p.add_argument("--n_dim", type=int, default=None, help="dimensionality")
    p.add_argument("--K", type=int, required=True, help="number of clusters")
    p.add_argument("--n_GPUs", "--n_devices", dest="n_devices", type=int,
                   default=None,
                   help="devices to use (reference name kept; default all)")
    p.add_argument("--n_max_iters", type=int, default=20,
                   help="iteration cap (reference default 20)")
    p.add_argument("--seed", type=int, default=123128,
                   help="PRNG seed — actually applied here, unlike the "
                        "reference where it was only logged (defect 3)")
    p.add_argument("--log_file", type=str, default=None,
                   help="append-only results CSV (header auto-created)")
    p.add_argument("--method_name", type=str, default="distributedKMeans",
                   choices=METHOD_NAMES)
    p.add_argument("--data_file", type=str, default=None,
                   help=".npz (keys X,Y) or .npy points file")
    # --- TPU-native extensions ---
    p.add_argument("--backend", type=str, default=None,
                   help="jax platform override (tpu|cpu); default auto")
    p.add_argument("--tol", type=float, default=1e-4,
                   help="convergence tolerance: centroid shift (kmeans/"
                        "fuzzy) or mean log-likelihood gain "
                        "(gaussianMixture); negative = fixed n_max_iters "
                        "(reference parity)")
    p.add_argument("--init", type=str, default="kmeans++",
                   choices=("kmeans++", "kmeans_parallel", "random", "first_k",
                            "kmeans"),
                   help="'kmeans' (gaussianMixture only): seed means with a "
                        "short multi-restart K-Means fit")
    p.add_argument("--fuzzifier", type=float, default=2.0,
                   help="fuzzy c-means m (explicit; reference bound it to "
                        "n_dim, defect 7)")
    p.add_argument("--covariance_type", type=str, default="diag",
                   choices=("diag", "spherical", "tied", "full"),
                   help="gaussianMixture covariance parameterization "
                        "(sklearn parity); all four types work in-memory "
                        "AND streamed (--num_batches/--streamed)")
    p.add_argument("--spherical", action="store_true",
                   help="cosine K-Means (normalize points and centroids)")
    p.add_argument("--empty_policy", type=str, default="keep",
                   choices=("keep", "relocate"),
                   help="empty-cluster policy for in-memory kmeans: 'keep' "
                        "(stale centroid survives — every other driver's "
                        "deterministic choice) or 'relocate' (sklearn "
                        "parity: reseed from highest-cost points each "
                        "iteration; closes the large-K SSE gap vs sklearn, "
                        "benchmarks/iters_to_converge.csv)")
    p.add_argument("--num_batches", type=int, default=1,
                   help="initial serial batch count; doubled on OOM "
                        "(reference :357-360 semantics)")
    p.add_argument("--streamed", action="store_true",
                   help="force exact streamed Lloyd even if data fits")
    p.add_argument("--minibatch", action="store_true",
                   help="Sculley-style mini-batch K-Means (BASELINE config 3): "
                        "one update per batch, n_max_iters epochs; batch size "
                        "from device memory unless --num_batches is given")
    p.add_argument("--reassignment_ratio", type=float, default=0.01,
                   help="mini-batch low-count-center reseed threshold "
                        "(sklearn MiniBatchKMeans parity; 0 disables)")
    p.add_argument("--mean_combine", action="store_true",
                   help="reference-parity batch mode: independent Lloyd per "
                        "batch, unweighted mean of per-batch centers "
                        "(reference :310 approximation, for apples-to-apples "
                        "iters-to-converge comparisons; kmeans only)")
    p.add_argument("--class_sep", type=float, default=1.5)
    p.add_argument("--assign", type=str, default=None,
                   choices=("exact", "auto", "coarse", "bounded"),
                   help="assignment strategy for streamed/K-sharded "
                        "kmeans: 'exact' (default, all-K), 'coarse' "
                        "(sub-linear coarse->refine tile-pruned "
                        "assignment, ops/subk.py — bounded-loss; see "
                        "benchmarks/bench_subk.py), 'bounded' (ZERO-LOSS "
                        "sub-linear Elkan/Hamerly bounds, ops/bounds.py "
                        "— needs --residency hbm/auto; falls back to "
                        "exact loudly otherwise), or 'auto' (bounded "
                        "with --residency hbm at large K, else coarse "
                        "at large K, exact below; logged as "
                        "assign_selected)")
    p.add_argument("--bounds", type=str, default=None,
                   choices=("hamerly", "elkan"),
                   help="bound kind for --assign bounded (1-D streamed "
                        "driver): 'hamerly' (default, one scalar lower "
                        "bound per point) or 'elkan' (additional "
                        "per-tile lower bounds — bounds prune points, "
                        "tiles prune centroids; O(n*sqrt(K)) extra HBM)")
    p.add_argument("--probe", type=str, default=None,
                   help="coarse tiles scanned per point block for "
                        "--assign coarse/auto: an integer or 'all' "
                        "(probing every tile routes to the exact path "
                        "and is bit-exact by construction); default "
                        "~sqrt(n_tiles)")
    p.add_argument("--kernel", type=str, default=None,
                   choices=("xla", "pallas", "pallas_bf16", "refined",
                            "auto", "auto:quantized"),
                   help="sufficient-stats kernel for K-Means: 'pallas' = "
                        "fused single-pass VMEM kernel (single-device and "
                        "mesh; with --shard_k, the blockwise online-argmin "
                        "kernel runs inside each shard); 'pallas_bf16' = "
                        "the fused kernel with its bf16-MXU/f32-accumulate "
                        "distance epilogue (assignment at bf16 MXU "
                        "precision, statistics exact f32; in-memory "
                        "kmeans, single-device); 'refined' = exact-"
                        "distance champion refinement (in-memory kmeans "
                        "only — the iters-to-converge parity path: matmul-"
                        "form cancellation can flip assignments near "
                        "convergence). Default: 'xla', except --layout=auto "
                        "may route narrow-d in-memory fits to the feature-"
                        "major tall kernel; passing --kernel explicitly "
                        "pins the sample-major layout. 'auto' picks the "
                        "fused Pallas path when the (K, d) block fits "
                        "VMEM on TPU and falls back to XLA loudly "
                        "(kernel_selected event; "
                        "ops/pallas_kernels.resolve_kernel). "
                        "'auto:quantized' = auto, plus permission to pick "
                        "the bf16-MXU epilogue where it applies (kmeans, "
                        "f32 inputs, single-device, fused-feasible) — the "
                        "caller accepts quantized-reduce tolerances")
    p.add_argument("--shard_k", type=int, default=1,
                   help="model-axis size: shard the K centroids/components "
                        "this many ways over a 2-D (data x model) mesh (the "
                        "K=16,384 regime; requires n_devices %% shard_k == 0 "
                        "and K %% shard_k == 0; kmeans, fuzzy, and "
                        "gaussianMixture — all three stream)")
    p.add_argument("--block_rows", type=int, default=-1,
                   help="N-block rows inside each shard for --shard_k "
                        "(-1 = auto from device memory, 0 = no blocking)")
    p.add_argument("--reduce", type=str, default="per_batch",
                   choices=("per_batch", "per_pass", "per_pass:bf16",
                            "per_pass:int8"),
                   help="cross-device stats reduction strategy for the "
                        "streamed fits (parallel/reduce.py): 'per_pass' "
                        "defers to ONE reduce per iteration instead of one "
                        "per batch (f32 summation reorder — tolerance-level "
                        "parity); ':bf16'/':int8' additionally quantize the "
                        "(K, d) sums on the wire with error feedback "
                        "(1-D meshes only)")
    p.add_argument("--gather", type=str, default="fp32",
                   choices=("fp32", "fp32_sharded", "bf16", "int8"),
                   help="model-axis collective strategy for the K-sharded "
                        "drivers (parallel/gather.py): 'fp32_sharded' "
                        "computes the centroid finalize on each device's "
                        "1/n_data K-slice and all-gathers the slices "
                        "(bit-exact, 1/n_data the replicated FLOPs); "
                        "'bf16'/'int8' additionally compress the champion "
                        "and finalize all_gathers with per-128-block "
                        "shared scales + persistent error feedback on the "
                        "finalize slices (tolerance-level parity; refuses "
                        "checkpointing, hbm/auto residency, and --assign "
                        "bounded — the EF residual must persist across "
                        "passes)")
    p.add_argument("--residency", type=str, default="stream",
                   choices=("stream", "auto", "hbm", "spill"),
                   help="streamed kmeans/fuzzy dataset residency "
                        "(data/device_cache.py): 'hbm' caches the padded "
                        "batches in device HBM during iteration 1 and runs "
                        "iterations 2..N as a compiled on-device loop with "
                        "zero host transfers per iteration; 'spill' "
                        "double-buffers staging + H2D copies on a producer "
                        "thread 2+ slots ahead of compute (data/spill.py — "
                        "the over-HBM-budget tier, bit-exact with plain "
                        "streaming); 'auto' picks hbm when dataset + "
                        "accumulators fit the HBM budget, spill when only "
                        "a slot ring fits, and falls back to streaming "
                        "(loudly) when neither does")
    p.add_argument("--native_loader", action="store_true",
                   help="stream batches through the C++ prefetch loader "
                        "(requires --data_file pointing at an .npy)")
    p.add_argument("--data_manifest", type=str, default=None,
                   help="stream batches from a sharded object-store "
                        "manifest (data/store.py): http(s):// URL, "
                        "file:// URL, or local path of a manifest.json "
                        "(or its directory). Geometry/dtype/batching come "
                        "from the manifest — batch size is its batch_rows "
                        "(--num_batches cannot override it) — ranged blob "
                        "reads are CRC-checked and routed through the "
                        "ingest guard's retry/quarantine ladder, and a "
                        "multi-process gang opens disjoint shard sets "
                        "with zero coordination. Streamed kmeans/fuzzy "
                        "only (--streamed, optionally --shard_k)")
    p.add_argument("--store_timeout", type=float, default=None,
                   help="with --data_manifest: socket deadline in seconds "
                        "per ranged read on the HTTP backend (default 10; "
                        "a stalled read surfaces as a transient timeout "
                        "the --io_retries ladder owns)")
    p.add_argument("--store_base", type=str, default=None,
                   help="base URL/directory a relative --data_manifest "
                        "resolves against (one configured bucket, many "
                        "datasets)")
    p.add_argument("--trace", type=str, default=None, metavar="DIR",
                   help="enable obs/trace span tracing: export Chrome-trace"
                        " JSON per process into DIR (also $TDC_TRACE) and "
                        "print the per-pass fit timeline; merge a gang's "
                        "traces with python -m tdc_tpu.obs.merge_trace DIR")
    p.add_argument("--profile_dir", type=str, default=None,
                   help="write a jax.profiler trace here (nvprof equivalent)")
    p.add_argument("--run_log", type=str, default=None,
                   help="append structured JSONL run events here")
    p.add_argument("--dtype", type=str, default="float32",
                   choices=("float32", "bfloat16"),
                   help="device dtype for the points (bfloat16 = MXU fast path)")
    p.add_argument("--layout", type=str, default="auto",
                   choices=("auto", "samples", "features"),
                   help="device storage layout for synthetic in-memory fits: "
                        "'features' stores points (d, N) — the TPU-native "
                        "layout for narrow d, where sample-major (N, d) "
                        "storage pads d to 128 lanes (25.6x HBM at d=5; see "
                        "ops/tall.py). 'auto' picks features on TPU when "
                        "d <= 32 and the fit is an in-memory kmeans/fuzzy")
    p.add_argument("--ckpt_dir", type=str, default=None,
                   help="checkpoint/resume directory (streamed mode): saves "
                        "centroids+iteration via orbax and resumes if "
                        "present. Checkpoints are size-portable (layout "
                        "manifest + full host arrays): a save taken at N "
                        "devices/processes resumes at M after an elastic "
                        "resize (docs/OPERATIONS.md)")
    p.add_argument("--prefetch", type=int, default=0,
                   help="streamed modes: background-thread batch prefetch "
                        "depth (0 = off, the measured-fastest default on "
                        "warm caches; enable for IO-bound cold streams, or "
                        "use --native_loader for GIL-free C++ prefetch)")
    p.add_argument("--ckpt_every_batches", type=int, default=None,
                   help="with --ckpt_dir: also checkpoint mid-pass every N "
                        "batches (accumulator + batch cursor; resume is "
                        "bit-identical)")
    # Defaults live in ONE place — data/ingest.IngestPolicy; None here
    # means "not set on the command line" so the fail-fast gate below can
    # distinguish an explicit value from the default without re-deriving
    # the numbers.
    p.add_argument("--io_retries", type=int, default=None,
                   help="streamed kmeans/fuzzy: transient stream-read "
                        "failures retried per batch read with exponential "
                        "backoff + jitter (data/ingest.py; 0 disables "
                        "retry; permanent failures never retry; "
                        "default 2)")
    p.add_argument("--io_backoff", type=float, default=None,
                   help="base retry backoff seconds (attempt n sleeps "
                        "~base * 2^(n-1) with deterministic jitter; "
                        "default 0.05)")
    p.add_argument("--io_deadline", type=float, default=None,
                   help="wall-clock budget in seconds for one batch read "
                        "including retries (default: none)")
    p.add_argument("--max_bad_fraction", type=float, default=None,
                   help="largest fraction of a pass's rows the ingest "
                        "quarantine may drop before the fit aborts loudly. "
                        "The strict default 0.0 aborts on ANY quarantined "
                        "batch — raise only when bounded data loss is "
                        "acceptable and monitored (tdc_ingest_* metrics)")
    p.add_argument("--ckpt_keep_last_n", type=int, default=None,
                   help="with --ckpt_dir (streamed kmeans/fuzzy): retain "
                        "only the newest N checkpoint steps (default all; "
                        "N >= 2 keeps the corruption-fallback step)")
    # Multi-host (jax.distributed over DCN); on managed TPU pods these
    # autodetect — pass explicitly for manual clusters.
    p.add_argument("--coordinator_address", type=str, default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--cache_dir", "--compile_cache_dir", dest="cache_dir",
                   type=str,
                   default=os.environ.get(
                       "TDC_COMPILE_CACHE",
                       os.path.expanduser("~/.cache/tdc_tpu_xla"),
                   ),
                   help="persistent XLA compilation cache ('' disables; "
                        "default $TDC_COMPILE_CACHE — gang relaunches "
                        "after preemption skip recompiles; thresholds via "
                        "TDC_COMPILE_CACHE_MIN_COMPILE_SECS / "
                        "TDC_COMPILE_CACHE_MIN_ENTRY_BYTES)")
    p.add_argument("--history_file", type=str, default=None,
                   help="write per-iteration (sse, shift) CSV (streamed mode)")
    p.add_argument("--weight_file", type=str, default=None,
                   help=".npy of (N,) nonnegative per-point sample weights "
                        "(sklearn sample_weight parity; in-memory and "
                        "streamed kmeans/fuzzy/gaussianMixture fits)")
    p.add_argument("--metrics", action="store_true",
                   help="after the fit, score the clustering (silhouette / "
                        "Davies-Bouldin / Calinski-Harabasz; the reference "
                        "validated visually only) and print + run-log them")
    p.add_argument("--metrics_sample", type=int, default=10000,
                   help="subsample size for the O(N²) silhouette "
                        "(0 = use all points)")
    return p


def validate_args(parser, args):
    if (args.data_file is None and args.data_manifest is None
            and (args.n_obs is None or args.n_dim is None)):
        parser.error("either --data_file, --data_manifest, or both "
                     "--n_obs and --n_dim required")
    if args.data_file is not None and not os.path.exists(args.data_file):
        parser.error(f"data file does not exist: {args.data_file}")
    if args.store_timeout is not None and args.store_timeout <= 0:
        parser.error("--store_timeout must be > 0 seconds")
    if ((args.store_timeout is not None or args.store_base)
            and not args.data_manifest):
        parser.error("--store_timeout/--store_base require --data_manifest")
    if args.data_manifest:
        # The manifest stream feeds the guarded streamed kmeans/fuzzy
        # drivers (1-D and K-sharded) only; reject every other route
        # rather than silently ignore, per the CLI's standing rule.
        if args.data_file or args.native_loader:
            parser.error("--data_manifest replaces --data_file/"
                         "--native_loader (the manifest names its own "
                         "blobs)")
        if args.method_name not in ("distributedKMeans",
                                    "distributedFuzzyCMeans"):
            parser.error("--data_manifest feeds the guarded streamed "
                         "kmeans/fuzzy drivers only")
        if not args.streamed:
            parser.error("--data_manifest requires --streamed (the "
                         "object-store tier is a streaming data plane; "
                         "batch size comes from the manifest)")
        if args.num_batches > 1:
            parser.error("--data_manifest takes its batching from the "
                         "manifest's batch_rows (the per-slice CRCs are "
                         "computed at that granularity); --num_batches "
                         "cannot override it")
        if args.minibatch or args.mean_combine:
            parser.error("--data_manifest supports the exact streamed "
                         "drivers only (not --minibatch/--mean_combine)")
        if args.layout == "features":
            parser.error("--data_manifest streams sample-major batches; "
                         "--layout=features is an in-memory device layout")
        if args.weight_file:
            parser.error("--data_manifest has no weight stream aligned "
                         "to manifest batches; drop --weight_file")
        if args.metrics:
            parser.error("--metrics scores in-memory points; "
                         "--data_manifest keeps the dataset in the "
                         "object store")
    for name in ("K", "n_max_iters"):
        if getattr(args, name) < 1:
            parser.error(f"--{name} must be >= 1")
    if args.n_obs is not None and args.n_obs < args.K:
        parser.error("--n_obs must be >= --K")
    if args.shard_k > 1:
        if args.K % args.shard_k != 0:
            parser.error(f"--K={args.K} not divisible by --shard_k={args.shard_k}")
        if args.method_name not in ("distributedKMeans",
                                    "distributedFuzzyCMeans",
                                    "gaussianMixture"):
            parser.error("--shard_k supports distributedKMeans, "
                         "distributedFuzzyCMeans, and gaussianMixture")
        if args.minibatch:
            parser.error("--minibatch and --shard_k are mutually exclusive")
        if args.method_name == "gaussianMixture":
            # The K-sharded GMM tower runs in-memory AND streamed (round 5)
            # but stays f32 XLA with no checkpoint/history; reject the
            # unsupported combos rather than silently ignore, per the
            # CLI's standing rule.
            if args.kernel == "pallas":
                parser.error("--shard_k --kernel=pallas is kmeans/fuzzy "
                             "only (the GMM shard tower is an XLA matmul "
                             "step)")
            if args.history_file:
                parser.error("--shard_k --history_file is kmeans/fuzzy "
                             "only (the GMM shard tower records no "
                             "per-iteration history)")
            if args.init == "kmeans":
                parser.error("--shard_k gaussianMixture seeds from a host "
                             "subsample; --init=kmeans (a full K-Means "
                             "pre-fit) is the unsharded mode")
    if args.gather != "fp32":
        if args.shard_k <= 1:
            parser.error("--gather applies to the K-sharded drivers "
                         "(model-axis collectives only exist there); add "
                         "--shard_k")
        if args.method_name == "gaussianMixture":
            parser.error("--gather is kmeans/fuzzy only (the GMM shard "
                         "tower keeps the replicated M-step)")
    if args.probe is not None and args.assign not in ("coarse", "auto"):
        parser.error("--probe needs --assign coarse|auto")
    if args.probe is not None and args.probe != "all":
        _valid_int(parser, "--probe", args.probe, 1)
    if args.bounds is not None and args.assign != "bounded":
        parser.error("--bounds needs --assign bounded")
    if args.assign == "bounded":
        if args.spherical:
            parser.error("--assign bounded does not support --spherical")
        if args.residency not in ("hbm", "auto"):
            parser.error("--assign bounded needs --residency hbm|auto "
                         "(per-point bounds live in the HBM-resident "
                         "cache; without it the fit would silently run "
                         "exact — ask for what you mean)")
        if args.bounds == "elkan" and args.shard_k > 1:
            parser.error("--bounds elkan is 1-D only (the K-sharded "
                         "bounded tower runs per-shard hamerly bounds)")
    if args.assign is not None:
        # Sub-linear assignment rides the streamed / K-sharded kmeans
        # drivers (models/streaming.py, parallel/sharded_k.py).
        if args.method_name != "distributedKMeans":
            parser.error("--assign is distributedKMeans only")
        if not (args.streamed or args.num_batches > 1 or args.shard_k > 1):
            parser.error("--assign needs a streamed or K-sharded fit "
                         "(--streamed / --num_batches / --shard_k)")
        if args.minibatch or args.mean_combine:
            parser.error("--assign supports the exact streamed driver "
                         "only (not --minibatch / --mean_combine)")
        if args.weight_file:
            parser.error("--assign coarse has no weighted fold; drop "
                         "--weight_file or --assign")
        if args.kernel in ("pallas", "pallas_bf16", "refined"):
            parser.error("--assign coarse is its own tile-pruned stats "
                         "path; --kernel pallas/refined cannot combine "
                         "with it")
    if args.minibatch and args.method_name != "distributedKMeans":
        parser.error("--minibatch supports distributedKMeans only")
    if args.method_name == "gaussianMixture":
        for flag in ("minibatch", "mean_combine", "spherical"):
            if getattr(args, flag):
                parser.error(f"--{flag} is not supported with gaussianMixture")

        if args.shard_k > 1 and args.covariance_type != "diag":
            parser.error("--shard_k gaussianMixture is diag-covariance only")
        if args.ckpt_every_batches:
            parser.error("gaussianMixture checkpoints per iteration only "
                         "(--ckpt_every_batches is kmeans/fuzzy)")
        if args.kernel == "pallas":
            # Reject rather than silently downgrade to the XLA E-step — an
            # explicit kernel request must not record XLA numbers as Pallas.
            if (args.covariance_type not in ("diag", "spherical")
                    or args.weight_file):
                parser.error("--kernel=pallas gaussianMixture supports the "
                             "diag/spherical, unweighted E-step only "
                             "(spherical runs the diag kernel with the "
                             "scalar variance broadcast)")
            # Only the EXPLICIT flag is checkable here: resolving the
            # implicit every-local-device default needs jax.device_count(),
            # which would initialize the backend before run_experiment's
            # jax.config.update('jax_platforms', --backend) and resolve the
            # count on the wrong platform. The implicit case is guarded in
            # run_experiment after n_devices resolves.
            if args.n_devices and args.n_devices > 1:
                parser.error("--kernel=pallas gaussianMixture is "
                             "single-device")
            # Fail fast when the shape is known here (--n_dim given).
            # --data_file runs (n_dim unknown until load) are covered by the
            # same check inside gmm_fit/streamed_gmm_fit, which raises into
            # the CSV error row. Streamed batches stay f32 regardless of
            # --dtype (bf16 applies to in-memory device arrays only), so the
            # itemsize must match what the fit will actually see.
            if args.n_dim is not None:
                from tdc_tpu.ops.pallas_kernels import gmm_block_n

                streamed = args.streamed or args.num_batches > 1
                itemsize = (
                    2 if (args.dtype == "bfloat16" and not streamed) else 4
                )
                if gmm_block_n(args.K, args.n_dim, itemsize) == 0:
                    parser.error(
                        f"--kernel=pallas gaussianMixture: K={args.K}, "
                        f"n_dim={args.n_dim} exceeds the fused E-step's VMEM "
                        "feasibility (gmm_stats_auto would silently run the "
                        "XLA E-step); drop --kernel=pallas"
                    )
    elif args.init == "kmeans":
        parser.error("--init=kmeans is a gaussianMixture seeding mode")
    elif args.covariance_type != "diag":
        parser.error("--covariance_type applies to gaussianMixture only")
    if args.method_name == "bisectingKMeans":
        # Splits are mask-weighted 2-means: in-memory over the full array,
        # exact streamed weighted Lloyd with --streamed/--num_batches
        # (round-3 VERDICT weak #5), and mesh-sharded over the data axis
        # with --n_GPUs>1 (round-4 weak #8 — the weight mask shards
        # alongside the points).
        for flag in ("minibatch", "mean_combine", "spherical"):
            if getattr(args, flag):
                parser.error(f"--{flag} is not supported with "
                             "bisectingKMeans")
        if args.shard_k > 1:
            parser.error("bisectingKMeans has no sharded-K mode (its "
                         "2-cluster splits have no K axis to shard)")
        if args.kernel is not None:
            parser.error("bisectingKMeans has no --kernel selection (each "
                         "split is a weighted XLA-path 2-means)")
        if args.ckpt_dir or args.ckpt_every_batches:
            parser.error("bisectingKMeans does not checkpoint")
        # Reject rather than silently ignore (same rule as the pallas gate).
        if args.init != "kmeans++":
            parser.error("bisectingKMeans seeds every split with kmeans++; "
                         f"--init={args.init} would be silently ignored")
        if args.history_file:
            parser.error("bisectingKMeans produces no per-iteration "
                         "history (--history_file is kmeans/fuzzy)")
    if args.empty_policy != "keep":
        # Only the in-memory Lloyd loop implements relocation; reject every
        # other route rather than silently keeping stale centroids.
        if args.method_name != "distributedKMeans":
            parser.error("--empty_policy=relocate is distributedKMeans only")
        for flag in ("minibatch", "streamed", "mean_combine"):
            if getattr(args, flag):
                parser.error(f"--empty_policy=relocate is in-memory only; "
                             f"--{flag} is not supported (mini-batch has "
                             "its own --reassignment_ratio policy)")
        if args.num_batches > 1 or args.shard_k > 1:
            parser.error("--empty_policy=relocate is in-memory single-shard")
        if args.layout == "features":
            parser.error("--empty_policy=relocate needs the sample-major "
                         "layout (--layout=samples)")
    if args.kernel == "refined":
        # The exact-champion path exists for tol-driven trajectory parity;
        # only the in-memory Lloyd fit implements it. Reject every other
        # route rather than silently recording xla numbers as 'refined'.
        if args.method_name != "distributedKMeans":
            parser.error("--kernel=refined is distributedKMeans only")
        for flag in ("minibatch", "streamed", "mean_combine"):
            if getattr(args, flag):
                parser.error(f"--kernel=refined is the in-memory exact-"
                             f"champion path; --{flag} is not supported")
        if args.num_batches > 1 or args.shard_k > 1:
            parser.error("--kernel=refined is in-memory single-shard "
                         "(use it for iters-to-converge parity runs)")
    if args.kernel == "pallas_bf16":
        # bf16-MXU / f32-accumulate distance epilogue: kmeans only,
        # single-device (models/kmeans rejects mesh/weights at fit time;
        # catch the CLI-visible combinations at parse time, per the
        # standing explicit-kernel fail-fast rule). The streamed driver
        # runs it per-batch (streamed_kmeans_fit's pallas_bf16 branch);
        # minibatch/mean_combine have no epilogue plumbing.
        if args.method_name != "distributedKMeans":
            parser.error("--kernel=pallas_bf16 is distributedKMeans only "
                         "(the bf16-MXU epilogue exists for the Lloyd "
                         "stats kernel)")
        for flag in ("minibatch", "mean_combine"):
            if getattr(args, flag):
                parser.error(f"--kernel=pallas_bf16 has no --{flag} "
                             f"plumbing (the epilogue lives in the fused "
                             f"Lloyd stats kernel)")
        if (args.num_batches > 1 and not args.streamed) or args.shard_k > 1:
            parser.error("--kernel=pallas_bf16 is single-shard (in-memory "
                         "or --streamed)")
        if args.n_devices and args.n_devices > 1:
            parser.error("--kernel=pallas_bf16 is single-device (no "
                         "shard_map tower; cast inputs to bf16 with "
                         "--kernel=pallas for the same MXU precision)")
        if args.weight_file:
            parser.error("--kernel=pallas_bf16 does not support "
                         "--weight_file (the weighted epilogue keeps full "
                         "precision)")
    if args.metrics_sample < 0:
        parser.error("--metrics_sample must be >= 0")
    if args.weight_file:
        if not os.path.exists(args.weight_file):
            parser.error(f"weight file does not exist: {args.weight_file}")
        if args.minibatch or args.mean_combine or args.shard_k > 1:
            parser.error("--weight_file is not supported with "
                         "--minibatch/--mean_combine/--shard_k")
        if args.kernel == "refined":
            parser.error("--kernel=refined does not support --weight_file")
        if args.kernel == "pallas":
            # Weighted Pallas stats exist for kmeans only (fused/sorted
            # weighted kernels, single-device — round-5); fuzzy/GMM
            # weighted stats stay f32 XLA. Reject rather than record XLA
            # numbers as Pallas (the standing rule). The implicit
            # every-device default is caught by the model-level
            # single-device check at runtime.
            if args.method_name != "distributedKMeans":
                parser.error("--kernel=pallas --weight_file is "
                             "distributedKMeans only (fuzzy/GMM weighted "
                             "stats are the f32 XLA path)")
            if args.n_devices and args.n_devices > 1:
                parser.error("--kernel=pallas --weight_file is "
                             "single-device (the weighted kernels have no "
                             "shard_map tower); pass --n_GPUs=1")
    if args.mean_combine:
        if args.method_name != "distributedKMeans":
            parser.error("--mean_combine supports distributedKMeans only")
        if args.minibatch or args.shard_k > 1:
            parser.error("--mean_combine excludes --minibatch/--shard_k")
    if args.ckpt_dir and args.mean_combine:
        # mean_combine has no checkpoint support; accepting the flag would
        # silently skip checkpointing AND corrupt the computation timing.
        parser.error("--ckpt_dir is not supported with --mean_combine")
    if args.ckpt_keep_last_n is not None:
        # Reject rather than silently ignore (the --covariance_type rule):
        # retention is wired through the 1-D streamed kmeans/fuzzy drivers.
        if args.ckpt_keep_last_n < 1:
            parser.error("--ckpt_keep_last_n must be >= 1")
        if not args.ckpt_dir:
            parser.error("--ckpt_keep_last_n requires --ckpt_dir")
        if (args.minibatch or args.shard_k > 1
                or args.method_name == "gaussianMixture"):
            parser.error("--ckpt_keep_last_n applies to the 1-D streamed "
                         "kmeans/fuzzy fits only")
    if args.io_retries is not None and args.io_retries < 0:
        parser.error("--io_retries must be >= 0")
    if args.io_backoff is not None and args.io_backoff < 0:
        parser.error("--io_backoff must be >= 0")
    if args.io_deadline is not None and args.io_deadline <= 0:
        parser.error("--io_deadline must be > 0 seconds")
    if args.max_bad_fraction is not None and not (
        0.0 <= args.max_bad_fraction <= 1.0
    ):
        parser.error("--max_bad_fraction must be in [0, 1]")
    if not (0 <= args.reassignment_ratio <= 1):
        parser.error("--reassignment_ratio must be in [0, 1]")
    if args.reassignment_ratio != 0.01 and not args.minibatch:
        # Reject rather than silently ignore (the --covariance_type rule):
        # the flag only drives the mini-batch reseed policy.
        parser.error("--reassignment_ratio applies to --minibatch only")
    if args.layout == "features":
        if args.method_name not in ("distributedKMeans",
                                    "distributedFuzzyCMeans"):
            parser.error("--layout=features supports kmeans/fuzzy only")
        for flag in ("streamed", "minibatch", "mean_combine", "native_loader"):
            if getattr(args, flag):
                parser.error(f"--layout=features is an in-memory device "
                             f"layout; --{flag} is not supported with it")
        if args.num_batches > 1 or args.shard_k > 1:
            parser.error("--layout=features is single-batch, single-shard "
                         "(it exists to make the full dataset fit in HBM)")
        if args.weight_file:
            parser.error("--layout=features does not support --weight_file")
        if args.kernel is not None:
            parser.error("--layout=features selects the tall kernel; "
                         "--kernel cannot be combined with it")


def run_experiment(args) -> dict:
    """Load/generate data, fit, and return the result row dict.

    Mirrors the reference main() (:320-409): 3-phase timers, OOM-adaptive
    batching, error capture handled by the caller.
    """
    # Span tracing (obs/trace, stdlib-only): enabled before any fit code
    # runs so pass/phase spans land from the first batch.
    if args.trace:
        from tdc_tpu.obs import trace as trace_lib

        trace_lib.configure(args.trace)

    # Deferred imports so --help works instantly and --backend can take effect.
    if args.backend:
        import jax
        jax.config.update("jax_platforms", args.backend)
    else:
        # A machine sitecustomize may pre-import jax and pin jax_platforms
        # before the environment is consulted, silently ignoring an explicit
        # JAX_PLATFORMS (e.g. the CPU-mesh drive recipe). Re-assert it —
        # the same dance as bench.py and __graft_entry__.dryrun_multichip.
        env_platforms = os.environ.get("JAX_PLATFORMS")
        if env_platforms:
            import jax
            try:
                if jax.config.jax_platforms != env_platforms:
                    jax.config.update("jax_platforms", env_platforms)
            except Exception:
                pass
    import jax

    # Persistent XLA compilation cache: the reference's graph-build cost
    # was per-run (setup 20-33 s, executions_log.csv); ours is per-shape
    # and amortizes across runs — and across gang relaunches after a
    # preemption (utils/compile_cache). Called even for --cache_dir ''
    # so the opt-out sticks: initialize_distributed's enable_from_env()
    # must not re-enable from $TDC_COMPILE_CACHE over an explicit flag.
    from tdc_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(args.cache_dir)

    if args.num_processes or args.coordinator_address:
        from tdc_tpu.parallel.multihost import initialize_distributed

        initialize_distributed(
            args.coordinator_address, args.num_processes, args.process_id
        )
    from tdc_tpu.data import load_points, make_blobs, NpzStream
    from tdc_tpu.data.batching import oom_adaptive
    from tdc_tpu.models import (
        fuzzy_cmeans_fit,
        kmeans_fit,
        streamed_fuzzy_fit,
        streamed_kmeans_fit,
    )
    from tdc_tpu.parallel import make_mesh
    from tdc_tpu.parallel.meshspec import MeshSpec
    from tdc_tpu.utils.timing import PhaseTimers

    timers = PhaseTimers()

    use_features = False
    with timers.phase("setup"):
        n_devices = args.n_devices or len(jax.devices())
        if args.data_file:
            if args.layout == "features":
                # Real-dataset tall layout (round-5 VERDICT weak #5): load
                # feature-major — mmap pass-through for *.fm.npy files,
                # chunked host transpose otherwise (data/loader.py) — and
                # run the tall kernels exactly as the synthetic path does.
                # The parse-time validation already pinned this to the
                # in-memory single-batch kmeans/fuzzy regime.
                if n_devices > 1:
                    # Checked on the RESOLVED count (the implicit default
                    # is every local device), before paying the data load.
                    raise ValueError(
                        "--layout=features is single-device; pass --n_GPUs=1"
                    )
                from tdc_tpu.data import load_points_feature_major

                x, _ = load_points_feature_major(args.data_file)
                n_dim, n_obs = x.shape
                use_features = True
            else:
                x, _ = load_points(args.data_file)
                n_obs, n_dim = x.shape
        manifest = None
        manifest_url = None
        if args.data_manifest:
            # Object-store tier: the dataset never lands in host memory.
            # Geometry, dtype, and batching all come from the manifest
            # document; x stays None (validate_args pinned this to the
            # streamed kmeans/fuzzy drivers, which only touch the stream).
            from tdc_tpu.data.store import fetch_manifest, resolve_url

            manifest_url = resolve_url(args.data_manifest, args.store_base)
            manifest = fetch_manifest(
                manifest_url,
                **({} if args.store_timeout is None
                   else {"timeout": args.store_timeout}),
            )
            x = None
            n_obs, n_dim = manifest.n_rows, manifest.d
        if (args.method_name == "gaussianMixture" and args.kernel == "pallas"
                and n_devices > 1):
            # The parse-time copy of this rule can only see an explicit
            # --n_GPUs (resolving the default would initialize the wrong
            # backend); the implicit every-local-device case lands here and
            # is captured as a CSV error row like any other runtime error.
            raise ValueError(
                "--kernel=pallas gaussianMixture is single-device "
                f"(resolved n_devices={n_devices}); pass --n_GPUs=1"
            )
        if not args.data_file and not args.data_manifest:
            n_obs, n_dim = args.n_obs, args.n_dim
            # Fully in-memory single-device fits keep the generated points on
            # device: a host round trip of the whole dataset through a
            # tunneled device link costs far more than generation + fit. The
            # host-slicing paths (streaming/minibatch/shard_k/mean_combine,
            # multi-device sharding) still get numpy, as do datasets big
            # enough that the OOM-adaptive batching fallback is plausible
            # (device-resident data would escape it). Generated directly in
            # the fit dtype so bf16 runs hold one device copy, not two.
            on_tpu = jax.devices()[0].platform == "tpu"
            streamy = (
                args.streamed or args.num_batches > 1 or args.minibatch
                or args.mean_combine or args.shard_k > 1 or n_devices > 1
            )
            feat_ok = (
                args.method_name in ("distributedKMeans",
                                     "distributedFuzzyCMeans")
                and not streamy and not args.weight_file
                # An explicit --kernel (even 'xla') pins the sample-major
                # layout so benchmark runs stay comparable across flags.
                and args.kernel is None
                and args.empty_policy == "keep"  # relocation gathers rows
            )
            if args.layout == "features":
                if not feat_ok:
                    raise ValueError(
                        "--layout=features requires an in-memory single-"
                        "device kmeans/fuzzy fit with the default kernel"
                    )
                use_features = True
            elif args.layout == "auto":
                use_features = feat_ok and on_tpu and n_dim <= 32
                if use_features:
                    # The tall kernels keep (K_s, BN) tiles + the (K, d)
                    # accumulator in VMEM; beyond their feasibility the
                    # sample-major kernels must keep working unchanged.
                    from tdc_tpu.ops.tall import tall_block_n

                    temps = (
                        5 if args.method_name == "distributedFuzzyCMeans"
                        else 3
                    )
                    use_features = tall_block_n(
                        args.K, n_dim,
                        2 if args.dtype == "bfloat16" else 4,
                        temps=temps,
                    ) > 0
            itemsize = 2 if args.dtype == "bfloat16" else 4
            if on_tpu:
                # TPU HBM stores (sublane, lane) = (8·4/itemsize, 128) tiles:
                # sample-major rows pad d to 128 lanes, feature-major columns
                # pad d to the sublane multiple (ops/tall.py rationale).
                sub = 8 * 4 // itemsize
                per_pt = itemsize * (
                    -(-n_dim // sub) * sub if use_features
                    else -(-n_dim // 128) * 128
                )
            else:
                per_pt = itemsize * n_dim
            needs_host = streamy
            gen_dtype = np.float32
            if not needs_host:
                try:
                    hbm = int(jax.devices()[0].memory_stats()
                              .get("bytes_limit", 16 << 30))
                except Exception:
                    hbm = 16 << 30
                needs_host = n_obs * per_pt > 0.4 * hbm
            if args.dtype == "bfloat16":
                # In-memory: one bf16 device copy instead of f32+cast.
                # Host/streamed: bf16 host generation halves RAM AND the
                # per-pass H2D transfer — the "batched bf16" configuration
                # for the 100M×256 regime (a 100M×256 f32 host array would
                # need ~205 GB at the generation concat peak; bf16 fits).
                # Stats accumulate f32 either way.
                import jax.numpy as jnp

                gen_dtype = jnp.bfloat16
            if needs_host and use_features:
                if args.layout == "features":
                    raise ValueError(
                        f"n_obs={n_obs} x d={n_dim} exceeds the HBM budget "
                        "even feature-major; drop --layout=features and "
                        "stream (--num_batches)"
                    )
                # Too big even feature-major → host generation + streaming.
                use_features = False
            x, _ = make_blobs(args.seed + 1, n_obs, n_dim, max(args.K, 2),
                              class_sep=args.class_sep, to_host=needs_host,
                              dtype=gen_dtype,
                              layout="features" if use_features else "samples")
        weights = None
        if args.weight_file:
            weights = np.load(args.weight_file)
            if weights.ndim != 1 or weights.shape[0] != n_obs:
                raise ValueError(
                    f"weight file has shape {weights.shape}; expected "
                    f"({n_obs},)"
                )
        mesh2d = None
        if args.shard_k > 1:
            if n_devices % args.shard_k != 0:
                raise ValueError(
                    f"n_devices={n_devices} not divisible by shard_k={args.shard_k}"
                )
            from tdc_tpu.parallel.sharded_k import make_mesh_2d

            mesh2d = make_mesh_2d(n_devices // args.shard_k, args.shard_k)
            mesh = None
        else:
            mesh = make_mesh(n_devices) if n_devices > 1 else None

    key = jax.random.PRNGKey(args.seed)

    def host_points():
        # Streamed paths need numpy. After an OOM fallback from a
        # device-resident dataset, convert once and REBIND x so the HBM copy
        # is freed before the streamed retry doubles batches again. A
        # feature-major device array comes back sample-major (the streamed
        # drivers slice rows).
        nonlocal x, use_features
        if not isinstance(x, np.ndarray):
            x = np.asarray(x).T if use_features else np.asarray(x)
            use_features = False
        return x

    def fit(num_batches: int):
        import jax.numpy as jnp

        streamed = args.streamed or num_batches > 1
        if args.reduce != "per_batch":
            # Fail fast instead of silently ignoring the knob: only the
            # streamed drivers take reduce= (in-memory fits are already
            # one reduce per iteration by construction; mean_combine /
            # minibatch / the K-sharded GMM driver have no knob).
            unsupported = (
                not streamed or args.mean_combine or args.minibatch
                or args.method_name == "bisectingKMeans"
                or (mesh2d is not None
                    and args.method_name == "gaussianMixture")
            )
            if unsupported:
                raise SystemExit(
                    f"--reduce={args.reduce} applies to the streamed "
                    "kmeans/fuzzy/gaussianMixture drivers (add "
                    "--streamed/--num_batches); in-memory fits already "
                    "reduce once per iteration, and mean_combine/minibatch/"
                    "bisecting/--shard_k gaussianMixture take no strategy"
                )
        if args.residency != "stream":
            # Same standing rule: fail instead of silently ignoring the
            # knob on a path with no resident loop.
            unsupported = (
                not streamed or args.mean_combine or args.minibatch
                or args.method_name in ("bisectingKMeans", "gaussianMixture")
            )
            if unsupported:
                raise SystemExit(
                    f"--residency={args.residency} applies to the streamed "
                    "kmeans/fuzzy drivers (add --streamed/--num_batches); "
                    "in-memory fits are already device-resident, and "
                    "gaussianMixture/bisecting/mean_combine/minibatch "
                    "have no resident loop"
                )
            if args.residency == "hbm" and args.ckpt_every_batches:
                raise SystemExit(
                    "--residency=hbm is incompatible with "
                    "--ckpt_every_batches: the compiled on-device loop has "
                    "no mid-pass boundaries to checkpoint at — drop one, "
                    "or use --residency=auto to prefer mid-pass durability"
                )
        from tdc_tpu.data.ingest import IngestPolicy

        ingest_overrides = {
            name: val for name, val in (
                ("io_retries", args.io_retries),
                ("io_backoff", args.io_backoff),
                ("io_deadline", args.io_deadline),
                ("max_bad_fraction", args.max_bad_fraction),
            ) if val is not None
        }
        if ingest_overrides:
            # Standing rule: fail fast instead of silently ignoring knobs
            # on a path that never routes through the ingest guard. The
            # K-sharded kmeans path always runs its (guarded) streamed
            # driver; K-sharded fuzzy only when streamed/checkpointed.
            guarded = (
                streamed
                or (mesh2d is not None
                    and (args.method_name == "distributedKMeans"
                         or (args.method_name == "distributedFuzzyCMeans"
                             and (args.ckpt_dir
                                  or args.ckpt_every_batches))))
            )
            unsupported = (
                not guarded or args.mean_combine or args.minibatch
                or args.method_name in ("bisectingKMeans", "gaussianMixture")
            )
            if unsupported:
                raise SystemExit(
                    "--io_retries/--io_backoff/--io_deadline/"
                    "--max_bad_fraction apply to the streamed kmeans/fuzzy "
                    "drivers (add --streamed/--num_batches); "
                    "gaussianMixture/bisecting/mean_combine/minibatch "
                    "streams are not routed through the ingest guard"
                )
        ingest_policy = IngestPolicy(**ingest_overrides)

        def residency_rows(rows: int, itemsize: int = 4,
                           n_cache_devices: int | None = None) -> int:
            """With a resident cache pinned in HBM for the whole fit, the
            per-batch working set must fit the REMAINDER of the budget —
            cap the batch rows via auto_batch_size(resident_bytes=...).
            Without this, an over-sized batch OOMs the fill pass and
            oom_adaptive halves batches forever against a budget that can
            never fit (the cache does not shrink when batches do).
            `n_cache_devices` is how many ways the cache itself divides:
            the K-sharded cache is sharded over the data axis only and
            REPLICATED across the model axis (_plan_sharded_residency), so
            those call sites pass n_devices // shard_k, not n_devices.

            This pre-check approximates plan_residency (which sees the
            stream's real padded-batch geometry this helper is still
            choosing): cache bytes here are unpadded, an under-estimate
            of at most (pad_multiple-1)/batch_rows. In the sliver where
            they disagree the planner still decides — worst case a
            slightly-too-large cap makes the fill abandon loudly and the
            fit streams; never a silent OOM spiral.

            Explicit --residency=spill skips the cap: the ring pins only
            (slots+1) batch slots, not the whole cache — the full-cache
            `pinned` math below would wrongly shrink batches for a fit
            that never builds a cache. Note an explicit spill whose ring
            exceeds the budget is FORCED past the planner's model
            (residency_forced_over_budget, like --residency=hbm) and can
            OOM during staging — only 'auto' degrades ring-doesn't-fit
            to streaming. Under 'auto' the full-cache math stays: it is
            exactly the hbm-tier feasibility pre-check, and when the
            cache can't fit the pinned >= budget early-return below
            already skips the cap."""
            if args.residency in ("stream", "spill"):
                return rows
            from tdc_tpu.data.batching import (
                auto_batch_size,
                hbm_budget_bytes,
            )
            from tdc_tpu.data.device_cache import state_reserve_bytes
            from tdc_tpu.utils.structlog import emit

            # Pinned alongside every batch: the cache shard plus the
            # O(K*d) model-state copies plan_residency reserves — both
            # must come out of the budget before the batch working set,
            # or the cap admits batches the planner's feasibility test
            # then rejects.
            pinned = (
                -(-n_obs * n_dim * itemsize
                  // max(n_cache_devices or n_devices, 1))
                + state_reserve_bytes(args.K, n_dim)
            )
            if pinned >= hbm_budget_bytes():
                # The cache + state cannot fit: plan_residency will fall
                # back to streaming (auto) or fail loudly in the fit
                # (hbm). Capping the stream against the exhausted
                # post-cache remainder here would collapse it to 1-row
                # batches for a fit that ends up streaming anyway.
                return rows
            cap = auto_batch_size(
                n_dim, args.K, n_devices=n_devices, itemsize=itemsize,
                kernel="pallas" if args.kernel == "pallas" else "xla",
                resident_bytes=pinned,
            )
            if rows > cap:
                emit("residency_batch_cap", rows=rows, cap=cap,
                     resident_bytes=pinned)
                return cap
            return rows

        def weight_stream(rows):
            # aligned batch-for-batch with make_stream's row slicing
            return NpzStream(np.asarray(weights, np.float32), rows)
        # bf16 applies to the in-memory device paths; streamed batches keep
        # their on-disk dtype (stats accumulate in f32 either way), and the
        # shard_k drivers cast host-side per batch/fit (shard_dtype) — so
        # the eager full-dataset device cast must not run for them (it
        # would waste a full H2D + HBM copy the mesh2d branches never read).
        shard_dtype = jnp.bfloat16 if args.dtype == "bfloat16" else None
        xx = (
            jnp.asarray(x, jnp.bfloat16)
            if (args.dtype == "bfloat16" and not streamed
                and mesh2d is None)
            else x
        )
        def make_stream(rows):
            """Batch stream honoring --data_manifest (object-store ranged
            reads) and --native_loader (C++ prefetch off an .npy) for
            both the 1-D streamed and the K-sharded paths."""
            if args.data_manifest:
                # `rows` is ignored: the manifest fixes batch_rows (the
                # per-slice CRC granularity). Gang placement rides the
                # MeshSpec — disjoint shard sets for a 1-D gang, every
                # batch for K-sharded/single-process fits.
                from tdc_tpu.data.store import open_manifest_stream

                m = mesh2d if mesh2d is not None else mesh
                return open_manifest_stream(
                    manifest_url,
                    spec=MeshSpec.of(m) if m is not None else None,
                    **({} if args.store_timeout is None
                       else {"timeout": args.store_timeout}),
                )
            if args.native_loader:
                if not (args.data_file and args.data_file.endswith(".npy")):
                    raise ValueError("--native_loader requires an .npy --data_file")
                from tdc_tpu.data.native_loader import NativePrefetchStream

                return NativePrefetchStream(args.data_file, rows)
            return NpzStream(host_points(), rows)

        # Streamed batches keep their source dtype: the manifest declares
        # it outright (no x in host memory); otherwise the loaded or
        # generated array's dtype drives the residency cap sizing.
        def stream_itemsize() -> int:
            if args.data_manifest:
                return np.dtype(manifest.dtype).itemsize
            return np.dtype(x.dtype).itemsize

        if args.minibatch:
            from tdc_tpu.data.batching import auto_batch_size
            from tdc_tpu.models.minibatch import minibatch_kmeans_fit

            if num_batches > 1:
                rows = -(-n_obs // num_batches)
            else:
                rows = min(auto_batch_size(n_dim, args.K,
                                           n_devices=n_devices,
                                           kernel=args.kernel or "xla"),
                           n_obs)
            return minibatch_kmeans_fit(
                make_stream(rows), args.K, n_dim, init=args.init, key=key,
                epochs=args.n_max_iters, tol=args.tol, mesh=mesh,
                prefetch=args.prefetch,
                reassignment_ratio=args.reassignment_ratio,
                ckpt_dir=args.ckpt_dir,
                kernel=args.kernel or "xla",
            )
        # --assign/--probe/--bounds pass-through for the streamed kmeans
        # drivers (validate_args already restricted the combinations).
        # `bounds` is 1-D only — the K-sharded bounded tower is per-shard
        # hamerly by construction and takes no bound-kind knob.
        assign_kw = {}
        assign_kw_1d = {}
        if args.assign is not None:
            assign_kw = {
                "assign": args.assign,
                "probe": (args.probe if args.probe in (None, "all")
                          else int(args.probe)),
            }
            assign_kw_1d = dict(assign_kw)
            if args.bounds is not None:
                assign_kw_1d["bounds"] = args.bounds

        def shard_block(rows_per_pass: int) -> int:
            """N-block for the K-sharded towers: --block_rows, or the
            auto size bounding the per-(data-shard, K-shard) intermediates
            (the towers pad ragged shards to the block multiple exactly)."""
            from tdc_tpu.models.kmeans import auto_block_rows

            if args.block_rows >= 0:
                return args.block_rows
            n_data_ax = n_devices // args.shard_k
            return auto_block_rows(
                -(-rows_per_pass // n_data_ax), args.K // args.shard_k
            )

        if mesh2d is not None and args.method_name == "distributedFuzzyCMeans":
            # Checkpointing lives in the streamed driver (one batch subsumes
            # the in-memory case — the kmeans tower's rule); the plain
            # in-memory fit below keeps x device-resident across iterations.
            if streamed or args.ckpt_dir or args.ckpt_every_batches:
                from tdc_tpu.parallel.sharded_k import (
                    streamed_fuzzy_fit_sharded,
                )

                rows = (
                    # The manifest fixes batch_rows (the CRC slice size);
                    # the residency planner sees the stream's own geometry.
                    manifest.batch_rows if args.data_manifest
                    else residency_rows(
                        -(-n_obs // num_batches),
                        itemsize=2 if args.dtype == "bfloat16" else 4,
                        n_cache_devices=MeshSpec.of(mesh2d).n_data,
                    )
                )
                return streamed_fuzzy_fit_sharded(
                    make_stream(rows), args.K, n_dim, mesh2d,
                    m=args.fuzzifier, init=args.init, key=key,
                    max_iters=args.n_max_iters, tol=args.tol,
                    kernel=args.kernel or "xla",
                    block_rows=shard_block(rows),
                    dtype=shard_dtype,
                    prefetch=args.prefetch,
                    ckpt_dir=args.ckpt_dir,
                    ckpt_every_batches=args.ckpt_every_batches,
                    reduce=_sharded_reduce(args),
                    residency=args.residency,
                    ingest=ingest_policy,
                    gather=_sharded_gather(args),
                )
            from tdc_tpu.parallel.sharded_k import fuzzy_fit_sharded

            return fuzzy_fit_sharded(
                host_points(), args.K, mesh2d, m=args.fuzzifier,
                init=args.init, key=key, max_iters=args.n_max_iters,
                tol=args.tol, block_rows=shard_block(n_obs),
                kernel=args.kernel or "xla",
                dtype=shard_dtype,
            )
        if mesh2d is not None and args.method_name == "gaussianMixture":
            # Checkpointing lives in the streamed driver (one batch
            # subsumes the in-memory case — the kmeans/fuzzy rule).
            if streamed or args.ckpt_dir:
                from tdc_tpu.parallel.sharded_k import (
                    streamed_gmm_fit_sharded,
                )

                rows = -(-n_obs // num_batches)
                return streamed_gmm_fit_sharded(
                    make_stream(rows), args.K, n_dim, mesh2d,
                    init=args.init, key=key, max_iters=args.n_max_iters,
                    tol=args.tol, block_rows=shard_block(rows),
                    prefetch=args.prefetch,
                    dtype=shard_dtype,
                    ckpt_dir=args.ckpt_dir,
                )
            from tdc_tpu.parallel.sharded_k import gmm_fit_sharded

            return gmm_fit_sharded(
                host_points(), args.K, mesh2d, init=args.init, key=key,
                max_iters=args.n_max_iters, tol=args.tol,
                block_rows=shard_block(n_obs),
                dtype=shard_dtype,
            )
        if mesh2d is not None:
            # K-sharded 2-D layout: always the streamed driver — it subsumes
            # the in-memory case (one batch) and pads ragged batches exactly.
            from tdc_tpu.parallel.sharded_k import streamed_kmeans_fit_sharded

            rows = (
                # The manifest fixes batch_rows (the CRC slice size).
                manifest.batch_rows if args.data_manifest
                else residency_rows(
                    -(-n_obs // num_batches),
                    itemsize=2 if args.dtype == "bfloat16" else 4,
                    # The K-sharded cache divides over the DATA axis only
                    # (replicated across model shards) — the MeshSpec is
                    # the one source of that geometry (parallel/meshspec).
                    n_cache_devices=MeshSpec.of(mesh2d).n_data,
                )
            )
            block = shard_block(rows)
            return streamed_kmeans_fit_sharded(
                make_stream(rows), args.K, n_dim, mesh2d,
                init=args.init, key=key, max_iters=args.n_max_iters,
                tol=args.tol, spherical=args.spherical,
                kernel=args.kernel or "xla",
                block_rows=block,
                dtype=shard_dtype,
                prefetch=args.prefetch,
                ckpt_dir=args.ckpt_dir,
                ckpt_every_batches=args.ckpt_every_batches,
                reduce=_sharded_reduce(args),
                residency=args.residency,
                ingest=ingest_policy,
                gather=_sharded_gather(args),
                **assign_kw,
            )
        if args.method_name == "gaussianMixture":
            if streamed:
                from tdc_tpu.models.gmm import streamed_gmm_fit

                rows = -(-n_obs // num_batches)
                return streamed_gmm_fit(
                    make_stream(rows), args.K, n_dim, init=args.init,
                    key=key, max_iters=args.n_max_iters, tol=args.tol,
                    mesh=mesh, prefetch=args.prefetch,
                    ckpt_dir=args.ckpt_dir,
                    kernel=args.kernel or "xla",
                    covariance_type=args.covariance_type,
                    sample_weight_batches=(
                        weight_stream(rows) if weights is not None else None
                    ),
                    reduce=args.reduce,
                )
            from tdc_tpu.models.gmm import gmm_fit

            return gmm_fit(
                xx, args.K, init=args.init, key=key,
                max_iters=args.n_max_iters, tol=args.tol, mesh=mesh,
                covariance_type=args.covariance_type,
                sample_weight=weights,
                kernel=args.kernel or "xla",
            )
        if args.method_name == "bisectingKMeans":
            from tdc_tpu.models.bisecting import (
                bisecting_kmeans_fit,
                streamed_bisecting_kmeans_fit,
            )

            if streamed:
                rows = -(-n_obs // num_batches)
                return streamed_bisecting_kmeans_fit(
                    make_stream(rows), args.K, n_dim, key=key,
                    max_iters=args.n_max_iters, tol=args.tol,
                    prefetch=args.prefetch,
                    sample_weight_batches=(
                        weight_stream(rows) if weights is not None else None
                    ),
                    mesh=mesh,
                )
            return bisecting_kmeans_fit(
                xx, args.K, key=key, max_iters=args.n_max_iters,
                tol=args.tol, sample_weight=weights, mesh=mesh,
            )
        if args.method_name == "distributedFuzzyCMeans":
            if streamed:
                rows = (
                    manifest.batch_rows if args.data_manifest
                    else residency_rows(
                        -(-n_obs // num_batches),
                        # The 1-D streamed drivers never cast: the cache
                        # holds the stream's own dtype (bf16 only when
                        # generation or the data file made it so), unlike
                        # the shard_k sites where --dtype drives a
                        # host-side cast.
                        itemsize=stream_itemsize(),
                    )
                )
                return streamed_fuzzy_fit(
                    make_stream(rows) if args.data_manifest
                    else NpzStream(host_points(), rows), args.K, n_dim,
                    m=args.fuzzifier, init=args.init, key=key,
                    max_iters=args.n_max_iters, tol=args.tol, mesh=mesh,
                    ckpt_dir=args.ckpt_dir,
                    ckpt_every_batches=args.ckpt_every_batches,
                    ckpt_keep_last_n=args.ckpt_keep_last_n,
                    prefetch=args.prefetch,
                    sample_weight_batches=(
                        weight_stream(rows) if weights is not None else None
                    ),
                    kernel=args.kernel or "xla",
                    reduce=args.reduce,
                    residency=args.residency,
                    ingest=ingest_policy,
                )
            return fuzzy_cmeans_fit(
                xx, args.K, m=args.fuzzifier, init=args.init, key=key,
                max_iters=args.n_max_iters, tol=args.tol, mesh=mesh,
                kernel=args.kernel or "xla",
                sample_weight=weights,
                layout="features" if use_features else "samples",
                history=args.history_file is not None,
            )
        if streamed:
            rows = (
                manifest.batch_rows if args.data_manifest
                else residency_rows(
                    -(-n_obs // num_batches),
                    itemsize=stream_itemsize(),
                )
            )
            if args.mean_combine:
                from tdc_tpu.models import mean_combine_fit

                return mean_combine_fit(
                    make_stream(rows), args.K, n_dim, init=args.init,
                    key=key, max_iters=args.n_max_iters, tol=args.tol,
                    spherical=args.spherical, mesh=mesh,
                    prefetch=args.prefetch,
                    kernel=args.kernel or "xla",
                )
            return streamed_kmeans_fit(
                make_stream(rows), args.K, n_dim,
                init=args.init, key=key, max_iters=args.n_max_iters,
                tol=args.tol, spherical=args.spherical, mesh=mesh,
                ckpt_dir=args.ckpt_dir,
                ckpt_every_batches=args.ckpt_every_batches,
                ckpt_keep_last_n=args.ckpt_keep_last_n,
                prefetch=args.prefetch,
                sample_weight_batches=(
                    weight_stream(rows) if weights is not None else None
                ),
                kernel=args.kernel or "xla",
                reduce=args.reduce,
                residency=args.residency,
                ingest=ingest_policy,
                **assign_kw_1d,
            )
        return kmeans_fit(
            xx, args.K, init=args.init, key=key, max_iters=args.n_max_iters,
            tol=args.tol, spherical=args.spherical, mesh=mesh,
            kernel=args.kernel or "xla",
            sample_weight=weights,
            layout="features" if use_features else "samples",
            history=args.history_file is not None,
            empty_policy=args.empty_policy,
        )

    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    try:
        # Initialization phase = first compiled+executed step incl. H2D; we
        # fold compile into "initialization_time" (the reference's
        # var-init+H2D phase) by timing the first fit separately from a warm
        # re-fit below.
        with timers.phase("initialization") as out:
            result, num_batches = oom_adaptive(
                fit, initial_num_batches=args.num_batches
            )
            out["block_on"] = getattr(result, "centroids",
                                      getattr(result, "means", None))

        # Computation phase: warm path (compile cached) — what steady-state
        # clustering costs. The reference's computation_time likewise excluded
        # graph build (:276-280). On the checkpointing path (streamed kmeans +
        # --ckpt_dir) the first fit already wrote a checkpoint at its final
        # iteration; a warm re-fit would resume from it and run ~zero
        # iterations, reporting only a final stats pass as the whole
        # computation — so reuse the first fit's timing instead (compile
        # included; the honest number for a checkpointed run). Non-streamed
        # fits never receive ckpt_dir, so they keep the warm re-fit.
        checkpointed = bool(
            args.ckpt_dir
            and (args.streamed or num_batches > 1 or args.shard_k > 1
                 or args.minibatch)
        )
        if checkpointed:
            timers.set("computation", timers.get("initialization"))
        else:
            with timers.phase("computation") as out:
                result = fit(num_batches)
                out["block_on"] = getattr(result, "centroids",
                                      getattr(result, "means", None))
    finally:
        if args.profile_dir:
            jax.profiler.stop_trace()

    if args.history_file and getattr(result, "history", None) is not None:
        import csv as _csv

        # K-Means history rows hold SSE; fuzzy rows hold the J_m objective.
        cost_col = (
            "objective" if args.method_name == "distributedFuzzyCMeans" else "sse"
        )
        with open(args.history_file, "w", newline="") as f:
            w = _csv.writer(f, lineterminator="\n")
            w.writerow(["iteration", cost_col, "shift"])
            for i, (cost_i, shift_i) in enumerate(np.asarray(result.history), 1):
                w.writerow([i, cost_i, shift_i])

    if args.trace:
        from tdc_tpu.obs import trace as trace_lib

        rows = getattr(result, "timeline", None)
        if rows:
            print(trace_lib.format_timeline(rows, label=args.method_name))
        else:
            print("timeline: this fit path records no per-pass timeline "
                  "(streamed kmeans/fuzzy drivers only)", file=sys.stderr)
        tpath = trace_lib.flush()
        if tpath:
            print(f"trace written: {tpath}", file=sys.stderr)

    metrics = None
    if args.metrics:
        try:
            metrics = _score_clustering(args, x, result, n_obs,
                                        features=use_features)
        except Exception as e:  # scoring must not discard a completed fit
            print(f"note: metrics scoring failed ({type(e).__name__}: {e}); "
                  "fit result reported without metrics", file=sys.stderr)

    n_iter = int(result.n_iter)
    # Throughput from iterations THIS process executed (differs from n_iter
    # when resuming a checkpoint — a resume with nothing left to do reports 0,
    # not an inflated rate from timing a bare stats pass).
    n_iter_run = getattr(result, "n_iter_run", None)
    if n_iter_run is None:
        n_iter_run = n_iter
    comp = timers.get("computation")
    pps = (n_obs * int(n_iter_run) / comp / n_devices) if comp > 0 else float("inf")
    return {
        "method_name": args.method_name,
        "seed": args.seed,
        "num_GPUs": n_devices,
        "K": args.K,
        "n_obs": n_obs,
        "n_dim": n_dim,
        "setup_time": round(timers.get("setup"), 6),
        "initialization_time": round(timers.get("initialization"), 6),
        "computation_time": round(comp, 6),
        "n_iter": n_iter,
        "n_iter_run": int(n_iter_run),
        "backend": jax.devices()[0].platform,
        "n_chips": n_devices,
        "points_per_sec_per_chip": round(pps, 1),
        "sse": float(
            getattr(result, "sse",
                    getattr(result, "objective",
                            getattr(result, "log_likelihood", float("nan"))))
        ),
        "converged": bool(result.converged),
        "num_batches": num_batches,
        "tol": args.tol,
        # What was requested ('' = method default): with the explicit-kernel
        # fail-fast gates (validate_args + the model-level rejections), a
        # recorded 'pallas' row now really means the Pallas path ran.
        "kernel": ("tall" if use_features else (args.kernel or "")),
        "status": "ok",
        "_metrics": metrics,
    }


def _score_clustering(args, x, result, n_obs: int, *,
                      features: bool = False) -> dict:
    """Internal quality metrics on the fitted labels. Silhouette is O(N²), so
    it scores a seeded subsample (--metrics_sample, sklearn's sample_size
    approach); DB/CH score the same subsample for consistency. features=True
    means x is the feature-major (d, N) device array (--layout=features);
    the subsample comes back sample-major either way."""
    import jax.numpy as jnp

    from tdc_tpu.analysis.metrics import (
        calinski_harabasz_score,
        davies_bouldin_score,
        silhouette_score,
    )
    from tdc_tpu.models import kmeans_predict

    sample = args.metrics_sample
    if sample and n_obs > sample:
        idx = np.sort(
            np.random.default_rng(args.seed).choice(n_obs, sample,
                                                    replace=False)
        )
        # Device-resident x: gather on device, transfer only the sample.
        if features:
            xs = np.asarray(jnp.asarray(x)[:, jnp.asarray(idx)].T)
        elif isinstance(x, np.ndarray):
            xs = x[idx]
        else:
            xs = np.asarray(jnp.asarray(x)[jnp.asarray(idx)])
    else:
        xs = np.asarray(x).T if features else np.asarray(x)
    xs = xs.astype(np.float32)
    if args.spherical:
        # Score in the space the fit/predict operate in: cosine K-Means
        # assigns on L2-normalized points, so Euclidean metrics on raw norms
        # would mix metric spaces.
        xs = xs / np.maximum(
            np.linalg.norm(xs, axis=-1, keepdims=True), 1e-12
        )
    if args.method_name == "gaussianMixture":
        from tdc_tpu.models.gmm import gmm_predict

        labels = np.asarray(gmm_predict(xs, result))
    elif args.method_name == "distributedFuzzyCMeans":
        from tdc_tpu.models.fuzzy import fuzzy_predict

        labels = np.asarray(
            fuzzy_predict(xs, result.centroids, m=args.fuzzifier)
        )
    else:
        labels = np.asarray(
            kmeans_predict(xs, result.centroids, spherical=args.spherical)
        )
    out = {"n_scored": int(len(xs))}
    if len(np.unique(labels)) < 2:
        nan = float("nan")
        out.update(silhouette=nan, davies_bouldin=nan, calinski_harabasz=nan)
        return out
    out["silhouette"] = round(silhouette_score(xs, labels), 6)
    out["davies_bouldin"] = round(davies_bouldin_score(xs, labels), 6)
    out["calinski_harabasz"] = round(calinski_harabasz_score(xs, labels), 3)
    return out


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    validate_args(parser, args)

    from tdc_tpu.utils.logging import append_result_row, error_row
    from tdc_tpu.utils.structlog import RunLog

    runlog = RunLog(args.run_log)
    runlog.event("run_start", method=args.method_name, K=args.K,
                 n_obs=args.n_obs, n_dim=args.n_dim, seed=args.seed,
                 n_devices=args.n_devices)
    base = {
        "method_name": args.method_name,
        "seed": args.seed,
        "num_GPUs": args.n_devices or "",
        "n_chips": args.n_devices or "",
        "K": args.K,
        "n_obs": args.n_obs or "",
        "n_dim": args.n_dim or "",
        "num_batches": args.num_batches,
    }
    try:
        row = run_experiment(args)
    except Exception as e:  # reference :362-377: capture into the CSV, exit 1
        if args.log_file:
            append_result_row(args.log_file, error_row(base, e))
        runlog.event("run_error", error=type(e).__name__, message=str(e)[:500])
        print(f"FAILED: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    metrics = row.pop("_metrics", None)
    if args.log_file:
        append_result_row(args.log_file, row)
    runlog.event("run_ok", **{k: row[k] for k in
                              ("n_iter", "sse", "converged", "computation_time",
                               "points_per_sec_per_chip", "num_batches")})
    print(
        f"{row['method_name']}: n_iter={row['n_iter']} "
        f"sse={row['sse']:.6g} converged={row['converged']} "
        f"computation_time={row['computation_time']}s "
        f"({row['points_per_sec_per_chip']:.3g} pt·iter/s/chip)"
    )
    if metrics is not None:
        runlog.event("metrics", **metrics)
        print(
            f"metrics (n={metrics['n_scored']}): "
            f"silhouette={metrics['silhouette']:.4f} "
            f"davies_bouldin={metrics['davies_bouldin']:.4f} "
            f"calinski_harabasz={metrics['calinski_harabasz']:.4g}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
