"""Fleet CLI: N serve replicas behind the readiness-routing proxy.

    python -m tdc_tpu.cli.fleet \
        --model_root /ckpts/models --replicas 2 --port 8200 \
        --min_replicas 1 --max_replicas 4

Replicas are `python -m tdc_tpu.cli.serve` children sharing the SAME
--model/--model_root arguments (one manifest dir is the whole control
plane: publish a new generation there and every replica hot-reloads
it). The router answers on --host:--port; each replica gets its own
fresh localhost port. With --autoscale on (default) the governor-driven
autoscaler grows the fleet when replicas shed and drains one replica at
a time when the fleet is calm — scale-in rides the SIGTERM→drain→
exit-75 contract, so in-flight work always completes.

docs/OPERATIONS.md "Running a fleet" is the runbook.
"""

from __future__ import annotations

import argparse
import os
import shlex
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tdc_tpu.fleet",
        description="Replicated serving behind a readiness-routing proxy",
    )
    p.add_argument("--model", action="append", default=[],
                   metavar="ID=PATH",
                   help="model spec forwarded to every replica "
                        "(repeatable)")
    p.add_argument("--model_root", type=str, default=None,
                   help="model dir forwarded to every replica — the "
                        "fleet's shared control plane")
    p.add_argument("--host", type=str, default="127.0.0.1",
                   help="router bind host")
    p.add_argument("--port", type=int, default=8200,
                   help="router bind port")
    p.add_argument("--replicas", type=int, default=1,
                   help="initial replica count")
    p.add_argument("--balance", type=str, default="p2c",
                   choices=("p2c", "rr"),
                   help="router balancing: power-of-two-choices over "
                        "in-flight + recent queue p99 (default), or "
                        "plain round-robin")
    p.add_argument("--pool_max_idle", type=int, default=8,
                   help="keep-alive sockets pooled per replica "
                        "(0 = connection-per-request, the PR-16 "
                        "behaviour)")
    p.add_argument("--min_replicas", type=int, default=1)
    p.add_argument("--max_replicas", type=int, default=4)
    p.add_argument("--autoscale", type=str, default="on",
                   choices=("on", "off"),
                   help="'off' = fixed fleet (dead replicas are still "
                        "replaced by the controller poll via the "
                        "autoscaler's repair path only when 'on')")
    p.add_argument("--scale_eval_s", type=float, default=0.5,
                   help="autoscaler evaluation period")
    p.add_argument("--scale_up_shed_frac", type=float, default=0.5,
                   help="fraction of live replicas shedding that "
                        "triggers scale-out")
    p.add_argument("--scale_up_hold_s", type=float, default=0.5,
                   help="how long the shed signal must hold before "
                        "scale-out")
    p.add_argument("--scale_down_hold_s", type=float, default=3.0,
                   help="how long the fleet must be calm before "
                        "scale-in")
    p.add_argument("--scale_cooldown_s", type=float, default=3.0,
                   help="minimum spacing between scale decisions")
    p.add_argument("--scale_p99_wait_ms", type=float, default=0.0,
                   help="windowed p99 queue wait that also triggers "
                        "scale-out (0 disables)")
    p.add_argument("--scale_down_rps", type=float, default=0.0,
                   help="offered rps per replica below which scale-in "
                        "is allowed (0 = only the all-admitting gate)")
    p.add_argument("--scale_error_frac", type=float, default=0.5,
                   help="router-observed error fraction at which a "
                        "replica is replaced even though its /readyz "
                        "looks fine (0 disables)")
    p.add_argument("--scale_failover_rate", type=float, default=0.0,
                   help="router failovers/s that also triggers "
                        "scale-out (0 disables)")
    p.add_argument("--poll_interval", type=float, default=2.0,
                   help="replica hot-reload poll period (forwarded)")
    p.add_argument("--fleet_poll_s", type=float, default=0.25,
                   help="router readiness-probe period per replica")
    p.add_argument("--drain_linger", type=float, default=5.0,
                   help="replica drain linger (forwarded)")
    p.add_argument("--warmup_buckets", type=str, default="8,64,512",
                   help="replica warmup buckets (forwarded)")
    p.add_argument("--engine_budget", type=int, default=256,
                   help="replica compiled-engine LRU budget (forwarded)")
    p.add_argument("--service_ms", type=float, default=0.0,
                   help="replica synthetic per-batch service time "
                        "(forwarded; capacity testing)")
    p.add_argument("--backend", type=str, default=None,
                   help="replica jax platform override (forwarded)")
    p.add_argument("--replica_arg", action="append", default=[],
                   metavar="'--flag value'",
                   help="extra argument string passed verbatim to every "
                        "replica (repeatable, shell-split)")
    p.add_argument("--log_file", type=str, default=None,
                   help="fleet-level JSONL event log")
    return p


def replica_args_from(args) -> list[str]:
    """The argv tail every replica is spawned with."""
    out: list[str] = []
    for spec in args.model:
        out += ["--model", spec]
    if args.model_root:
        out += ["--model_root", args.model_root]
    if args.backend:
        out += ["--backend", args.backend]
    out += ["--poll_interval", str(args.poll_interval)]
    out += ["--drain_linger", str(args.drain_linger)]
    out += ["--warmup_buckets", args.warmup_buckets]
    out += ["--engine_budget", str(args.engine_budget)]
    if args.service_ms > 0:
        out += ["--service_ms", str(args.service_ms)]
    for extra in args.replica_arg:
        out += shlex.split(extra)
    return out


def make_fleet(args):
    """Build (fleet, router, autoscaler, log) from parsed args — the
    testable seam; nothing is started."""
    from tdc_tpu.fleet import (
        Autoscaler,
        AutoscalerConfig,
        FleetRouter,
        ServeFleet,
        subprocess_spawner,
    )
    from tdc_tpu.utils.structlog import RunLog

    log = RunLog(args.log_file)
    fleet = ServeFleet(
        subprocess_spawner(replica_args_from(args)),
        log=log,
        poll_interval=args.fleet_poll_s,
        drain_grace_s=max(30.0, args.drain_linger + 25.0),
    )
    router = FleetRouter(fleet, log=log, balance=args.balance,
                         pool_max_idle=args.pool_max_idle)
    autoscaler = Autoscaler(
        fleet,
        AutoscalerConfig(
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            eval_interval_s=args.scale_eval_s,
            up_hold_s=args.scale_up_hold_s,
            down_hold_s=args.scale_down_hold_s,
            cooldown_s=args.scale_cooldown_s,
            shed_frac_high=args.scale_up_shed_frac,
            p99_wait_high_ms=args.scale_p99_wait_ms,
            rps_per_replica_low=args.scale_down_rps,
            error_frac_high=args.scale_error_frac,
            failover_rate_high=args.scale_failover_rate,
            enabled=args.autoscale != "off",
        ),
        registry=router.registry,
        log=log,
        router=router,
    )
    return fleet, router, autoscaler, log


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not args.model and not args.model_root:
        parser.error("no models: pass --model ID=PATH or --model_root DIR")
    fleet, router, autoscaler, log = make_fleet(args)

    # SIGTERM: same discipline as the replica CLI (one raw fd-2 write —
    # the TDC004 signal-safety rule), then unwind serve_forever so the
    # drain runs outside the handler. stop_http() blocks until the serve
    # loop acknowledges the shutdown, and this handler runs ON the serve
    # loop's thread — hand it to a helper so the handler returns and the
    # loop can actually unwind (calling it inline self-deadlocks).
    # Installed BEFORE fleet.start: a SIGTERM landing in the startup
    # window must still drain the replicas already spawned instead of
    # killing the front door and orphaning them — `stopping` skips the
    # serve loop so the finally-drain runs straight away.
    import signal
    import threading
    import time as _time

    stopping = threading.Event()

    def _stop_router():
        # serve_http may be mid-bind when the signal lands: retry until
        # there is an httpd to stop (or the main thread saw `stopping`
        # and never started one — the deadline bounds that case).
        deadline = _time.monotonic() + 10.0
        while not router.stop_http() and _time.monotonic() < deadline:
            _time.sleep(0.05)

    def _term(signum, frame):
        try:
            os.write(2, b'{"event": "fleet_drain_begin"}\n')
        except OSError:
            pass
        stopping.set()
        threading.Thread(
            target=_stop_router, name="tdc-fleet-term", daemon=True,
        ).start()

    try:
        signal.signal(signal.SIGTERM, _term)
    except ValueError:  # non-main thread (embedded); no signal path
        pass

    fleet.start(args.replicas)
    try:
        if not fleet.wait_ready(1, timeout=120.0) and not stopping.is_set():
            print("fleet: no replica became ready within 120s", flush=True)
            fleet.stop(drain=False)
            return 1
        if args.autoscale != "off" and not stopping.is_set():
            autoscaler.start()

        counts = fleet.counts()
        print(f"fleet router on http://{args.host}:{args.port} "
              f"(replicas: {counts['ready']} ready / "
              f"{sum(counts.values())} total)", flush=True)
        if not stopping.is_set():
            router.serve_http(args.host, args.port)
    except KeyboardInterrupt:
        pass
    finally:
        autoscaler.stop()
        fleet.stop(drain=True)
        log.event("fleet_stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
