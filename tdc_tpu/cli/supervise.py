"""Elastic gang launcher CLI — supervised multi-process runs with
restart-from-checkpoint.

The reference launches each experiment as one unsupervised subprocess
(scripts/new_experiment.py:59); a crash loses the run. This launcher runs a
worker command as a gang of N `jax.distributed` processes, detects worker
loss (nonzero exit or heartbeat silence), and restarts the whole gang from
the latest checkpoint step common to all workers (see
parallel/supervisor.py for why the gang, not the worker, is the recovery
unit).

Usage:
    python -m tdc_tpu.cli.supervise --num_processes=2 --max_restarts=2 \\
        --ckpt_root=/tmp/ckpts --log_dir=/tmp/gang_logs \\
        -- python my_worker.py --flags...

Elastic resize (docs/OPERATIONS.md "Elastic resize"): write the desired
gang size into the resize request file (`--resize_file`, default
`<log_dir>/resize`) — the supervisor drains the gang at a checkpoint
boundary and relaunches it at the new size from the latest aligned
checkpoint, charging no restart budget; SIGHUP forces an immediate
re-read, and `$TDC_RESIZE` on the supervisor's environment overrides the
initial size. Requires --ckpt_root (a shared checkpoint dir): the
checkpoints are layout-portable (parallel/reshard.py), per-worker dirs
are not.

The worker should call `tdc_tpu.parallel.multihost.initialize_from_env()`
first, read its checkpoint directory from $TDC_CKPT_DIR, pass it as
`ckpt_dir=` to a streamed fit (models/streaming.py) so resume works, and call
`tdc_tpu.parallel.multihost.barrier()` before exiting (an unsynchronized exit
cancels peers mid-shutdown, which reads as a gang failure). Template:
examples/elastic_worker.py.
"""

from __future__ import annotations

import argparse
import os
import sys

from tdc_tpu.parallel.supervisor import (
    PREEMPTED_EXIT_CODE,
    GangFailed,
    GangPreempted,
    run_gang,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tdc_tpu.cli.supervise",
        description="Run a worker command as a supervised jax.distributed "
                    "gang with restart-from-checkpoint.",
    )
    p.add_argument("--num_processes", type=int, required=True)
    p.add_argument("--max_restarts", type=int, default=2,
                   help="budget of NON-progress failure restarts (a restart "
                        "whose checkpoint step advanced resets it; "
                        "preemption exits never charge it)")
    p.add_argument("--heartbeat_timeout", type=float, default=None,
                   help="seconds of worker heartbeat silence treated as a "
                        "hang (off by default; the clock starts at spawn, so "
                        "allow for compile time)")
    p.add_argument("--backoff_base", type=float, default=0.5,
                   help="base seconds of the exponential backoff between "
                        "failure relaunches (0 disables)")
    p.add_argument("--backoff_max", type=float, default=30.0,
                   help="backoff ceiling in seconds")
    p.add_argument("--drain_grace", type=float, default=30.0,
                   help="seconds workers get to checkpoint and exit after "
                        "a preemption SIGTERM before being killed")
    p.add_argument("--max_preemption_restarts", type=int, default=32,
                   help="hard cap on budget-free preemption relaunches")
    p.add_argument("--ckpt_root", type=str, default=None,
                   help="shared checkpoint dir exported to every worker as "
                        "$TDC_CKPT_DIR (process 0 is the single writer — "
                        "atomic state.npz per step — so the dir must be "
                        "shared); trimmed to the latest complete step "
                        "before every restart")
    p.add_argument("--log_dir", type=str, required=True,
                   help="per-attempt per-worker stdout+stderr capture")
    p.add_argument("--resize_file", type=str, default=None,
                   help="elastic-resize request file (one integer: the "
                        "desired gang size; default <log_dir>/resize). A "
                        "write drains the gang and relaunches it at the "
                        "new size from the latest checkpoint; SIGHUP "
                        "forces an immediate re-read")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="worker command (prefix with --)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        build_parser().error("no worker command given (append: -- cmd ...)")
    if args.num_processes < 1:
        build_parser().error("--num_processes must be >= 1")
    ckpt_dirs = None
    if args.ckpt_root is not None:
        os.makedirs(args.ckpt_root, exist_ok=True)
        ckpt_dirs = [args.ckpt_root]  # shared by the whole gang
    try:
        result = run_gang(
            cmd,
            args.num_processes,
            max_restarts=args.max_restarts,
            max_preemption_restarts=args.max_preemption_restarts,
            heartbeat_timeout=args.heartbeat_timeout,
            ckpt_dirs=ckpt_dirs,
            log_dir=args.log_dir,
            drain_grace=args.drain_grace,
            backoff_base=args.backoff_base,
            backoff_max=args.backoff_max,
            resize_request_path=args.resize_file,
        )
    except GangFailed as e:
        print(f"supervise: {e}", file=sys.stderr)
        return 1
    except GangPreempted as e:
        # Propagate the preemption contract: the scheduler that SIGTERMed
        # us sees the same retry-later code a drained worker uses.
        print(f"supervise: {e}", file=sys.stderr)
        return PREEMPTED_EXIT_CODE
    sizes = ""
    if result.resizes:
        sizes = (f", {result.resizes} resize(s): sizes "
                 + "->".join(str(s) for s in result.size_history))
    print(f"supervise: gang completed in {result.attempts} attempt(s) "
          f"({result.preemptions} preemption(s), restart budget used "
          f"{result.budget_used}{sizes}); logs: {args.log_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
