"""Serving CLI: load fitted models and answer predict traffic over HTTP.

    python -m tdc_tpu.cli.serve \
        --model km=/ckpts/kmeans_model --model gmm=/ckpts/gmm_model \
        --port 8100 --log_file serve_log.jsonl

Models are fitted-model dirs (models/persist.save_fitted) or raw
utils/checkpoint.py checkpoint dirs; each is polled for hot-reload every
--poll_interval seconds. With --shard_model > 1 the engine builds a 2-D
(data × model) mesh and routes hard assignment for models with
K ≥ --shard_k_threshold through parallel.sharded_k.sharded_assign.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tdc_tpu.serve",
        description="Online inference serving for fitted clustering models",
    )
    p.add_argument("--model", action="append", default=[],
                   metavar="ID=PATH",
                   help="register model ID from a fitted-model or "
                        "checkpoint dir (repeatable)")
    p.add_argument("--model_root", type=str, default=None,
                   help="register every immediate subdirectory of this "
                        "dir as a model (id = subdir name)")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--backend", type=str, default=None,
                   help="jax platform override (tpu|cpu); default auto")
    p.add_argument("--n_devices", type=int, default=None,
                   help="devices for the serving mesh (default all)")
    p.add_argument("--shard_model", type=int, default=1,
                   help="model-axis size of the 2-D serving mesh; >1 "
                        "enables the sharded_assign route for large-K "
                        "models")
    p.add_argument("--shard_k_threshold", type=int, default=8192,
                   help="K at or above which hard assignment routes "
                        "through sharded_assign (needs --shard_model>1)")
    p.add_argument("--max_batch_rows", type=int, default=4096,
                   help="device micro-batch row cap")
    p.add_argument("--max_wait_ms", type=float, default=2.0,
                   help="micro-batch coalescing deadline")
    p.add_argument("--max_queue_rows", type=int, default=65536,
                   help="queued-rows bound; beyond it requests are "
                        "rejected as overloaded (HTTP 503)")
    p.add_argument("--poll_interval", type=float, default=2.0,
                   help="hot-reload manifest poll period in seconds "
                        "(0 disables)")
    # Admission governor (serve/governor.py): readiness-based shedding
    # from measured signals, BEFORE work is queued. docs/OPERATIONS.md
    # "Overload triage" is the tuning runbook.
    p.add_argument("--shed", type=str, default="on",
                   choices=("on", "off"),
                   help="admission governor: 'off' = admit everything "
                        "and let queue backpressure be the only limit "
                        "(A/B the ungoverned overload behavior)")
    p.add_argument("--shed_queue_high", type=float, default=0.75,
                   help="queued-rows fraction of --max_queue_rows that "
                        "ENTERS shedding")
    p.add_argument("--shed_queue_low", type=float, default=0.35,
                   help="queued-rows fraction that (with the other "
                        "signals) EXITS shedding — hysteresis")
    p.add_argument("--shed_p99_wait_ms", type=float, default=500.0,
                   help="recent p99 queue wait (scrape-derived, off the "
                        "tdc_serve_queue_wait_ms buckets) that enters "
                        "shedding; 0 disables the signal")
    p.add_argument("--shed_inflight_high", type=int, default=0,
                   help="in-flight request count that enters shedding; "
                        "0 disables the signal")
    p.add_argument("--shed_min_hold_s", type=float, default=1.0,
                   help="minimum shed duration before recovery is "
                        "considered (flap damping)")
    p.add_argument("--shed_retry_after_s", type=float, default=1.0,
                   help="Retry-After advertised on shed 503s")
    p.add_argument("--shed_fair_frac", type=float, default=0.5,
                   help="per-model fair share of --max_queue_rows "
                        "(x 1/models) still admitted mid-shed, so one "
                        "flooded tenant cannot starve the rest")
    p.add_argument("--engine_budget", type=int, default=256,
                   help="compiled-engine LRU budget: how many (model, "
                        "generation) predict engines stay resident; an "
                        "evicted model re-admits on its next request "
                        "with no jit re-trace")
    p.add_argument("--service_ms", type=float, default=0.0,
                   help="add this many ms of synthetic per-batch service "
                        "time after each engine run — a capacity-testing "
                        "knob (fleet smoke/bench) that makes saturation "
                        "cheap to reach; 0 (default) = off")
    p.add_argument("--warmup_buckets", type=str, default="8,64,512",
                   help="comma-separated row buckets to pre-compile per "
                        "model ('' skips warmup)")
    p.add_argument("--log_file", type=str, default=None,
                   help="request-level JSONL event log "
                        "(utils/structlog.RunLog)")
    p.add_argument("--drain_linger", type=float, default=5.0,
                   help="on SIGTERM: seconds the HTTP listener keeps "
                        "answering (503 for new work, 200 liveness) "
                        "before closing — the LB deregistration window")
    p.add_argument("--compile_cache_dir", type=str,
                   default=os.environ.get("TDC_COMPILE_CACHE", ""),
                   help="persistent XLA compilation cache ('' disables; "
                        "default $TDC_COMPILE_CACHE) — a restarted server "
                        "deserializes its warmup/predict executables "
                        "instead of recompiling (utils/compile_cache)")
    # Online updates (serve/online): fold sampled traffic back into a
    # registered kmeans model through the guarded screen -> shadow-validate
    # -> atomic-swap -> auto-rollback pipeline.
    p.add_argument("--online", type=str, default=None, metavar="ID",
                   help="run the in-process online updater for this "
                        "registered model (kmeans fitted-model dirs only)")
    p.add_argument("--online_tick", type=float, default=5.0,
                   help="seconds between online fold/validate ticks")
    p.add_argument("--feed_dir", type=str, default=None,
                   help="export every --feed_sample'th dispatched device "
                        "batch under <feed_dir>/<model_id>/ for a "
                        "tdc_tpu.cli.online sidecar (point its --feed_dir "
                        "at the per-model subdirectory)")
    p.add_argument("--feed_sample", type=int, default=1,
                   help="feed-dir sampling stride (1 = every batch)")
    from tdc_tpu.cli.online import add_config_flags

    add_config_flags(p, prefix="online_")
    return p


def _parse_models(args, parser) -> list[tuple[str, str]]:
    pairs = []
    for spec in args.model:
        mid, sep, path = spec.partition("=")
        if not sep or not mid or not path:
            parser.error(f"--model must be ID=PATH, got {spec!r}")
        pairs.append((mid, path))
    if args.model_root:
        for name in sorted(os.listdir(args.model_root)):
            path = os.path.join(args.model_root, name)
            if os.path.isdir(path):
                pairs.append((name, path))
    if not pairs:
        parser.error("no models: pass --model ID=PATH or --model_root DIR")
    return pairs


def make_app(args):
    """Build a started ServeApp from parsed args (the testable seam)."""
    if args.backend:
        import jax

        jax.config.update("jax_platforms", args.backend)
    import jax

    if hasattr(args, "compile_cache_dir"):
        # '' (the no-env default and the explicit opt-out) still calls in:
        # recording the choice keeps a later enable_from_env() from
        # re-enabling over it (utils/compile_cache).
        from tdc_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache(args.compile_cache_dir)

    from tdc_tpu.serve import (
        GovernorConfig,
        ModelRegistry,
        PredictEngine,
        ServeApp,
    )
    from tdc_tpu.utils.structlog import RunLog

    log = RunLog(args.log_file)
    mesh = None
    if args.shard_model > 1:
        from tdc_tpu.parallel.sharded_k import make_mesh_2d

        n = args.n_devices or len(jax.devices())
        if n % args.shard_model != 0:
            raise SystemExit(
                f"--shard_model={args.shard_model} does not divide "
                f"{n} devices"
            )
        mesh = make_mesh_2d(n // args.shard_model, args.shard_model)
    registry = ModelRegistry()
    engine = PredictEngine(
        mesh,
        shard_k_threshold=args.shard_k_threshold,
        engine_budget=getattr(args, "engine_budget", 256),
        log=log,
    )
    service_ms = float(getattr(args, "service_ms", 0.0) or 0.0)
    if service_ms > 0:
        # Capacity-testing knob: stretch every device batch by a fixed
        # synthetic service time so fleet smokes/benches reach saturation
        # at CI-friendly request rates. Instance-attribute wrap — the
        # engine class (and its jit caches) are untouched.
        import time as _time

        inner = engine.run

        def _slow_run(entry, method, x, _inner=inner, _ms=service_ms):
            out = _inner(entry, method, x)
            _time.sleep(_ms / 1e3)
            return out

        engine.run = _slow_run
    app = ServeApp(
        registry,
        engine,
        log=log,
        max_batch_rows=args.max_batch_rows,
        max_wait_ms=args.max_wait_ms,
        max_queue_rows=args.max_queue_rows,
        poll_interval=args.poll_interval,
        feed_dir=getattr(args, "feed_dir", None),
        feed_sample=getattr(args, "feed_sample", 1),
        governor_config=GovernorConfig(
            enabled=args.shed != "off",
            queue_high_frac=args.shed_queue_high,
            queue_low_frac=args.shed_queue_low,
            p99_wait_high_ms=args.shed_p99_wait_ms,
            inflight_high=args.shed_inflight_high,
            min_shed_s=args.shed_min_hold_s,
            retry_after_s=args.shed_retry_after_s,
            fair_frac=args.shed_fair_frac,
        ),
    )
    return app, log


def _attach_online(app, args, pairs, log) -> None:
    """--online=ID: build the in-process updater for a registered model.
    Loud CLI-vocabulary failures: a typo'd id or a fuzzy/gmm model must
    not silently serve without the promised update loop."""
    from tdc_tpu.cli.online import config_from
    from tdc_tpu.serve.online import OnlineUpdater

    paths = dict(pairs)
    if args.online not in paths:
        raise SystemExit(
            f"--online={args.online!r} is not a registered model id "
            f"(have {sorted(paths)})"
        )
    try:
        updater = OnlineUpdater(
            paths[args.online],
            model_id=args.online,
            registry=app.registry,
            config=config_from(
                args, prefix="online_", tick_interval=args.online_tick
            ),
            log=log,
        )
    except ValueError as e:
        raise SystemExit(f"--online: {e}") from None
    app.attach_online(args.online, updater)
    print(f"online updates on {args.online}: mode={updater.config.mode} "
          f"live={updater.live_version} "
          f"(pinned={updater.status()['pinned']})", flush=True)


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    pairs = _parse_models(args, parser)
    app, log = make_app(args)
    for mid, path in pairs:
        entry = app.registry.add(mid, path, log=log)
        print(f"loaded {mid}: {entry.fitted.model} K={entry.fitted.k} "
              f"d={entry.fitted.d} version={entry.version}", flush=True)
    if args.online:
        _attach_online(app, args, pairs, log)
    buckets = [int(b) for b in args.warmup_buckets.split(",") if b]
    if buckets:  # '' really does skip warmup (engine.warmup defaults [])
        for mid, _ in pairs:
            entry = app.registry.get(mid)
            compiles = app.engine.warmup(entry, buckets=buckets)
            print(
                f"warmed {mid}: {compiles} compiles over buckets {buckets}",
                flush=True,
            )
    app.start()

    # SIGTERM (preemption / rolling restart): flip /readyz and reject new
    # work IMMEDIATELY while the listener keeps answering for the LB
    # deregistration window (begin_drain) — raising out of serve_forever
    # right away would close the socket first and turn the promised 503s
    # into connection-refused. serve_forever unwinds when begin_drain's
    # linger expires; app.stop() then flushes in-flight batches and closes.
    import signal

    drained = []  # non-empty once the SIGTERM drain path ran

    def _drain(signum, frame):
        # Async-signal context: print/emit into a buffered stderr the
        # signal may have interrupted raises RuntimeError('reentrant
        # call') inside the handler (the utils/preempt._on_signal rule,
        # TDC004). One raw fd-2 write is the whole breadcrumb; the drain
        # machinery logs properly once it runs outside the handler.
        try:
            os.write(2, b'{"event": "serve_drain_begin", '
                        b'"linger_s": %d}\n' % int(args.drain_linger))
        except OSError:
            pass
        drained.append(True)
        app.begin_drain(linger=args.drain_linger)

    try:
        signal.signal(signal.SIGTERM, _drain)
    except ValueError:  # non-main thread (embedded); no signal path
        pass

    print(f"serving on http://{args.host}:{args.port} "
          f"(models: {', '.join(app.registry.ids())})", flush=True)
    try:
        app.serve_http(args.host, args.port)
    except KeyboardInterrupt:
        pass
    finally:
        app.stop()
    if drained:
        # The supervisor/fleet preemption contract (utils/preempt): a
        # SIGTERM'd replica that drained cleanly exits 75, so the party
        # that sent the signal can tell "drained as asked" from "died".
        from tdc_tpu.utils.preempt import PREEMPTED_EXIT_CODE

        return PREEMPTED_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
