"""Online-update sidecar + admin CLI (serve/online.py).

Sidecar mode — fold sampled traffic from a feed directory into a served
model dir, through the guarded validate/publish/rollback pipeline; the
serving process (cli/serve) picks each publish up via its normal
hot-reload poll of the same dir:

    python -m tdc_tpu.cli.online --model_dir /models/km \\
        --feed_dir /models/km_feed --interval 2.0

Admin verbs — drive the ledger in the model dir directly (works whether
the updater is a sidecar or in-process, but do NOT run a verb while a
sidecar is mid-tick on the same dir: one writer at a time):

    python -m tdc_tpu.cli.online --model_dir /models/km --rollback
    python -m tdc_tpu.cli.online --model_dir /models/km --pin
    python -m tdc_tpu.cli.online --model_dir /models/km --status

The sidecar honors the PR-3 preemption contract: SIGTERM finishes the
current tick (state is atomically persisted every event) and exits 75,
so a supervisor relaunch is budget-free and resumes from the ledger.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def add_config_flags(p: argparse.ArgumentParser, prefix: str = "") -> None:
    """The OnlineConfig knobs, shared between this CLI (bare names) and
    cli/serve (prefix='online_') so the two surfaces cannot drift."""
    from tdc_tpu.serve.online import OnlineConfig

    dflt = OnlineConfig()
    p.add_argument(f"--{prefix}mode", type=str, default=dflt.mode,
                   choices=("minibatch", "streaming"),
                   help="fold rule: Sculley per-center rates, or decayed "
                        "sufficient-stats (models/streaming.streaming_fold)")
    p.add_argument(f"--{prefix}decay", type=float, default=dflt.decay,
                   help="streaming-mode forgetting per fold (1.0 = none)")
    p.add_argument(f"--{prefix}prior_count", type=float,
                   default=dflt.prior_count,
                   help="pseudo-points seeding each center's fold mass")
    p.add_argument(f"--{prefix}min_fold_rows", type=int,
                   default=dflt.min_fold_rows,
                   help="pending rows before a fold is attempted")
    p.add_argument(f"--{prefix}holdback_rows", type=int,
                   default=dflt.holdback_rows,
                   help="sliding shadow-validation window size")
    p.add_argument(f"--{prefix}min_holdback_rows", type=int,
                   default=dflt.min_holdback_rows,
                   help="validation evidence floor before any publish")
    p.add_argument(f"--{prefix}max_inertia_ratio", type=float,
                   default=dflt.max_inertia_ratio,
                   help="candidate/live holdback-inertia publish ceiling")
    p.add_argument(f"--{prefix}max_churn", type=float,
                   default=dflt.max_churn,
                   help="candidate vs live assignment-churn ceiling")
    p.add_argument(f"--{prefix}min_entropy_ratio", type=float,
                   default=dflt.min_entropy_ratio,
                   help="candidate/live cluster-size entropy floor")
    p.add_argument(f"--{prefix}rollback_ratio", type=float,
                   default=dflt.rollback_inertia_ratio,
                   help="live/last-good inertia auto-rollback trigger")
    p.add_argument(f"--{prefix}keep", type=int,
                   default=dflt.keep_generations,
                   help="generations retained (live+last-good pinned)")
    p.add_argument(f"--{prefix}seed", type=int, default=dflt.seed,
                   help="holdback-sampling PRNG seed")


def config_from(args, prefix: str = "", **overrides):
    from tdc_tpu.serve.online import OnlineConfig

    def g(name):
        return getattr(args, prefix + name)

    return OnlineConfig(
        mode=g("mode"),
        decay=g("decay"),
        prior_count=g("prior_count"),
        min_fold_rows=g("min_fold_rows"),
        holdback_rows=g("holdback_rows"),
        min_holdback_rows=g("min_holdback_rows"),
        max_inertia_ratio=g("max_inertia_ratio"),
        max_churn=g("max_churn"),
        min_entropy_ratio=g("min_entropy_ratio"),
        rollback_inertia_ratio=g("rollback_ratio"),
        keep_generations=g("keep"),
        seed=g("seed"),
        **overrides,
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tdc_tpu.online",
        description="Online-update sidecar / admin for a served model dir",
    )
    p.add_argument("--model_dir", type=str, required=True,
                   help="save_fitted model dir (the one cli/serve polls)")
    p.add_argument("--feed_dir", type=str, default=None,
                   help="directory a server exports sampled traffic "
                        "batches into (cli/serve --feed_dir)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between sidecar ticks")
    p.add_argument("--max_ticks", type=int, default=0,
                   help="exit 0 after this many ticks (0 = run forever)")
    p.add_argument("--log_file", type=str, default=None,
                   help="JSONL event log (utils/structlog.RunLog)")
    verbs = p.add_mutually_exclusive_group()
    verbs.add_argument("--rollback", action="store_true",
                       help="republish the last-good generation and exit")
    verbs.add_argument("--pin", action="store_true",
                       help="freeze publishes/auto-rollback and exit")
    verbs.add_argument("--unpin", action="store_true",
                       help="resume publishes/auto-rollback and exit")
    verbs.add_argument("--status", action="store_true",
                       help="print the ledger status as JSON and exit")
    add_config_flags(p)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    from tdc_tpu.serve.online import OnlineUpdater, feed_drain
    from tdc_tpu.utils.structlog import RunLog

    # No --log_file: leave log unset so updater events route through
    # structlog.emit (stderr + $TDC_RUNLOG) instead of a no-op RunLog —
    # a sidecar's recovery story must be greppable somewhere by default.
    log = RunLog(args.log_file) if args.log_file else None
    try:
        updater = OnlineUpdater(
            args.model_dir, config=config_from(args), log=log,
        )
    except (ValueError, FileNotFoundError) as e:
        raise SystemExit(f"tdc_tpu.online: {e}") from None

    if args.status:
        print(json.dumps(updater.status(), indent=1, sort_keys=True))
        return 0
    if args.rollback:
        try:
            version = updater.rollback(reason="admin_cli")
        except ValueError as e:
            raise SystemExit(f"tdc_tpu.online: {e}") from None
        print(f"rolled back to {version}", flush=True)
        return 0
    if args.pin or args.unpin:
        updater.pin() if args.pin else updater.unpin()
        print(f"pinned={updater.status()['pinned']}", flush=True)
        return 0

    if args.feed_dir is None:
        parser.error("sidecar mode needs --feed_dir (or pass an admin "
                     "verb: --rollback/--pin/--unpin/--status)")

    from tdc_tpu.utils import preempt
    from tdc_tpu.utils.preempt import Preempted, install_preemption_handler

    install_preemption_handler()  # SIGTERM -> finish the tick, exit 75
    print(f"online sidecar on {args.model_dir} "
          f"(feed {args.feed_dir}, live {updater.live_version})", flush=True)
    ticks = 0
    while True:
        feed_drain(args.feed_dir, updater)
        updater.tick()
        ticks += 1
        if args.max_ticks and ticks >= args.max_ticks:
            return 0
        if preempt.requested():
            # Everything is already persisted (ledger + fold state are
            # atomic-replace per event): drain is just a clean exit 75.
            raise Preempted()
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
