"""Experiment harness: CLI, sweep runner, results parsing."""
