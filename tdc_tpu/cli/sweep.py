"""Sweep runner — the reference's experiment matrix driver, config-as-data.

Reference: scripts/new_experiment.py:30-64 (and generate-logs.py): nested
hard-coded loops over n_obs x K x n_GPUs x method, each config run as a
subprocess under nvprof for crash isolation, results appended to one CSV.
Here the matrix is a JSON spec, isolation is still per-config subprocess, and
profiling is jax.profiler traces via --profile_dir.

Spec format (JSON):
{
  "data": {"n_obs": [1000000], "n_dim": [8], "seed": 123128},
  "grid": {"K": [3, 9, 15], "n_devices": [1], "method_name": ["distributedKMeans"]},
  "fixed": {"n_max_iters": 20, "tol": -1.0},
  "log_file": "executions_log.csv"
}
"""

from __future__ import annotations

import argparse
import itertools
import json
import subprocess
import sys


def expand_grid(spec: dict) -> list[dict]:
    """Cartesian product of data x grid axes, reference loop-nest order
    (n_obs outermost, then grid keys in declaration order)."""
    data = spec.get("data", {})
    grid = dict(spec.get("grid", {}))
    fixed = spec.get("fixed", {})
    axes = {"n_obs": data.get("n_obs", [None]), "n_dim": data.get("n_dim", [None])}
    axes.update(grid)
    configs = []
    for combo in itertools.product(*axes.values()):
        cfg = dict(zip(axes.keys(), combo))
        cfg.update(fixed)
        if "seed" in data:
            cfg.setdefault("seed", data["seed"])
        configs.append({k: v for k, v in cfg.items() if v is not None})
    return configs


def config_argv(cfg: dict, log_file: str | None) -> list[str]:
    argv = [sys.executable, "-m", "tdc_tpu.cli.main"]
    rename = {"n_devices": "n_GPUs"}
    for k, v in cfg.items():
        flag = rename.get(k, k)
        if isinstance(v, bool):
            if v:
                argv.append(f"--{flag}")
        else:
            argv.append(f"--{flag}={v}")
    if log_file:
        argv.append(f"--log_file={log_file}")
    return argv


_RESUME_KEYS = ("method_name", "seed", "K", "n_obs", "n_dim")


def completed_configs(log_file: str | None) -> set[tuple]:
    """Configs already logged with status ok — sweep resume works by diffing
    the CSV against the config matrix (SURVEY.md §5 checkpoint/resume row)."""
    import csv
    import os

    done = set()
    if not log_file or not os.path.exists(log_file):
        return done
    with open(log_file) as f:
        for row in csv.DictReader(f):
            if row.get("status") == "ok":
                done.add(tuple(str(row.get(k, "")) for k in _RESUME_KEYS))
    return done


def _config_key(cfg: dict) -> tuple:
    defaults = {"method_name": "distributedKMeans", "seed": 123128}
    return tuple(str(cfg.get(k, defaults.get(k, ""))) for k in _RESUME_KEYS)


def run_sweep(
    spec: dict, *, dry_run: bool = False, isolate: bool = True, resume: bool = False
) -> list[int]:
    """Run every config; per-config subprocess isolation (reference :59) so a
    hard crash can't kill the sweep. Returns per-config exit codes.
    resume=True skips configs already logged ok in the spec's log_file."""
    log_file = spec.get("log_file")
    codes = []
    configs = expand_grid(spec)
    if resume:
        done = completed_configs(log_file)
        skipped = [c for c in configs if _config_key(c) in done]
        configs = [c for c in configs if _config_key(c) not in done]
        if skipped:
            print(f"resume: skipping {len(skipped)} completed configs")
    for i, cfg in enumerate(configs):
        argv = config_argv(cfg, log_file)
        print(f"[{i + 1}/{len(configs)}] {' '.join(argv[2:])}", flush=True)
        if dry_run:
            codes.append(0)
            continue
        if isolate:
            proc = subprocess.run(argv)
            codes.append(proc.returncode)
            print(f"  -> exit {proc.returncode}", flush=True)
        else:
            from tdc_tpu.cli.main import main as run_main
            codes.append(run_main(argv[3:]))
    return codes


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tdc_tpu.sweep")
    p.add_argument("spec", help="JSON sweep spec path, or '-' for stdin")
    p.add_argument("--dry_run", action="store_true")
    p.add_argument("--no_isolate", action="store_true",
                   help="run in-process (faster, no crash isolation)")
    p.add_argument("--resume", action="store_true",
                   help="skip configs already logged ok in the log_file")
    args = p.parse_args(argv)
    spec = json.load(sys.stdin if args.spec == "-" else open(args.spec))
    codes = run_sweep(
        spec, dry_run=args.dry_run, isolate=not args.no_isolate, resume=args.resume
    )
    failed = sum(1 for c in codes if c != 0)
    print(f"sweep done: {len(codes) - failed}/{len(codes)} ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
