"""Sweep runner — the reference's experiment matrix driver, config-as-data.

Reference: scripts/new_experiment.py:30-64 (and generate-logs.py): nested
hard-coded loops over n_obs x K x n_GPUs x method, each config run as a
subprocess under nvprof for crash isolation, results appended to one CSV.
Here the matrix is a JSON spec, isolation is still per-config subprocess, and
profiling is jax.profiler traces via --profile_dir.

Spec format (JSON):
{
  "data": {"n_obs": [1000000], "n_dim": [8], "seed": 123128},
  "grid": {"K": [3, 9, 15], "n_devices": [1], "method_name": ["distributedKMeans"]},
  "fixed": {"n_max_iters": 20, "tol": -1.0},
  "log_file": "executions_log.csv"
}
"""

from __future__ import annotations

import argparse
import itertools
import json
import subprocess
import sys


def expand_grid(spec: dict) -> list[dict]:
    """Cartesian product of data x grid axes, reference loop-nest order
    (n_obs outermost, then grid keys in declaration order)."""
    data = spec.get("data", {})
    grid = dict(spec.get("grid", {}))
    fixed = spec.get("fixed", {})
    axes = {"n_obs": data.get("n_obs", [None]), "n_dim": data.get("n_dim", [None])}
    axes.update(grid)
    configs = []
    for combo in itertools.product(*axes.values()):
        cfg = dict(zip(axes.keys(), combo))
        cfg.update(fixed)
        if "seed" in data:
            cfg.setdefault("seed", data["seed"])
        configs.append({k: v for k, v in cfg.items() if v is not None})
    return configs


def config_argv(cfg: dict, log_file: str | None) -> list[str]:
    argv = [sys.executable, "-m", "tdc_tpu.cli.main"]
    rename = {"n_devices": "n_GPUs"}
    for k, v in cfg.items():
        flag = rename.get(k, k)
        if isinstance(v, bool):
            if v:
                argv.append(f"--{flag}")
        else:
            argv.append(f"--{flag}={v}")
    if log_file:
        argv.append(f"--log_file={log_file}")
    return argv


# CSV-fallback resume keys (legacy logs only; new runs use the sidecar hash
# file, which covers every axis). num_GPUs is compared separately because a
# config that doesn't pin n_devices can't be matched against the CSV's
# recorded actual device count.
_RESUME_KEYS = ("method_name", "seed", "K", "n_obs", "n_dim")


def _config_hash(cfg: dict) -> str:
    """Stable hash over the FULL config — every grid axis participates, so a
    sweep varying tol/n_devices/anything resumes correctly."""
    import hashlib
    import json as _json

    blob = _json.dumps(cfg, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _done_file(log_file: str) -> str:
    return log_file + ".sweep_done"


def completed_configs(log_file: str | None) -> set[str]:
    """Full-config hashes already completed, from the sidecar done-file.

    A done-file whose log CSV has been deleted is stale — the user's "delete
    the log to redo the sweep clean" gesture must reset resume state too, so
    the sidecar is discarded (with a notice) rather than silently honored.
    """
    import os
    import sys

    done = set()
    if not log_file or not os.path.exists(_done_file(log_file)):
        return done
    if not os.path.exists(log_file):
        print(
            f"note: {log_file} is gone; removing stale {_done_file(log_file)} "
            "and restarting the sweep from scratch",
            file=sys.stderr,
        )
        os.remove(_done_file(log_file))
        return done
    with open(_done_file(log_file)) as f:
        for line in f:
            if line.strip():
                done.add(line.strip())
    return done


def completed_csv_keys(log_file: str | None) -> set[tuple]:
    """Legacy fallback: configs logged ok in the CSV as (key5, num_GPUs)
    pairs. Coarser than the hash — only consulted when no done-file exists."""
    import csv
    import os

    done = set()
    if not log_file or not os.path.exists(log_file):
        return done
    with open(log_file) as f:
        for row in csv.DictReader(f):
            if row.get("status") == "ok":
                key5 = tuple(str(row.get(k, "")) for k in _RESUME_KEYS)
                done.add((key5, str(row.get("num_GPUs", ""))))
    return done


def _config_key(cfg: dict) -> tuple:
    defaults = {"method_name": "distributedKMeans", "seed": 123128}
    return tuple(str(cfg.get(k, defaults.get(k, ""))) for k in _RESUME_KEYS)


def _covered_by_csv(cfg: dict, csv_done: set[tuple]) -> bool:
    """True if a legacy CSV row covers this config. num_GPUs participates only
    when the config pins n_devices (otherwise the CSV records the run's actual
    device count, which the config can't predict)."""
    key5 = _config_key(cfg)
    if "n_devices" in cfg:
        return (key5, str(cfg["n_devices"])) in csv_done
    return any(k == key5 for k, _ in csv_done)


def _mark_done(log_file: str | None, cfg: dict) -> None:
    if not log_file:
        return
    with open(_done_file(log_file), "a") as f:
        f.write(_config_hash(cfg) + "\n")


def run_sweep(
    spec: dict,
    *,
    dry_run: bool = False,
    isolate: bool = True,
    resume: bool = False,
    resume_legacy_csv: bool = False,
) -> list[int]:
    """Run every config; per-config subprocess isolation (reference :59) so a
    hard crash can't kill the sweep. Returns per-config exit codes.

    resume=True skips configs whose full-config hash is in the sidecar
    done-file (written per completed config; covers every grid axis).
    resume_legacy_csv=True additionally lets pre-done-file logs skip configs
    via coarse CSV matching — explicitly opt-in because the CSV records only
    method/seed/K/n_obs/n_dim/num_GPUs: a legacy row CANNOT distinguish
    configs that differ on tol/init/n_max_iters/... (round-1 advisor bug
    class). Safe default: hash-only, worst case a re-run.
    """
    log_file = spec.get("log_file")
    codes = []
    configs = expand_grid(spec)
    if resume:
        done = completed_configs(log_file)
        keep = [c for c in configs if _config_hash(c) not in done]
        if resume_legacy_csv and not done:
            # Opt-in coarse fallback for pre-done-file logs. Matched
            # completions are migrated into the done-file so later resumes
            # (hash branch) keep them. A config whose 5-key collides with
            # another in THIS grid is never covered (known-ambiguous even
            # within the grid).
            from collections import Counter

            key_counts = Counter(_config_key(c) for c in configs)
            csv_done = completed_csv_keys(log_file)
            still = []
            for c in keep:
                if key_counts[_config_key(c)] == 1 and _covered_by_csv(c, csv_done):
                    if not dry_run:  # a dry run must not mutate on-disk state
                        _mark_done(log_file, c)
                else:
                    still.append(c)
            keep = still
        if len(keep) < len(configs):
            print(f"resume: skipping {len(configs) - len(keep)} completed configs")
        configs = keep
    for i, cfg in enumerate(configs):
        argv = config_argv(cfg, log_file)
        print(f"[{i + 1}/{len(configs)}] {' '.join(argv[2:])}", flush=True)
        if dry_run:
            codes.append(0)
            continue
        if isolate:
            proc = subprocess.run(argv)
            code = proc.returncode
            print(f"  -> exit {code}", flush=True)
        else:
            from tdc_tpu.cli.main import main as run_main
            code = run_main(argv[3:])
        codes.append(code)
        if code == 0:
            _mark_done(log_file, cfg)
    return codes


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tdc_tpu.sweep")
    p.add_argument("spec", help="JSON sweep spec path, or '-' for stdin")
    p.add_argument("--dry_run", action="store_true")
    p.add_argument("--no_isolate", action="store_true",
                   help="run in-process (faster, no crash isolation)")
    p.add_argument("--resume", action="store_true",
                   help="skip configs already completed (full-config hash "
                        "recorded in <log_file>.sweep_done)")
    p.add_argument("--resume_legacy_csv", action="store_true",
                   help="with --resume on a pre-done-file log: also skip via "
                        "coarse CSV matching (cannot distinguish configs "
                        "differing only on axes the CSV doesn't record)")
    args = p.parse_args(argv)
    spec = json.load(sys.stdin if args.spec == "-" else open(args.spec))
    codes = run_sweep(
        spec, dry_run=args.dry_run, isolate=not args.no_isolate,
        resume=args.resume, resume_legacy_csv=args.resume_legacy_csv,
    )
    failed = sum(1 for c in codes if c != 0)
    print(f"sweep done: {len(codes) - failed}/{len(codes)} ok")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
