"""Compile-time IR checking — the layer past source AST.

TDC001 catches `if process_index: psum(...)` lexically; this module
catches the same divergence class where it actually becomes binding: in
the traced program. It walks a function's jaxpr and extracts the ordered
sequence of collective primitives (psum / all_gather / ppermute / ...),
then asserts two SPMD invariants:

1. **Branch uniformity** — under SPMD, one program runs on every shard,
   so shards can only execute different collective sequences through
   value-dependent control flow: `lax.cond`/`lax.switch` branches that
   emit different collectives (asserted identical here), or a
   `lax.while_loop` whose trip count varies per shard (undecidable
   statically — such collectives are surfaced in
   TraceReport.while_collectives and can be hard-rejected with
   forbid_while_collectives=True). With uniform branches and no
   while-body collectives, the emitted sequence is identical across
   shards by construction — the static companion to test_reduce's
   compiled-HLO no-collective proof.
2. **Trace stability** — tracing twice yields the same sequence. A trace
   that consults ambient state (a global counter, dict ordering, an RNG)
   can emit different reduction orders per compile; with per-process jit
   caches that means two processes that compiled at different times run
   different programs — the quantized-reduce towers (int8 pmax + psum
   pairs) fail *numerically*, not loudly, when that happens.

On top of the collective walk (formerly lint/jaxpr_check, which now
re-exports from here) this module adds the other three IR audits the
verify CLI drives:

- `transfer_ops` — host-transfer/callback primitives reachable from a
  traced program (the static generalization of the resident drivers'
  runtime `jax.transfer_guard("disallow")`);
- `donation_report` — `tf.aliasing_output` attributes in the lowered
  StableHLO, the compiled-artifact truth of `donate_argnums` (a
  shape/dtype mismatch silently drops the alias and the "donated"
  buffer is copied every step);
- `recompile_report` — jit-cache identity across two perturbed but
  static-compatible calls (the semantic form of TDC003).

Uses jax — imported by tests and explicit callers only, never by the
`python -m tdc_tpu.lint` CLI (which must run with zero third-party
imports); every jax import below is function-local for that reason.
"""

from __future__ import annotations

import re
import warnings
from dataclasses import dataclass, field

# The collective primitive names as they appear in jaxpr eqns. pmean is
# absent on purpose: it decomposes to psum + div before it reaches a
# jaxpr.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "psum_scatter", "reduce_scatter", "pgather", "pbroadcast",
})

# Primitives that imply a host round trip (or a host-driven callback)
# inside a compiled program: a `jax.device_put` traced into a hot step, a
# `jax.debug.print`/`pure_callback`/`io_callback` in a path that runs per
# batch, or the infeed/outfeed legacy channels. Any of these inside a
# registry entry defeats the zero-transfer contract the resident tier's
# runtime transfer_guard enforces — this walk proves it statically, for
# every traced path rather than the one the smoke happened to execute.
TRANSFER_PRIMITIVES = frozenset({
    "device_put", "pure_callback", "io_callback", "debug_callback",
    "callback", "infeed", "outfeed",
})


class CollectiveDivergenceError(AssertionError):
    """A cond/switch emits different collective sequences per branch, or
    two traces of the same function disagree — some shard/process can
    execute a collective sequence its peers don't, which deadlocks the
    gang (or silently corrupts a quantized reduce)."""


@dataclass(frozen=True)
class CollectiveOp:
    """One collective eqn in program order — the golden-schedule record:
    primitive, named axes, and every operand's shape/dtype (the contract
    arXiv 2112.01075 verifies; a dtype change on the wire is drift even
    when the primitive sequence is unchanged)."""

    prim: str
    axes: str  # "axes=('data',)" — _axes_of's format (legacy-pinned)
    operands: tuple[tuple[tuple[int, ...], str], ...]  # ((shape, dtype),...)
    in_while: bool = False

    def legacy(self) -> str:
        """The string format TraceReport.sequence has always used (and
        tests pin): 'psum[axes=(...)]', 'while:'-prefixed in loop
        bodies."""
        s = f"{self.prim}[{self.axes}]"
        return f"while:{s}" if self.in_while else s

    def to_json(self) -> dict:
        return {
            "prim": self.prim,
            "axes": self.axes,
            "operands": [
                {"shape": list(shape), "dtype": dtype}
                for shape, dtype in self.operands
            ],
            "while": self.in_while,
        }

    @staticmethod
    def from_json(d: dict) -> "CollectiveOp":
        return CollectiveOp(
            prim=d["prim"],
            axes=d["axes"],
            operands=tuple(
                (tuple(o["shape"]), o["dtype"]) for o in d["operands"]
            ),
            in_while=bool(d.get("while", False)),
        )


@dataclass
class TraceReport:
    sequence: list[str]  # e.g. ["psum[axes=('data',)]", ...]
    divergences: list[str] = field(default_factory=list)
    # Collectives inside lax.while_loop bodies (entries also appear in
    # `sequence` with a "while:" prefix). A while loop's trip count is
    # value-dependent: if the predicate consults shard-local values, the
    # shards issue these collectives DIFFERENT numbers of times and the
    # gang deadlocks — a divergence this static walk cannot prove or
    # refute (the repo's in-jit Lloyd loops are safe because their
    # predicate derives from the globally-psum'd shift, but that is a
    # data-flow property). Callers wanting a hard guarantee pass
    # forbid_while_collectives=True.
    while_collectives: list[str] = field(default_factory=list)
    # The detailed per-op records `sequence` is derived from (shapes and
    # dtypes included) — what the schedule goldens serialize.
    ops: list[CollectiveOp] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.divergences


def _axes_of(params: dict) -> str:
    for key in ("axes", "axis_name", "axis_index_groups"):
        if key in params and params[key] is not None and \
                key != "axis_index_groups":
            val = params[key]
            if not isinstance(val, tuple):
                val = (val,)
            named = tuple(str(a) for a in val)
            return f"axes={named}"
    return "axes=?"


def _operands_of(eqn) -> tuple[tuple[tuple[int, ...], str], ...]:
    out = []
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        shape = tuple(int(s) for s in getattr(aval, "shape", ()))
        dtype = str(getattr(aval, "dtype", "?"))
        out.append((shape, dtype))
    return tuple(out)


def _subjaxprs(params: dict):
    """Every Jaxpr/ClosedJaxpr reachable from an eqn's params — covers
    pjit, shard_map, scan, while, cond, remat, custom_* generically."""
    import jax.core as core

    closed = getattr(core, "ClosedJaxpr", None)
    open_ = getattr(core, "Jaxpr", None)

    def visit(val):
        if closed is not None and isinstance(val, closed):
            yield val.jaxpr
        elif open_ is not None and isinstance(val, open_):
            yield val
        elif isinstance(val, (tuple, list)):
            for v in val:
                yield from visit(v)

    for key, val in params.items():
        if key in ("branches",):
            continue  # cond branches are compared, not inlined, below
        yield from visit(val)


def _walk(jaxpr, out: list[CollectiveOp], divergences: list[str],
          in_while: bool = False) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMITIVES:
            out.append(CollectiveOp(
                prim=prim, axes=_axes_of(eqn.params),
                operands=_operands_of(eqn), in_while=in_while,
            ))
            continue
        if prim == "while":
            # Value-dependent trip count: body collectives repeat an
            # unknowable number of times — recorded separately (see
            # TraceReport.while_collectives) instead of silently inlined
            # as if they ran once.
            for key in ("cond_jaxpr", "body_jaxpr"):
                sub = eqn.params.get(key)
                if sub is not None:
                    _walk(sub.jaxpr, out, divergences, in_while=True)
            continue
        if prim in ("cond", "switch"):
            branch_seqs: list[list[CollectiveOp]] = []
            for br in eqn.params.get("branches", ()):
                seq: list[CollectiveOp] = []
                _walk(br.jaxpr, seq, divergences, in_while)
                branch_seqs.append(seq)
            if branch_seqs and any(
                    [o.legacy() for o in s]
                    != [o.legacy() for o in branch_seqs[0]]
                    for s in branch_seqs[1:]):
                legacy = [[o.legacy() for o in s] for s in branch_seqs]
                divergences.append(
                    f"cond branches emit different collective sequences "
                    f"{legacy} — a shard-varying predicate here "
                    "desyncs the gang"
                )
            # Executed exactly once whichever branch wins; with uniform
            # branches the subsequence is unconditionally part of the
            # program order.
            if branch_seqs:
                out.extend(branch_seqs[0])
            continue
        for sub in _subjaxprs(eqn.params):
            _walk(sub, out, divergences, in_while)


def collective_trace(fn, *args, **kwargs) -> TraceReport:
    """Trace fn(*args, **kwargs) and return its ordered collective
    sequence plus any branch-divergence findings."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    ops: list[CollectiveOp] = []
    divergences: list[str] = []
    _walk(closed.jaxpr, ops, divergences)
    return TraceReport(
        sequence=[o.legacy() for o in ops],
        divergences=divergences,
        while_collectives=[o.legacy() for o in ops if o.in_while],
        ops=ops,
    )


def assert_uniform_collectives(fn, *args, n_traces: int = 2,
                               require_collectives: bool = False,
                               forbid_while_collectives: bool = False,
                               **kwargs) -> TraceReport:
    """The whole contract in one call: trace `fn` `n_traces` times,
    assert (a) no divergent cond branches, (b) the sequence is identical
    across traces, and optionally (c) at least one collective is present
    (a tower that silently lost its psum 'passes' any divergence check).
    Returns the report of the first trace.

    Caveat (see TraceReport.while_collectives): collectives inside
    lax.while_loop bodies run trip-count-many times, and trip-count
    uniformity across shards is a data-flow property this static walk
    cannot decide — a convergence loop whose predicate derives from a
    globally-reduced value is safe; one consulting shard-local state is
    a deadlock. Such collectives are reported, and hard-rejected with
    forbid_while_collectives=True."""
    reports = [collective_trace(fn, *args, **kwargs)
               for _ in range(max(n_traces, 1))]
    first = reports[0]
    if first.divergences:
        raise CollectiveDivergenceError("\n".join(first.divergences))
    if forbid_while_collectives and first.while_collectives:
        raise CollectiveDivergenceError(
            f"collectives inside while-loop bodies "
            f"{first.while_collectives}: the trip count is value-"
            "dependent, so per-shard uniformity of these collectives "
            "cannot be statically guaranteed — prove the predicate is "
            "derived from globally-reduced values, or restructure with "
            "a static-length lax.scan"
        )
    for i, rep in enumerate(reports[1:], start=2):
        if rep.sequence != first.sequence:
            raise CollectiveDivergenceError(
                f"collective sequence is not stable across traces: trace 1 "
                f"emitted {first.sequence} but trace {i} emitted "
                f"{rep.sequence} — the trace consults ambient state, and "
                "processes compiling at different times would run "
                "different programs"
            )
    if require_collectives and not first.sequence:
        raise CollectiveDivergenceError(
            "no collective primitive found in the trace — the cross-shard "
            "reduce was lost (or the wrong tower was checked)"
        )
    return first


# ---------------------------------------------------------------------------
# Transfer audit (jaxpr walk)
# ---------------------------------------------------------------------------


def transfer_ops(fn, *args, **kwargs) -> list[str]:
    """Host-transfer/callback primitives reachable from tracing
    fn(*args) — 'device_put', 'debug_callback(while)' etc., in program
    order ('(while)' marks ops inside a while body, where they repeat
    per iteration). Empty list = the zero-transfer contract holds
    statically."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    found: list[str] = []

    def walk(jaxpr, in_while: bool) -> None:
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim in TRANSFER_PRIMITIVES:
                found.append(f"{prim}(while)" if in_while else prim)
            if prim == "while":
                for key in ("cond_jaxpr", "body_jaxpr"):
                    sub = eqn.params.get(key)
                    if sub is not None:
                        walk(sub.jaxpr, True)
                continue
            if prim in ("cond", "switch"):
                for br in eqn.params.get("branches", ()):
                    walk(br.jaxpr, in_while)
                continue
            for sub in _subjaxprs(eqn.params):
                walk(sub, in_while)

    walk(closed.jaxpr, False)
    return found


# ---------------------------------------------------------------------------
# Donation audit (lowered-artifact inspection)
# ---------------------------------------------------------------------------

# Donation in the lowered artifact takes two spellings: a definite
# input→output alias (`tf.aliasing_output = N`, single-device/committed
# layouts) or a compiler-delegated donation (`jax.buffer_donor = true`,
# sharded args whose aliasing XLA resolves at compile time). Either one
# means the donate_argnums contract survived lowering; a shape/dtype
# mismatch drops BOTH (with a "donated buffers were not usable" warning).
_ALIAS_RE = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")


@dataclass(frozen=True)
class DonationReport:
    """declared = donated leaves the factory contract promises; aliased =
    input-output aliases actually present in the lowered artifact. A
    shortfall means some 'donated' buffer is silently copied every
    dispatch (shape/dtype mismatch between the donated input and every
    output, or a dropped donate_argnums)."""

    declared: int
    aliased: int
    dropped: tuple[str, ...]  # lowering warnings naming unusable buffers

    @property
    def ok(self) -> bool:
        return self.aliased == self.declared


def donation_report(jit_fn, *args, declared: int, **kwargs) -> DonationReport:
    """Lower `jit_fn(*args)` and count `tf.aliasing_output` argument
    attributes in the StableHLO — the compiled-artifact truth of
    donate_argnums. `declared` is the number of donated *leaves* the
    entry promises (every leaf of every donated argument)."""
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        text = jit_fn.lower(*args, **kwargs).as_text()
    aliased = len(_ALIAS_RE.findall(text))
    dropped = tuple(
        str(w.message) for w in caught
        if "donated" in str(w.message).lower()
    )
    return DonationReport(declared=declared, aliased=aliased,
                          dropped=dropped)


# ---------------------------------------------------------------------------
# Recompile audit (jit-cache identity)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RecompileReport:
    """Cache growth across two static-compatible calls. new_entries_second
    must be 0: a second compile for inputs that only changed *values*
    means some static argument (an f-string config, a fresh closure, a
    non-hashable-coerced object) varies per call — TDC003's hazard, proven
    on the artifact cache instead of the source."""

    new_entries_first: int
    new_entries_second: int

    @property
    def ok(self) -> bool:
        return self.new_entries_second == 0


def recompile_report(jit_fn, args_first, args_second) -> RecompileReport:
    """Call `jit_fn` with two freshly-built, perturbed-but-compatible
    argument tuples and report jit-cache growth per call. Arguments must
    be fresh per call (donated buffers are consumed)."""
    import jax

    size = jit_fn._cache_size
    before = size()
    jax.block_until_ready(jit_fn(*args_first))
    mid = size()
    jax.block_until_ready(jit_fn(*args_second))
    after = size()
    return RecompileReport(
        new_entries_first=mid - before,
        new_entries_second=after - mid,
    )


__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "CollectiveDivergenceError",
    "CollectiveOp",
    "DonationReport",
    "RecompileReport",
    "TRANSFER_PRIMITIVES",
    "TraceReport",
    "assert_uniform_collectives",
    "collective_trace",
    "donation_report",
    "recompile_report",
    "transfer_ops",
]
