import sys

from tdc_tpu.verify.cli import main

sys.exit(main())
