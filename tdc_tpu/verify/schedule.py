"""Collective-schedule goldens: load, compare, ratchet.

The committed golden file (tests/golden/collective_schedules/
schedules.json) is the single source of truth for every registry entry's
collective schedule — primitive, named axes, operand shapes/dtypes, and
while-body membership, in program order. The CI stage compares live
traces against it and fails with a structured diff on ANY difference;
regeneration is an explicit, reviewed step:

    python -m tdc_tpu.verify --write-goldens
    git diff tests/golden/collective_schedules/schedules.json  # REVIEW!

exactly the tdclint-baseline workflow (docs/LINTING.md): the diff of the
committed JSON reads as a schedule ledger, and a regeneration that adds
or reorders collectives is a reviewable event, never an invisible one.

Tests assert against the same file via `golden_sequence(entry_id)`
(legacy 'psum[axes=(...)]' strings, shape-independent) so the scattered
assert_uniform_collectives pins and the CI goldens can never disagree.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from functools import lru_cache

from tdc_tpu.verify.ir import CollectiveOp

GOLDEN_VERSION = 1

# Repo-relative default; resolved against this file so the CLI works from
# any cwd (the lint CLI's path discipline).
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_GOLDEN_PATH = os.path.join(
    _REPO, "tests", "golden", "collective_schedules", "schedules.json")


@dataclass(frozen=True)
class ScheduleDiff:
    """One entry's golden-vs-live difference, human-structured: the first
    divergent position plus both full legacy sequences."""

    entry: str
    message: str


def load_goldens(path: str = DEFAULT_GOLDEN_PATH) -> dict:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != GOLDEN_VERSION:
        raise ValueError(
            f"golden {path}: unsupported version {data.get('version')!r} "
            f"(want {GOLDEN_VERSION})"
        )
    return data


@lru_cache(maxsize=4)
def _load_cached(path: str) -> dict:
    """Read-only consumers (the test pins call golden_sequence several
    times per test) share one parse per path; the gate and the regen
    path go through the uncached load_goldens."""
    return load_goldens(path)


def golden_ops(entry_id: str, path: str = DEFAULT_GOLDEN_PATH) \
        -> list[CollectiveOp]:
    """The committed CollectiveOps for one entry (KeyError if absent —
    a test asserting against a missing golden must fail loudly)."""
    data = _load_cached(path)
    ent = data["entries"][entry_id]
    return [CollectiveOp.from_json(d) for d in ent["collectives"]]


def golden_sequence(entry_id: str, path: str = DEFAULT_GOLDEN_PATH) \
        -> list[str]:
    """The committed legacy-format sequence ('psum[axes=(...)]', while:
    prefixed) for one entry — what the migrated test pins assert against.
    Shape-independent on purpose: tests trace their own (smaller) configs
    of the same factory."""
    return [op.legacy() for op in golden_ops(entry_id, path)]


def write_goldens(schedules: dict[str, list[CollectiveOp]],
                  path: str = DEFAULT_GOLDEN_PATH) -> dict:
    """Serialize `schedules` (entry id → traced ops) as the new golden
    file — sorted keys, one op per JSON object, trailing newline, atomic
    replace (the baseline writer's conventions)."""
    data = {
        "version": GOLDEN_VERSION,
        "note": (
            "tdcverify collective-schedule goldens — ONE source of truth "
            "for every driver entry point's compiled collective sequence "
            "(docs/VERIFICATION.md). Regenerate with `python -m "
            "tdc_tpu.verify --write-goldens` and REVIEW the diff: a new/"
            "reordered/retyped collective here is a cross-gang contract "
            "change, not noise."
        ),
        "entries": {
            eid: {"collectives": [op.to_json() for op in ops]}
            for eid, ops in sorted(schedules.items())
        },
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def _fmt_op(op: CollectiveOp) -> str:
    shapes = ", ".join(
        f"{dtype}[{'x'.join(map(str, shape))}]" for shape, dtype in
        op.operands
    )
    return f"{op.legacy()} <{shapes}>"


def compare(schedules: dict[str, list[CollectiveOp]],
            goldens: dict,
            known_ids: set[str] | None = None) -> list[ScheduleDiff]:
    """Structured golden-vs-live diff over the whole registry. Every
    difference is a finding: sequence drift (with the first divergent
    index), entries missing a golden (regen + review), and stale goldens
    whose entry no longer exists (regen so the ledger doesn't rot).

    known_ids: every registry id the run attempted (traced or not). A
    golden whose id is known but absent from `schedules` already produced
    a trace-failure finding upstream — reporting it stale here would
    steer the operator into a ledger-wiping regeneration. None skips the
    stale sweep entirely (a filtered --entries run, the lint partial-run
    rule)."""
    diffs: list[ScheduleDiff] = []
    recorded = goldens.get("entries", {})
    for eid, ops in sorted(schedules.items()):
        if eid not in recorded:
            diffs.append(ScheduleDiff(
                eid,
                "no committed golden for this entry — run `python -m "
                "tdc_tpu.verify --write-goldens`, review the diff, and "
                "commit tests/golden/collective_schedules/schedules.json",
            ))
            continue
        want = [CollectiveOp.from_json(d)
                for d in recorded[eid]["collectives"]]
        if ops == want:
            continue
        live_s = [_fmt_op(o) for o in ops]
        want_s = [_fmt_op(o) for o in want]
        first = next(
            (i for i, (a, b) in enumerate(zip(live_s, want_s)) if a != b),
            min(len(live_s), len(want_s)),
        )
        diffs.append(ScheduleDiff(
            eid,
            f"collective schedule drifted from golden at position {first}: "
            f"live={live_s} golden={want_s} — if the change is intended, "
            "regenerate with --write-goldens and review the diff",
        ))
    if known_ids is not None:
        for eid in sorted(set(recorded) - known_ids):
            diffs.append(ScheduleDiff(
                eid,
                "golden entry has no registry entry point (renamed or "
                "removed) — regenerate goldens so the ledger tracks the "
                "zoo",
            ))
    return diffs


__all__ = [
    "DEFAULT_GOLDEN_PATH",
    "GOLDEN_VERSION",
    "ScheduleDiff",
    "compare",
    "golden_ops",
    "golden_sequence",
    "load_goldens",
    "write_goldens",
]
