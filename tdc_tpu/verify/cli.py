"""`python -m tdc_tpu.verify` — the IR-verification CLI over
entries + ir + schedule (docs/VERIFICATION.md).

Mirrors the tdclint CLI conventions: exit 0 clean, 1 findings, 2 usage
error; `--format=json` is the machine interface; regeneration of the
committed artifact (`--write-goldens`) is an explicit, reviewed step,
never a side effect of a failing run.

The stage runs on CPU CI against TPU-shaped meshes: before jax loads we
force `JAX_PLATFORMS=cpu` (unless the caller pinned a platform) and 8
virtual host devices — tests/conftest.py's environment, so the traced
meshes are exactly the suite's.

`--mutate=path/to/module.py` (test-only) loads a module whose
`entries()` override registry entries by id — how the mutation suite
proves the stage actually catches a process-branched psum, a dropped
donation, and an f-string static argument (tests/verify_fixtures/).
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time
from dataclasses import dataclass

AUDITS = ("schedule", "transfer", "donation", "recompile")


def _force_cpu_mesh_env() -> None:
    """Must run before jax is imported anywhere in this process."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


@dataclass(frozen=True)
class VerifyFinding:
    entry: str
    audit: str
    message: str

    def location(self) -> str:
        return f"{self.entry}:{self.audit}"


def _load_mutations(paths: list[str]):
    out = []
    for i, p in enumerate(paths):
        spec = importlib.util.spec_from_file_location(
            f"_tdcverify_mutation_{i}", p)
        if spec is None or spec.loader is None:
            raise FileNotFoundError(f"cannot load mutation module: {p}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        if not hasattr(mod, "entries"):
            raise ValueError(
                f"mutation module {p} must define entries() -> "
                "list[VerifyEntry]")
        out.extend(mod.entries())
    return out


def _resolve_entries(mutate_paths: list[str], patterns: list[str]):
    from tdc_tpu.verify.entries import entries as base_entries

    ents = list(base_entries())
    if mutate_paths:
        overrides = _load_mutations(mutate_paths)
        by_id = {e.id: i for i, e in enumerate(ents)}
        for ov in overrides:
            if ov.id in by_id:
                ents[by_id[ov.id]] = ov
            else:
                ents.append(ov)
    if patterns:
        ents = [e for e in ents
                if any(pat in e.id for pat in patterns)]
    return ents


def _run_entry(entry, audits, schedules, findings):
    from tdc_tpu.verify import ir

    try:
        built = entry.build()
    except Exception as e:  # a broken builder must gate, not crash the run
        findings.append(VerifyFinding(
            entry.id, "build", f"entry builder raised: {type(e).__name__}: "
            f"{e}"))
        return

    if "schedule" in audits or "transfer" in audits:
        args0 = built.fresh(0)
    if "schedule" in audits:
        try:
            rep = ir.collective_trace(built.fn, *args0)
            rep2 = ir.collective_trace(built.fn, *built.fresh(0))
            if rep.divergences:
                for dmsg in rep.divergences:
                    findings.append(VerifyFinding(entry.id, "schedule", dmsg))
            elif rep2.sequence != rep.sequence:
                findings.append(VerifyFinding(
                    entry.id, "schedule",
                    f"collective sequence unstable across traces: "
                    f"{rep.sequence} vs {rep2.sequence} — the trace "
                    "consults ambient state",
                ))
            else:
                schedules[entry.id] = rep.ops
        except Exception as e:
            findings.append(VerifyFinding(
                entry.id, "schedule",
                f"trace failed: {type(e).__name__}: {e}"))
    if "transfer" in audits:
        try:
            hops = ir.transfer_ops(built.fn, *args0)
            if hops:
                findings.append(VerifyFinding(
                    entry.id, "transfer",
                    f"host-transfer/callback primitives inside the "
                    f"compiled unit: {hops} — a per-dispatch round trip "
                    "the runtime transfer_guard would reject (and a hot "
                    "path the smoke may never execute)",
                ))
        except Exception as e:
            findings.append(VerifyFinding(
                entry.id, "transfer",
                f"transfer walk failed: {type(e).__name__}: {e}"))
    if "donation" in audits and entry.donated_leaves:
        try:
            drep = ir.donation_report(
                built.jit_fn, *built.fresh(0),
                declared=entry.donated_leaves)
            if not drep.ok:
                extra = f" (lowering: {drep.dropped})" if drep.dropped else ""
                findings.append(VerifyFinding(
                    entry.id, "donation",
                    f"declared {drep.declared} donated leaves but the "
                    f"lowered artifact aliases {drep.aliased} — a donated "
                    "buffer is silently copied every dispatch (dropped "
                    f"donate_argnums or shape/dtype mismatch){extra}",
                ))
        except Exception as e:
            findings.append(VerifyFinding(
                entry.id, "donation",
                f"donation lowering failed: {type(e).__name__}: {e}"))
    if "recompile" in audits and entry.recompile:
        try:
            rrep = ir.recompile_report(
                built.jit_fn, built.fresh(1), built.fresh(2))
            if not rrep.ok:
                findings.append(VerifyFinding(
                    entry.id, "recompile",
                    f"second static-compatible call grew the jit cache by "
                    f"{rrep.new_entries_second} entr(y/ies) — a static "
                    "argument varies per call (TDC003's hazard, proven on "
                    "the artifact cache)",
                ))
        except Exception as e:
            findings.append(VerifyFinding(
                entry.id, "recompile",
                f"recompile proof failed: {type(e).__name__}: {e}"))


def _check_same_schedule(ents, schedules, findings) -> None:
    for e in ents:
        if e.same_schedule_as is None:
            continue
        if e.id not in schedules or e.same_schedule_as not in schedules:
            continue  # the missing trace already gated above
        a = [op.legacy() for op in schedules[e.id]]
        b = [op.legacy() for op in schedules[e.same_schedule_as]]
        if a != b:
            findings.append(VerifyFinding(
                e.id, "schedule",
                f"schedule must be identical to {e.same_schedule_as!r} "
                f"(cross-entry invariant) but differs: {a} vs {b}",
            ))


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tdc_tpu.verify",
        description="tdcverify: IR-level compiled-artifact verification "
                    "(docs/VERIFICATION.md)",
    )
    p.add_argument("--audits", metavar="NAMES",
                   help=f"comma-separated subset of {','.join(AUDITS)} "
                        "(default: all)")
    p.add_argument("--entries", metavar="SUBSTR", action="append",
                   default=[],
                   help="only entries whose id contains SUBSTR "
                        "(repeatable)")
    p.add_argument("--golden", metavar="PATH",
                   help="golden schedule file (default: tests/golden/"
                        "collective_schedules/schedules.json)")
    p.add_argument("--write-goldens", action="store_true",
                   help="rewrite the golden file from the live traces "
                        "(REVIEW the diff before committing)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--mutate", metavar="PATH", action="append", default=[],
                   help="test-only: load entry overrides from a module "
                        "file (tests/verify_fixtures/)")
    p.add_argument("--list-entries", action="store_true")
    args = p.parse_args(argv)

    audits = AUDITS
    if args.audits:
        audits = tuple(a.strip() for a in args.audits.split(",") if a.strip())
        bad = set(audits) - set(AUDITS)
        if bad:
            p.error(f"unknown audits: {sorted(bad)} (want {AUDITS})")
    if args.write_goldens and args.entries:
        # The golden-file twin of tdclint's partial-path --write-baseline
        # refusal: regenerating from an entry subset would drop every
        # other entry's schedule from the committed ledger.
        p.error("--write-goldens cannot be combined with --entries "
                "(a partial regeneration would drop the other entries' "
                "goldens)")
    if args.write_goldens and args.audits:
        # An audit subset omitting 'schedule' collects NO schedules — the
        # regeneration would rewrite the ledger EMPTY; and one skipping
        # the other audits is exactly the dirty-audit tree the findings
        # refusal below exists to reject.
        p.error("--write-goldens cannot be combined with --audits "
                "(goldens are regenerated only from a fully-audited tree)")
    if args.write_goldens and args.mutate:
        # A mutated registry whose defect happens to trace uniformly
        # would poison the committed contract silently.
        p.error("--write-goldens cannot be combined with --mutate "
                "(test-only overrides must never reach the committed "
                "goldens)")

    _force_cpu_mesh_env()

    from tdc_tpu.verify import schedule as schedule_mod

    golden_path = args.golden or schedule_mod.DEFAULT_GOLDEN_PATH

    try:
        ents = _resolve_entries(args.mutate, args.entries)
    except (FileNotFoundError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2

    if args.list_entries:
        for e in ents:
            marks = []
            if e.donated_leaves:
                marks.append(f"donate={e.donated_leaves}")
            if e.same_schedule_as:
                marks.append(f"same_as={e.same_schedule_as}")
            suffix = f"  [{', '.join(marks)}]" if marks else ""
            print(f"{e.id}{suffix}")
        return 0

    t0 = time.monotonic()
    findings: list[VerifyFinding] = []
    schedules: dict = {}
    for entry in ents:
        _run_entry(entry, audits, schedules, findings)
    _check_same_schedule(ents, schedules, findings)

    if args.write_goldens:
        if findings:
            for f in findings:
                print(f"{f.location()}: {f.message}", file=sys.stderr)
            print(
                "tdcverify: refusing --write-goldens with audit findings "
                "above — goldens must be regenerated from a tree whose "
                "uniformity/transfer/donation/recompile audits pass",
                file=sys.stderr,
            )
            return 1
        schedule_mod.write_goldens(schedules, golden_path)
        print(
            f"tdcverify: goldens written to {golden_path} for "
            f"{len(schedules)} entr(y/ies) — review the diff before "
            "committing"
        )
        return 0

    if "schedule" in audits:
        try:
            goldens = schedule_mod.load_goldens(golden_path)
        except FileNotFoundError:
            findings.append(VerifyFinding(
                "*", "schedule",
                f"golden file {golden_path} not found — generate it with "
                "--write-goldens and commit it",
            ))
        else:
            known = {e.id for e in ents} if not args.entries else None
            for diff in schedule_mod.compare(schedules, goldens, known):
                findings.append(VerifyFinding(
                    diff.entry, "schedule", diff.message))

    elapsed = time.monotonic() - t0
    findings.sort(key=lambda f: (f.entry, f.audit))
    if args.format == "json":
        print(json.dumps({
            "version": 1,
            "entries": len(ents),
            "audits": list(audits),
            "elapsed_seconds": round(elapsed, 3),
            "findings": [
                {"entry": f.entry, "audit": f.audit, "message": f.message}
                for f in findings
            ],
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.location()}: {f.message}")
        print(
            f"tdcverify: {len(findings)} finding(s) across {len(ents)} "
            f"entr(y/ies), audits={','.join(audits)}, in {elapsed:.1f}s",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
