"""tdcverify — IR-level verification of the compiled artifacts.

tdclint (tdc_tpu/lint) guards the *source* layer with stdlib AST rules;
this package guards the layer where SPMD correctness actually becomes
binding: the traced/lowered program. Four audits, run as one gating CI
stage (`python -m tdc_tpu.verify`, docs/VERIFICATION.md):

- **schedule** — every registry entry point's ordered collective
  sequence (primitive, axis names, operand shapes/dtypes) is extracted
  from its jaxpr and compared against committed goldens
  (tests/golden/collective_schedules/schedules.json). Any drift fails
  with a structured diff; regeneration is an explicit, reviewed step
  (`--write-goldens`), the tdclint-baseline ratchet applied to the
  collective contract. Cross-entry invariants (`same_schedule_as`, e.g.
  coarse assignment must match exact's schedule) are machine-checked on
  the live traces, not just the goldens.
- **transfer** — the jaxpr walk proves no implicit host transfer
  (callback/device_put/infeed primitives) hides inside a hot compiled
  unit: the static generalization of models/resident's runtime
  `transfer_guard`, covering paths the smoke never executes.
- **donation** — every buffer a step factory declares in
  `donate_argnums` is *actually aliased* in the lowered artifact
  (`tf.aliasing_output` in the StableHLO): a shape/dtype mismatch that
  silently defeats donation (copy-on-alias) fails the stage.
- **recompile** — each jitted entry runs twice under perturbed but
  static-compatible inputs and the jit cache must not grow: the
  semantic companion of TDC003's syntactic recompile heuristic.

Layout: `ir.py` (the jaxpr/MLIR toolkit — grown from lint/jaxpr_check,
which remains as a thin re-export), `entries.py` (the driver-zoo
registry), `schedule.py` (golden load/compare/write), `cli.py`.

Like the lint package, importing `tdc_tpu.verify` itself stays cheap;
jax is pulled in by the registry/CLI, never by `ir`'s module scope.
"""

from tdc_tpu.verify.ir import (  # noqa: F401
    COLLECTIVE_PRIMITIVES,
    CollectiveDivergenceError,
    CollectiveOp,
    TraceReport,
    TRANSFER_PRIMITIVES,
    assert_uniform_collectives,
    collective_trace,
    donation_report,
    recompile_report,
    transfer_ops,
)

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "CollectiveDivergenceError",
    "CollectiveOp",
    "TRANSFER_PRIMITIVES",
    "TraceReport",
    "assert_uniform_collectives",
    "collective_trace",
    "donation_report",
    "recompile_report",
    "transfer_ops",
]
