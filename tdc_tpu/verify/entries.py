"""The driver-zoo registry tdcverify audits.

Each VerifyEntry names one compiled unit a public driver dispatches —
the per-batch stats towers, the per-pass deferred adds/reduces (plain,
bf16/int8-quantized), the resident chunk loops, the coarse→refine
assignment paths — across the config matrix the platform claims
invariants for: 1-D vs K-sharded × kmeans/fuzzy/GMM × per_batch vs
per_pass[:int8] × exact vs coarse assign × stream vs hbm residency.

Tracing is abstract at heart (`jax.make_jaxpr` over small concrete
examples — shapes are the contract, values are irrelevant), so CPU CI
covers TPU-shaped meshes exactly the way tests/conftest.py does: 8
virtual devices, the same meshes the drivers build on a pod slice.

Entries whose jaxpr carries NO explicit collective (the 1-D flat-mesh
per-batch paths, where XLA's GSPMD inserts the reduce during SPMD
partitioning, below the jaxpr) golden an EMPTY schedule on purpose:
"nothing explicit here" is itself a pinned property — an explicit
collective appearing in such a path is drift worth reviewing.

The registry is data: the CLI (and the mutation-test fixtures, via
--mutate) consume `entries()`. Keep ids stable — they key the committed
goldens in tests/golden/collective_schedules/schedules.json and the
test-suite pins that assert against them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, NamedTuple


class Built(NamedTuple):
    """One materialized entry: `fn` is the traceable target (used for the
    schedule + transfer walks), `jit_fn` the jitted callable for the
    donation/recompile audits (identical to fn when the factory already
    jits), `fresh(i)` builds a brand-new argument tuple (donated buffers
    are consumed, so every audit call gets its own)."""

    fn: Callable
    jit_fn: Callable
    fresh: Callable[[int], tuple]


@dataclass(frozen=True)
class VerifyEntry:
    id: str
    build: Callable[[], Built]
    # Donated *leaves* the factory declares (0 = no donation contract —
    # the donation audit is skipped, not trivially green).
    donated_leaves: int = 0
    # Skip the recompile proof (e.g. an entry kept trace-only).
    recompile: bool = True
    # Assert this entry's legacy collective sequence equals another
    # entry's — the cross-entry invariants (coarse assignment must be
    # schedule-identical to exact), machine-checked on live traces.
    same_schedule_as: str | None = None
    notes: str = ""


# ---------------------------------------------------------------------------
# Shared fixtures (built lazily, once per process)
# ---------------------------------------------------------------------------

_K1, _D1 = 8, 4        # 1-D driver shapes
_K2, _D2 = 16, 4       # K-sharded shapes (K % n_model == 0)
_ROWS = 64             # batch rows (multiple of every data-axis extent)

_cache = {}


def _np():
    import numpy as np

    return np


def _mesh1():
    if "mesh1" not in _cache:
        from tdc_tpu.parallel.mesh import make_mesh

        _cache["mesh1"] = make_mesh(8)
    return _cache["mesh1"]


def _mesh_hier():
    if "meshH" not in _cache:
        from tdc_tpu.parallel.mesh import make_hierarchical_mesh

        _cache["meshH"] = make_hierarchical_mesh(n_hosts=2)
    return _cache["meshH"]


def _mesh2d():
    if "mesh2" not in _cache:
        from tdc_tpu.parallel.sharded_k import make_mesh_2d

        _cache["mesh2"] = make_mesh_2d(2, 4)
    return _cache["mesh2"]


def _rows(i: int, n: int = _ROWS, d: int = _D1):
    """Deterministic full-rank-ish data; `i` perturbs values only (the
    recompile audit's static-compatible second call)."""
    np = _np()
    base = np.arange(n * d, dtype=np.float32).reshape(n, d)
    return (base % 17.0) + 0.25 * base / (n * d) + float(i)


def _centroids(i: int, k: int, d: int):
    np = _np()
    return (np.arange(k * d, dtype=np.float32).reshape(k, d) % 5.0) + float(i)


# ---------------------------------------------------------------------------
# 1-D streamed driver units (models/streaming.py)
# ---------------------------------------------------------------------------


def _build_1d_per_batch(mesh_fn, k=_K1, d=_D1):
    def build():
        import jax
        import jax.numpy as jnp

        from tdc_tpu.models.streaming import _accumulate
        from tdc_tpu.ops.assign import SufficientStats

        mesh = mesh_fn()
        fn = jax.jit(
            lambda acc, b, c, nv: _accumulate(acc, b, c, nv, False, "xla",
                                              mesh)
        )

        def fresh(i):
            acc = SufficientStats(
                sums=jnp.zeros((k, d), jnp.float32),
                counts=jnp.zeros((k,), jnp.float32),
                sse=jnp.zeros((), jnp.float32),
            )
            return (acc, jnp.asarray(_rows(i)), jnp.asarray(_centroids(i, k, d)),
                    jnp.asarray(float(_ROWS), jnp.float32))

        return Built(fn, fn, fresh)

    return build


def _deferred_1d(model: str, quantize):
    """(zero_acc, acc_add, reduce) for a 1-D per-pass family."""
    mesh = _mesh1()
    if model == "kmeans":
        from tdc_tpu.models.streaming import _deferred_lloyd_fns

        return _deferred_lloyd_fns(mesh, _K1, _D1, False, "xla", quantize,
                                   False), mesh
    if model == "fuzzy":
        from tdc_tpu.models.streaming import _deferred_fuzzy_fns

        return _deferred_fuzzy_fns(mesh, _K1, _D1, 2.0, "xla", quantize,
                                   False), mesh
    from tdc_tpu.models.gmm import _deferred_gmm_fns

    return _deferred_gmm_fns(mesh, _K1, _D1, "xla", "diag", quantize,
                             False), mesh


def _gmm_params(i: int):
    import jax.numpy as jnp

    means = jnp.asarray(_centroids(i, _K1, _D1))
    variances = jnp.ones((_K1, _D1), jnp.float32) + 0.1 * float(i)
    weights = jnp.full((_K1,), 1.0 / _K1, jnp.float32)
    return means, variances, weights


def _build_acc_add(model: str):
    def build():
        import jax.numpy as jnp

        (zero_acc, acc_add, _), _mesh = _deferred_1d(model, None)

        def fresh(i):
            acc = zero_acc()
            x = jnp.asarray(_rows(i))
            if model == "gmm":
                return (acc, x, *_gmm_params(i))
            return (acc, x, jnp.asarray(_centroids(i, _K1, _D1)))

        return Built(acc_add, acc_add, fresh)

    return build


def _build_reduce(model: str, quantize):
    def build():
        from tdc_tpu.parallel import reduce as reduce_lib

        (zero_acc, _, reducer), mesh = _deferred_1d(model, quantize)
        if model == "kmeans":
            from tdc_tpu.models.streaming import _lloyd_example

            example = _lloyd_example(_K1, _D1)
        elif model == "fuzzy":
            from tdc_tpu.models.streaming import _fuzzy_example

            example = _fuzzy_example(_K1, _D1)
        else:
            from tdc_tpu.models.gmm import _gmm_example

            example = _gmm_example(_K1, _D1, "diag")

        def fresh(i):
            acc = zero_acc()
            if quantize is None:
                return (acc,)
            err = reduce_lib.zero_deferred(mesh, example)
            return (acc, err)

        return Built(reducer, reducer, fresh)

    return build


def _build_coarse_accumulate():
    def build():
        import jax
        import jax.numpy as jnp

        from tdc_tpu.models.streaming import _accumulate_subk
        from tdc_tpu.ops.assign import SufficientStats
        from tdc_tpu.ops import subk as subk_lib

        spec = subk_lib.resolve_assign("coarse", _K1, probe=2,
                                      label="tdcverify")

        def fn(acc, b, c, nv):
            return _accumulate_subk(acc, b, c, nv, False, spec)

        jit_fn = jax.jit(fn)

        def fresh(i):
            acc = SufficientStats(
                sums=jnp.zeros((_K1, _D1), jnp.float32),
                counts=jnp.zeros((_K1,), jnp.float32),
                sse=jnp.zeros((), jnp.float32),
            )
            return (acc, jnp.asarray(_rows(i)),
                    jnp.asarray(_centroids(i, _K1, _D1)),
                    jnp.asarray(_ROWS, jnp.int32))

        return Built(fn, jit_fn, fresh)

    return build


# ---------------------------------------------------------------------------
# Resident (hbm) units (models/resident.py via streaming factories)
# ---------------------------------------------------------------------------


def _resident_cache():
    """One shared 3-batch DeviceCache on the 1-D mesh (not donated — safe
    to reuse across audits and entries)."""
    if "rcache" not in _cache:
        from tdc_tpu.data.device_cache import DeviceCacheBuilder
        from tdc_tpu.models.streaming import _prepare_batch

        mesh = _mesh1()
        b = DeviceCacheBuilder(3, mesh=mesh)
        for j in range(3):
            xb, nv, _ = _prepare_batch(_rows(0, _ROWS, _D1) + j, mesh)
            b.add(xb, nv)
        _cache["rcache"] = b.finish()
    return _cache["rcache"]


def _resident_cache_nomesh():
    """Mesh-free 3-batch cache for the single-device bounded chunk (the
    1-D bounded driver is mesh-free by contract)."""
    if "rcache0" not in _cache:
        from tdc_tpu.data.device_cache import DeviceCacheBuilder
        from tdc_tpu.models.streaming import _prepare_batch

        b = DeviceCacheBuilder(3)
        for j in range(3):
            xb, nv, _ = _prepare_batch(_rows(0, _ROWS, _D1) + j, None)
            b.add(xb, nv)
        _cache["rcache0"] = b.finish()
    return _cache["rcache0"]


def _build_bounded_chunk(kind: str):
    """The 1-D bounded resident chunk: per-point bounds carry donated
    alongside the centroids; single-device, so the pinned property is an
    EMPTY explicit collective schedule — bounds prune FLOPs, never
    collectives."""

    def build():
        import jax.numpy as jnp

        from tdc_tpu.models import resident as resident_lib
        from tdc_tpu.models.streaming import _resident_lloyd_fns
        from tdc_tpu.ops import bounds as bounds_lib
        from tdc_tpu.ops import subk as subk_lib

        bspec = bounds_lib.BoundsSpec(kind=kind, **(
            {"n_tiles": 2, "tile_size": _K1 // 2} if kind == "elkan" else {}
        ))
        (chunk, _), cache = (
            _resident_lloyd_fns(None, _K1, _D1, False, "xla", None, False,
                                False, 1e-6, 4, subk_lib.EXACT, bspec),
            _resident_cache_nomesh(),
        )

        def fresh(i):
            c = jnp.asarray(_centroids(i, _K1, _D1))
            aux = bounds_lib.init_state(cache, c, bspec)
            cap = resident_lib.place_scalar(4, None)
            return (c, aux, cap, cache)

        return Built(chunk, chunk, fresh)

    return build


def _build_sharded_bounded_stats():
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tdc_tpu.parallel import sharded_k as sk

        mesh = _mesh2d()
        fn = sk.make_sharded_bounded_stats(mesh)
        jit_fn = jax.jit(fn)

        def fresh(i):
            x = jnp.asarray(_rows(i, _ROWS, _D2))
            c = jax.device_put(
                jnp.asarray(_centroids(i, _K2, _D2)),
                NamedSharding(mesh, P(sk.MODEL_AXIS, None)),
            )
            st = sk.init_sharded_bounds(mesh, _ROWS, _centroids(i, _K2,
                                                                _D2))
            return (x, c, st.prev_c, st.lab, st.lb)

        return Built(fn, jit_fn, fresh)

    return build


def _resident_fns(model: str, deferred: bool, quantize, coarse: bool = False):
    mesh = _mesh1()
    if model == "fuzzy":
        from tdc_tpu.models.streaming import _resident_fuzzy_fns

        return _resident_fuzzy_fns(mesh, _K1, _D1, 2.0, "xla", quantize,
                                   False, deferred, 1e-6, 4), mesh
    from tdc_tpu.models.streaming import _resident_lloyd_fns
    from tdc_tpu.ops import subk as subk_lib

    aspec = (subk_lib.resolve_assign("coarse", _K1, probe=2,
                                     label="tdcverify")
             if coarse else subk_lib.EXACT)
    return _resident_lloyd_fns(mesh, _K1, _D1, False, "xla", quantize,
                               False, deferred, 1e-6, 4, aspec), mesh


def _resident_aux(deferred: bool, quantize, model: str):
    if not deferred or quantize is None:
        return ()
    from tdc_tpu.parallel import reduce as reduce_lib

    if model == "fuzzy":
        from tdc_tpu.models.streaming import _fuzzy_example

        example = _fuzzy_example(_K1, _D1)
    else:
        from tdc_tpu.models.streaming import _lloyd_example

        example = _lloyd_example(_K1, _D1)
    return reduce_lib.zero_deferred(_mesh1(), example)


def _build_resident(model: str, deferred: bool, quantize,
                    coarse: bool = False, final_pass: bool = False):
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tdc_tpu.models import resident as resident_lib

        (chunk, pass_only), mesh = _resident_fns(model, deferred, quantize,
                                                 coarse)
        cache = _resident_cache()
        fn = pass_only if final_pass else chunk

        def fresh(i):
            c = jax.device_put(
                jnp.asarray(_centroids(i, _K1, _D1)),
                NamedSharding(mesh, P()),
            )
            aux = _resident_aux(deferred, quantize, model)
            if final_pass:
                return (c, aux, cache)
            cap = resident_lib.place_scalar(4, mesh)
            return (c, aux, cap, cache)

        return Built(fn, fn, fresh)

    return build


# ---------------------------------------------------------------------------
# K-sharded units (parallel/sharded_k.py)
# ---------------------------------------------------------------------------


def _sharded_args(i: int, with_nv: bool = False):
    import jax.numpy as jnp

    x = jnp.asarray(_rows(i, _ROWS, _D2))
    c = jnp.asarray(_centroids(i, _K2, _D2))
    if with_nv:
        return (x, c, jnp.asarray(_ROWS, jnp.int32))
    return (x, c)


def _build_sharded_stats(coarse: bool, reduce_data: bool):
    def build():
        import jax

        from tdc_tpu.parallel.sharded_k import make_sharded_stats
        from tdc_tpu.ops import subk as subk_lib

        # Local K/Pm = 4 → 2 default tiles; probe must stay below the
        # tile count or resolve_assign routes back to exact.
        aspec = (subk_lib.resolve_assign("coarse", _K2 // 4, probe=1,
                                         label="tdcverify")
                 if coarse else None)
        if coarse:
            assert aspec.coarse, aspec
        fn = make_sharded_stats(_mesh2d(), reduce_data=reduce_data,
                                assign_spec=aspec)
        jit_fn = jax.jit(fn)

        def fresh(i):
            return _sharded_args(i, with_nv=coarse)

        return Built(fn, jit_fn, fresh)

    return build


def _build_sharded_gather_stats(mode: str, coarse: bool = False):
    """The compressed-gather stats towers (parallel/gather.py): the
    champion (min, argmin) all_gather pair with the mins leg encoded
    bf16 / packed-int8 — the packed payload keeps the collective count
    and order IDENTICAL to fp32 (the property same_schedule_as pins)."""
    def build():
        import jax

        from tdc_tpu.parallel.sharded_k import make_sharded_stats
        from tdc_tpu.ops import subk as subk_lib

        aspec = (subk_lib.resolve_assign("coarse", _K2 // 4, probe=1,
                                         label="tdcverify")
                 if coarse else None)
        fn = make_sharded_stats(_mesh2d(), assign_spec=aspec, gather=mode)
        jit_fn = jax.jit(fn)

        def fresh(i):
            return _sharded_args(i, with_nv=coarse)

        return Built(fn, jit_fn, fresh)

    return build


def _build_sharded_finalize(mode: str):
    """The data-axis-sharded centroid finalize: one slice all_gather
    (data) + one 4-byte shift pmax (data, model); the quantized modes
    add the error-feedback residual operand without changing the
    collective count/order."""
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tdc_tpu.parallel import sharded_k as sk

        mesh = _mesh2d()
        fn = sk.make_sharded_finalize(mesh, mode=mode)
        jit_fn = jax.jit(fn)
        quantized = mode in ("bf16", "int8")

        def fresh(i):
            sums = jnp.asarray(_centroids(i, _K2, _D2))
            sums = jax.device_put(
                sums, NamedSharding(mesh, P(sk.MODEL_AXIS, None)))
            counts = jax.device_put(
                jnp.ones((_K2,), jnp.float32) * (i + 1),
                NamedSharding(mesh, P(sk.MODEL_AXIS)))
            c = jax.device_put(
                jnp.asarray(_centroids(i + 7, _K2, _D2)),
                NamedSharding(mesh, P(sk.MODEL_AXIS, None)))
            if quantized:
                return (sums, counts, c,
                        sk.zero_finalize_err(mesh, _K2, _D2))
            return (sums, counts, c)

        return Built(fn, jit_fn, fresh)

    return build


def _build_sharded_deferred_reduce():
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tdc_tpu.parallel.sharded_k import (
            DATA_AXIS, MODEL_AXIS, make_sharded_deferred_reduce,
        )

        mesh = _mesh2d()
        fn = make_sharded_deferred_reduce(mesh)
        jit_fn = jax.jit(fn)
        n_data = 2

        def fresh(i):
            sums = jnp.zeros((n_data, _K2, _D2), jnp.float32,
                             device=NamedSharding(
                                 mesh, P(DATA_AXIS, MODEL_AXIS, None)))
            counts = jnp.zeros((n_data, _K2), jnp.float32,
                               device=NamedSharding(
                                   mesh, P(DATA_AXIS, MODEL_AXIS)))
            sse = jnp.zeros((n_data,), jnp.float32,
                            device=NamedSharding(mesh, P(DATA_AXIS)))
            return (sums + i, counts, sse)

        return Built(fn, jit_fn, fresh)

    return build


def _build_sharded_deferred_accumulate(model: str):
    def build():
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tdc_tpu.parallel import sharded_k as sk

        mesh = _mesh2d()
        n_data = 2
        if model == "fuzzy":
            stats_fn = sk.make_sharded_fuzzy_stats(mesh, reduce_data=False)
            acc_cls = sk._ShardedFuzzyAcc

            def zero():
                return acc_cls(
                    wsums=jnp.zeros(
                        (n_data, _K2, _D2), jnp.float32,
                        device=NamedSharding(
                            mesh, P(sk.DATA_AXIS, sk.MODEL_AXIS, None))),
                    weights=jnp.zeros(
                        (n_data, _K2), jnp.float32,
                        device=NamedSharding(
                            mesh, P(sk.DATA_AXIS, sk.MODEL_AXIS))),
                    obj=jnp.zeros(
                        (n_data * 4,), jnp.float32,
                        device=NamedSharding(
                            mesh, P((sk.DATA_AXIS, sk.MODEL_AXIS)))),
                )
        else:
            stats_fn = sk.make_sharded_stats(mesh, reduce_data=False)
            acc_cls = sk._ShardedAcc

            def zero():
                return acc_cls(
                    sums=jnp.zeros(
                        (n_data, _K2, _D2), jnp.float32,
                        device=NamedSharding(
                            mesh, P(sk.DATA_AXIS, sk.MODEL_AXIS, None))),
                    counts=jnp.zeros(
                        (n_data, _K2), jnp.float32,
                        device=NamedSharding(
                            mesh, P(sk.DATA_AXIS, sk.MODEL_AXIS))),
                    sse=jnp.zeros(
                        (n_data,), jnp.float32,
                        device=NamedSharding(mesh, P(sk.DATA_AXIS))),
                )

        fn = sk.make_sharded_deferred_accumulate(stats_fn, acc_cls)

        def fresh(i):
            return (zero(), *_sharded_args(i))

        return Built(fn, fn, fresh)

    return build


def _build_sharded_fuzzy_stats(reduce_data: bool):
    def build():
        import jax

        from tdc_tpu.parallel.sharded_k import make_sharded_fuzzy_stats

        fn = make_sharded_fuzzy_stats(_mesh2d(), reduce_data=reduce_data)
        jit_fn = jax.jit(fn)

        def fresh(i):
            return _sharded_args(i)

        return Built(fn, jit_fn, fresh)

    return build


def _build_sharded_fuzzy_reduce():
    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tdc_tpu.parallel.sharded_k import (
            DATA_AXIS, MODEL_AXIS, make_sharded_fuzzy_deferred_reduce,
        )

        mesh = _mesh2d()
        fn = make_sharded_fuzzy_deferred_reduce(mesh)
        jit_fn = jax.jit(fn)
        n_data = 2

        def fresh(i):
            wsums = jnp.zeros((n_data, _K2, _D2), jnp.float32,
                              device=NamedSharding(
                                  mesh, P(DATA_AXIS, MODEL_AXIS, None)))
            weights = jnp.zeros((n_data, _K2), jnp.float32,
                                device=NamedSharding(
                                    mesh, P(DATA_AXIS, MODEL_AXIS)))
            obj = jnp.zeros((n_data * 4,), jnp.float32,
                            device=NamedSharding(
                                mesh, P((DATA_AXIS, MODEL_AXIS))))
            return (wsums + i, weights, obj)

        return Built(fn, jit_fn, fresh)

    return build


def _build_sharded_gmm_stats():
    def build():
        import jax
        import jax.numpy as jnp

        from tdc_tpu.parallel.sharded_k import make_sharded_gmm_stats

        fn = make_sharded_gmm_stats(_mesh2d())
        jit_fn = jax.jit(fn)

        def fresh(i):
            x = jnp.asarray(_rows(i, _ROWS, _D2))
            means = jnp.asarray(_centroids(i, _K2, _D2))
            variances = jnp.ones((_K2, _D2), jnp.float32)
            weights = jnp.full((_K2,), 1.0 / _K2, jnp.float32)
            return (x, means, variances, weights)

        return Built(fn, jit_fn, fresh)

    return build


def _build_gmm_per_batch_hier():
    def build():
        import jax
        import jax.numpy as jnp

        from tdc_tpu.models.gmm import GMMStats, _accumulate_gmm

        mesh = _mesh_hier()
        fn = jax.jit(
            lambda acc, b, mu, v, w, nv: _accumulate_gmm(
                acc, b, mu, v, w, nv, "xla", "diag", mesh)
        )

        def fresh(i):
            acc = GMMStats(
                ll_sum=jnp.zeros((), jnp.float32),
                nk=jnp.zeros((_K1,), jnp.float32),
                sx=jnp.zeros((_K1, _D1), jnp.float32),
                sxx=jnp.zeros((_K1, _D1), jnp.float32),
            )
            return (acc, jnp.asarray(_rows(i)), *_gmm_params(i),
                    jnp.asarray(float(_ROWS), jnp.float32))

        return Built(fn, fn, fresh)

    return build


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------


def entries() -> list[VerifyEntry]:
    """The whole driver zoo, id-keyed. Order is the goldens' file order —
    append new entries at the family's end and regenerate goldens with
    `python -m tdc_tpu.verify --write-goldens` (review the diff!)."""
    return [
        # ---- 1-D streamed kmeans -------------------------------------
        VerifyEntry(
            id="kmeans_1d.per_batch.stream",
            build=_build_1d_per_batch(_mesh1),
            notes="flat 1-D mesh: the reduce is GSPMD-implicit — empty "
                  "explicit schedule is the pinned property",
        ),
        VerifyEntry(
            id="kmeans_1d.per_batch.hier",
            build=_build_1d_per_batch(_mesh_hier),
            notes="hierarchical (dcn, ici) mesh: explicit two-stage tower",
        ),
        VerifyEntry(
            id="kmeans_1d.per_pass.acc_add",
            build=_build_acc_add("kmeans"),
            donated_leaves=3,
            notes="deferred per-batch add must stay collective-free",
        ),
        VerifyEntry(
            id="kmeans_1d.per_pass.reduce",
            build=_build_reduce("kmeans", None),
        ),
        VerifyEntry(
            id="kmeans_1d.per_pass_int8.reduce",
            build=_build_reduce("kmeans", "int8"),
            notes="per-row scale pmax + payload psums, EF threaded",
        ),
        VerifyEntry(
            id="kmeans_1d.coarse.accumulate",
            build=_build_coarse_accumulate(),
            same_schedule_as="kmeans_1d.per_batch.stream",
            notes="coarse assignment adds no collectives on the 1-D path",
        ),
        # ---- 1-D streamed fuzzy --------------------------------------
        VerifyEntry(
            id="fuzzy_1d.per_pass.acc_add",
            build=_build_acc_add("fuzzy"),
            donated_leaves=3,
        ),
        VerifyEntry(
            id="fuzzy_1d.per_pass.reduce",
            build=_build_reduce("fuzzy", None),
        ),
        # ---- 1-D streamed GMM ----------------------------------------
        VerifyEntry(
            id="gmm_1d.per_batch.hier",
            build=_build_gmm_per_batch_hier(),
        ),
        VerifyEntry(
            id="gmm_1d.per_pass.reduce",
            build=_build_reduce("gmm", None),
        ),
        VerifyEntry(
            id="gmm_1d.per_pass_int8.reduce",
            build=_build_reduce("gmm", "int8"),
        ),
        # ---- resident (hbm) tier -------------------------------------
        VerifyEntry(
            id="kmeans_1d.hbm.per_batch.chunk",
            build=_build_resident("kmeans", False, None),
            donated_leaves=1,
        ),
        VerifyEntry(
            id="kmeans_1d.hbm.per_pass.chunk",
            build=_build_resident("kmeans", True, None),
            donated_leaves=1,
            notes="exactly the one logical per-pass reduce in the while "
                  "body (test_resident's pin, now golden-backed)",
        ),
        VerifyEntry(
            id="kmeans_1d.hbm.per_pass.final_pass",
            build=_build_resident("kmeans", True, None, final_pass=True),
        ),
        VerifyEntry(
            id="kmeans_1d.hbm.per_pass_int8.chunk",
            build=_build_resident("kmeans", True, "int8"),
            donated_leaves=4,
            notes="donated carry = centroids + the 3-leaf EF aux tree",
        ),
        VerifyEntry(
            id="kmeans_1d.hbm.coarse.chunk",
            build=_build_resident("kmeans", False, None, coarse=True),
            donated_leaves=1,
            same_schedule_as="kmeans_1d.hbm.per_batch.chunk",
        ),
        VerifyEntry(
            id="fuzzy_1d.hbm.per_pass.chunk",
            build=_build_resident("fuzzy", True, None),
            donated_leaves=1,
        ),
        VerifyEntry(
            id="kmeans_1d.hbm.bounded.chunk",
            build=_build_bounded_chunk("hamerly"),
            donated_leaves=8,
            notes="centroids + the 7-leaf Hamerly bounds carry donated "
                  "(no upper-bound leaf: the pass always tightens); "
                  "single-device — empty explicit schedule is the pinned "
                  "property (bounds prune FLOPs, never collectives)",
        ),
        VerifyEntry(
            id="kmeans_1d.hbm.bounded_elkan.chunk",
            build=_build_bounded_chunk("elkan"),
            donated_leaves=11,
            same_schedule_as="kmeans_1d.hbm.bounded.chunk",
            notes="adds the per-tile bounds + fixed tile ids to the carry",
        ),
        # ---- K-sharded towers ----------------------------------------
        VerifyEntry(
            id="sharded_k.kmeans.per_batch.exact",
            build=_build_sharded_stats(coarse=False, reduce_data=True),
            notes="2 champion all_gathers (model) + 3 stat psums (data)",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.per_batch.coarse",
            build=_build_sharded_stats(coarse=True, reduce_data=True),
            same_schedule_as="sharded_k.kmeans.per_batch.exact",
            notes="assignment-mode independence: byte-identical schedule",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.per_batch.bounded",
            build=_build_sharded_bounded_stats(),
            same_schedule_as="sharded_k.kmeans.per_batch.exact",
            notes="zero-loss bounded tower: per-shard bound maintenance "
                  "adds NO collectives — byte-identical schedule to exact",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.gather_bf16.exact",
            build=_build_sharded_gather_stats("bf16"),
            same_schedule_as="sharded_k.kmeans.per_batch.exact",
            notes="bf16 champion-mins gather: dtype narrows, collective "
                  "count/order byte-identical to fp32",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.gather_int8.exact",
            build=_build_sharded_gather_stats("int8"),
            same_schedule_as="sharded_k.kmeans.per_batch.exact",
            notes="packed int8 codes + bitcast block scales travel as ONE "
                  "all_gather — schedule identical to fp32",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.gather_int8.coarse",
            build=_build_sharded_gather_stats("int8", coarse=True),
            same_schedule_as="sharded_k.kmeans.gather_int8.exact",
            notes="assignment-mode independence holds under quantized "
                  "gathers too (pad rows decode to exactly 0.0)",
        ),
        VerifyEntry(
            id="sharded_k.finalize.fp32",
            build=_build_sharded_finalize("fp32_sharded"),
            notes="data-axis-sharded centroid finalize: 1 slice "
                  "all_gather (data) + 1 shift pmax (data, model)",
        ),
        VerifyEntry(
            id="sharded_k.finalize.int8",
            build=_build_sharded_finalize("int8"),
            same_schedule_as="sharded_k.finalize.fp32",
            notes="quantized finalize adds the EF residual operand, not "
                  "collectives — schedule identical to fp32_sharded",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.per_pass.acc",
            build=_build_sharded_stats(coarse=False, reduce_data=False),
            notes="champion gathers remain; data-axis psums deferred",
        ),
        VerifyEntry(
            id="sharded_k.kmeans.per_pass.reduce",
            build=_build_sharded_deferred_reduce(),
        ),
        VerifyEntry(
            id="sharded_k.kmeans.per_pass.accumulate",
            build=_build_sharded_deferred_accumulate("kmeans"),
            donated_leaves=3,
        ),
        VerifyEntry(
            id="sharded_k.fuzzy.per_batch",
            build=_build_sharded_fuzzy_stats(reduce_data=True),
        ),
        VerifyEntry(
            id="sharded_k.fuzzy.per_pass.acc",
            build=_build_sharded_fuzzy_stats(reduce_data=False),
        ),
        VerifyEntry(
            id="sharded_k.fuzzy.per_pass.reduce",
            build=_build_sharded_fuzzy_reduce(),
        ),
        VerifyEntry(
            id="sharded_k.fuzzy.per_pass.accumulate",
            build=_build_sharded_deferred_accumulate("fuzzy"),
            donated_leaves=3,
        ),
        VerifyEntry(
            id="sharded_k.gmm.per_batch",
            build=_build_sharded_gmm_stats(),
            notes="distributed logsumexp: model-axis pmax + psum per block",
        ),
    ]


__all__ = ["Built", "VerifyEntry", "entries"]
