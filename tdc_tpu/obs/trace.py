"""Span tracing: nested wall-clock spans at pass/phase granularity,
exported as per-process Chrome-trace-event JSON, plus the per-fit
timeline (`result.timeline`) assembled from the same spans.

Enablement: `$TDC_TRACE=<dir>` in the environment (read at import) or
`trace.configure(dir)` (the CLI's `--trace <dir>`). Disabled — the
default — every entry point is a flag check returning a shared no-op:
no imports, no allocation, no syncs; the streamed drivers' async
dispatch behavior is untouched (the bench-smoke <=1% overhead bar).

Enabled, the contract changes deliberately at phase boundaries where
device truth matters: `trace.sync(x)` runs `timing.hard_sync` (a real
completion fence, not an enqueue ack), so a span that closes over a
sync reads device wall time, not dispatch time. Per-BATCH compute spans
stay dispatch-time (a per-batch fence would serialize the pipeline the
spill tier exists to fill); the per-pass boundary sync is where truth
is re-established.

Export format: Chrome trace events (`"X"` complete events with ts/dur in
microseconds, `"i"` instants, `"M"` metadata), one JSON file per process
(`trace_p<process_index>_<pid>.json`) under the configured directory.
Spans carry the caller's thread id, so the spill ring's producer
threads land on their own tracks and the read/stage/H2D overlap is
visible instead of inferred. Every pass emits a `pass_boundary` instant
— the alignment anchor `python -m tdc_tpu.obs.merge_trace` uses to put
N gang processes on one timeline.

Span names are registered in KNOWN_SPANS (the docs/OBSERVABILITY.md
drift test pins the doc's span table to it), mirroring
testing/faults.KNOWN_POINTS.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
import time

# Every span/instant name the instrumentation emits. Like
# faults.KNOWN_POINTS: the name is an interface for greps and the merge
# tool — add here AND to docs/OBSERVABILITY.md when instrumenting a new
# phase.
KNOWN_SPANS = frozenset({
    "fit",            # whole streamed fit (1-D or K-sharded)
    "pass",           # one accumulation pass over the stream
    "read",           # pulling the next batch off the (possibly ringed) stream
    "stage",          # pad/cast/shard/device_put of one batch
    "compute",        # stats-accumulate dispatch for one batch
    "reduce",         # the per-pass cross-device reduce (deferred mode)
    "shift_check",    # centroid update + shift fetch (device truth boundary)
    "checkpoint",     # one checkpoint save
    "resident_chunk",  # one compiled R-iteration resident dispatch
    "final_pass",     # the end-of-fit reporting pass
    "bounds_init",    # building/placing a bounded fit's per-point carry
    "produce",        # spill-ring producer: read+stage+H2D for one batch
    "ingest_retry",   # instant: one retried read (data/ingest.py)
    "pass_boundary",  # instant: gang alignment anchor, args {"pass": n}
    "spill_cross_pass",  # instant: next-pass batches staged across the
                         # iteration boundary (data/spill.SpillRing)
})

# Span name -> per-fit timeline column. shift_check books into reduce_s:
# it is the per-iteration finalization (update + device-truth fetch), the
# same budget slot the deferred mode's explicit reduce occupies — so the
# per_batch and per_pass timelines stay comparable column-for-column.
_TIMELINE_PHASE = {
    "read": "read_s",
    "stage": "stage_s",
    "compute": "compute_s",
    "reduce": "reduce_s",
    "shift_check": "reduce_s",
    "checkpoint": "ckpt_s",
}

TIMELINE_COLUMNS = (
    "pass", "iters", "batches", "read_s", "stage_s", "compute_s",
    "reduce_s", "ckpt_s", "shift",
)

_MAX_EVENTS_DEFAULT = 1_000_000

_enabled = False
_lock = threading.Lock()
_events: list[dict] = []
_dropped = 0
_dir: str | None = None
_perf_t0 = 0.0
_wall_t0 = 0.0
_max_events = _MAX_EVENTS_DEFAULT
_seen_tids: set[int] = set()
_atexit_registered = False

_tls = threading.local()  # .timeline (per-fit), .pass_n


def enabled() -> bool:
    return _enabled


def configure(trace_dir: str) -> None:
    """Enable tracing; exported JSON lands under `trace_dir` at flush()
    and process exit. Idempotent; re-configuring redirects the output
    directory but keeps already-recorded events."""
    global _enabled, _dir, _perf_t0, _wall_t0, _max_events
    global _atexit_registered
    with _lock:
        _dir = str(trace_dir)
        if not _enabled:
            _perf_t0 = time.perf_counter()
            _wall_t0 = time.time()
            _enabled = True
        try:
            _max_events = int(
                os.environ.get("TDC_TRACE_MAX_EVENTS", _MAX_EVENTS_DEFAULT)
            )
        except ValueError:
            _max_events = _MAX_EVENTS_DEFAULT
        if not _atexit_registered:
            atexit.register(flush)
            _atexit_registered = True


def disable() -> None:
    """Disable and drop recorded state (tests)."""
    global _enabled, _dropped
    with _lock:
        _enabled = False
        _events.clear()
        _seen_tids.clear()
        _dropped = 0


def _now_us() -> float:
    return (time.perf_counter() - _perf_t0) * 1e6


def _record(ev: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) >= _max_events:
            _dropped += 1
            return
        tid = ev["tid"]
        if tid not in _seen_tids:
            _seen_tids.add(tid)
            _events.append({
                "name": "thread_name", "ph": "M", "pid": os.getpid(),
                "tid": tid,
                "args": {"name": threading.current_thread().name},
            })
        _events.append(ev)


class _Span:
    """One live span; records an 'X' complete event on exit (inclusive
    wall time — the trace viewer nests children visually) and books its
    SELF time (inclusive minus nested spans) into the ambient per-fit
    timeline, so an inline-staged batch's stage_s is not double-counted
    inside compute_s. `seconds` (inclusive) is readable after exit (the
    resident loop re-books chunk rows explicitly)."""

    __slots__ = ("name", "args", "_t0", "seconds", "child_seconds")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args
        self.seconds = 0.0
        self.child_seconds = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        stack = getattr(_tls, "spans", None)
        if stack is None:
            stack = _tls.spans = []
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        self.seconds = dt
        stack = getattr(_tls, "spans", None) or []
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # an unwound raise skipped a child's exit
            while stack and stack[-1] is not self:
                stack.pop()
            if stack:
                stack.pop()
        if stack:
            stack[-1].child_seconds += dt
        ev = {
            "name": self.name, "cat": "tdc", "ph": "X",
            "ts": round((self._t0 - _perf_t0) * 1e6, 3),
            "dur": round(dt * 1e6, 3),
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if self.args:
            ev["args"] = self.args
        _record(ev)
        col = _TIMELINE_PHASE.get(self.name)
        if col is not None:
            tl = getattr(_tls, "timeline", None)
            if tl is not None:
                tl.add(col, max(dt - self.child_seconds, 0.0),
                       inc_batches=(self.name == "compute"))
        return False


class _NoopSpan:
    """Shared disabled-path span: __enter__/__exit__ do nothing."""

    __slots__ = ()
    seconds = 0.0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


def span(name: str, **args):
    """Context manager for one wall-clock span. No-op when disabled."""
    if not _enabled:
        return _NOOP
    return _Span(name, args)


def instant(name: str, **args) -> None:
    """One instant event (retries, anchors). No-op when disabled."""
    if not _enabled:
        return
    ev = {
        "name": name, "cat": "tdc", "ph": "i", "s": "p",
        "ts": round(_now_us(), 3),
        "pid": os.getpid(), "tid": threading.get_ident(),
    }
    if args:
        ev["args"] = args
    _record(ev)


def sync(target) -> None:
    """Device-truth fence at a phase boundary: `timing.hard_sync` when
    tracing is enabled, nothing otherwise — the async-dispatch semantics
    of untraced runs are untouched."""
    if not _enabled or target is None:
        return
    from tdc_tpu.utils.timing import hard_sync

    hard_sync(target)


def timed_iter(it, name: str):
    """Wrap an iterator so each __next__ is a span (the 'read' phase).
    Returns `it` unchanged when disabled — zero per-batch overhead."""
    if not _enabled:
        return it

    def gen():
        iterator = iter(it)
        while True:
            with span(name):
                try:
                    item = next(iterator)
                except StopIteration:
                    return
            yield item

    return gen()


# ---------------------------------------------------------------------------
# Per-fit timeline: per-pass rows assembled from the same spans.
# ---------------------------------------------------------------------------


class Timeline:
    """Per-fit aggregation of phase spans into per-pass rows.

    Rows are dicts keyed by TIMELINE_COLUMNS; `pass` is the driver's
    iteration number (0 = the end-of-fit reporting pass), `iters` > 1
    marks a resident chunk row covering several on-device iterations.
    Thread-ambient: spans book into the ACTIVATING thread's timeline
    only (spill producer threads record chrome events on their own
    track; their staging wall time is deliberately not added to the
    consumer's per-pass budget — that double-count is exactly what the
    merged trace view exists to disentangle)."""

    def __init__(self, label: str):
        self.label = label
        self._rows: dict[int, dict] = {}
        self._order: list[int] = []
        self._current = 0

    def begin_pass(self, n: int) -> None:
        n = int(n)
        self._current = n
        if n not in self._rows:
            self._rows[n] = {
                "pass": n, "iters": 1, "batches": 0, "read_s": 0.0,
                "stage_s": 0.0, "compute_s": 0.0, "reduce_s": 0.0,
                "ckpt_s": 0.0, "shift": None,
            }
            self._order.append(n)

    def add(self, col: str, dt: float, inc_batches: bool = False) -> None:
        row = self._rows.get(self._current)
        if row is None:
            self.begin_pass(self._current)
            row = self._rows[self._current]
        row[col] = row[col] + dt
        if inc_batches:
            row["batches"] += 1

    def set_shift(self, n: int, shift) -> None:
        row = self._rows.get(int(n))
        if row is not None and shift is not None:
            row["shift"] = float(shift)

    def add_chunk(self, n_end: int, iters: int, seconds: float,
                  shift) -> None:
        """One resident chunk dispatch = `iters` on-device iterations
        ending at iteration `n_end`, booked as a single compute row."""
        self.begin_pass(n_end)
        row = self._rows[int(n_end)]
        row["iters"] = int(iters)
        row["compute_s"] += float(seconds)
        if shift is not None:
            row["shift"] = float(shift)

    def rows(self) -> list[dict]:
        return [dict(self._rows[n]) for n in self._order]


def begin_fit(label: str, **args):
    """Activate a per-fit Timeline on this thread (None when disabled).
    The matching end_fit() deactivates and returns the rows; an
    exception path leaves the stale timeline ambient until the next
    begin_fit — harmless (phase spans book into a dead object)."""
    if not _enabled:
        return None
    instant("fit", label=label, **args)
    tl = Timeline(label)
    _tls.timeline = tl
    return tl


def end_fit(tl) -> list[dict] | None:
    """Deactivate `tl` and return its per-pass rows (None when tracing
    was off at begin_fit)."""
    if tl is None:
        return None
    if getattr(_tls, "timeline", None) is tl:
        _tls.timeline = None
    return tl.rows()


def begin_pass(n_iter: int) -> None:
    """Open pass `n_iter` on the ambient timeline and emit the gang
    alignment anchor. No-op when disabled."""
    if not _enabled:
        return
    tl = getattr(_tls, "timeline", None)
    if tl is not None:
        tl.begin_pass(n_iter)
    instant("pass_boundary", **{"pass": int(n_iter)})


def timeline_shift(n_iter: int, shift) -> None:
    if not _enabled:
        return
    tl = getattr(_tls, "timeline", None)
    if tl is not None:
        tl.set_shift(n_iter, shift)


def timeline_chunk(n_end: int, iters: int, seconds: float, shift) -> None:
    if not _enabled:
        return
    tl = getattr(_tls, "timeline", None)
    if tl is not None:
        tl.add_chunk(n_end, iters, seconds, shift)


def format_timeline(rows, label: str = "") -> str:
    """Fixed-width table of timeline rows (the CLI's --trace printout)."""
    if not rows:
        return "timeline: (no passes recorded)"
    head = (f"timeline{f' ({label})' if label else ''}:\n"
            "  pass iters batches   read_s  stage_s compute_s reduce_s"
            "   ckpt_s      shift")
    lines = [head]
    for r in rows:
        pname = "final" if r["pass"] == 0 else str(r["pass"])
        shift = "-" if r.get("shift") is None else f"{r['shift']:.3g}"
        lines.append(
            f"  {pname:>4} {r['iters']:>5} {r['batches']:>7} "
            f"{r['read_s']:>8.3f} {r['stage_s']:>8.3f} "
            f"{r['compute_s']:>9.3f} {r['reduce_s']:>8.3f} "
            f"{r['ckpt_s']:>8.3f} {shift:>10}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------


def _process_index():
    try:
        from tdc_tpu.utils.structlog import process_index

        return process_index()
    except Exception:
        return None


def trace_path() -> str | None:
    """The file flush() writes (None while disabled/unconfigured)."""
    if not _enabled or _dir is None:
        return None
    idx = _process_index()
    return os.path.join(
        _dir, f"trace_p{0 if idx is None else int(idx)}_{os.getpid()}.json"
    )


def flush() -> str | None:
    """Write the Chrome-trace JSON (atomic replace); returns the path.
    Safe to call repeatedly — each call rewrites the full event list."""
    path = trace_path()
    if path is None:
        return None
    with _lock:
        events = list(_events)
        dropped = _dropped
    idx = _process_index()
    doc = {
        "traceEvents": [{
            "name": "process_name", "ph": "M", "pid": os.getpid(), "tid": 0,
            "args": {"name": (
                f"tdc p{0 if idx is None else int(idx)} (pid {os.getpid()})"
            )},
        }] + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "pid": os.getpid(),
            "process_index": idx,
            "wall_t0": _wall_t0,
            "dropped_events": dropped,
            "argv": " ".join(sys.argv[:4]),
        },
    }
    os.makedirs(_dir, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


# $TDC_TRACE in the environment enables tracing for any entry point
# (drivers, gang workers, benchmarks) without a flag to thread through.
_env_dir = os.environ.get("TDC_TRACE")
if _env_dir:
    configure(_env_dir)
del _env_dir


__all__ = [
    "KNOWN_SPANS",
    "TIMELINE_COLUMNS",
    "Timeline",
    "begin_fit",
    "begin_pass",
    "configure",
    "disable",
    "enabled",
    "end_fit",
    "flush",
    "format_timeline",
    "instant",
    "span",
    "sync",
    "timed_iter",
    "timeline_chunk",
    "timeline_shift",
    "trace_path",
]
