"""Central metrics registry: counter/gauge/histogram primitives and the
ONE Prometheus text renderer every `tdc_*` series goes through.

Before PR 12 the exposition was ~200 lines of hand-formatted text in
serve/server.py reading five ad-hoc counter globals (GLOBAL_COMMS,
GLOBAL_H2D, GLOBAL_INGEST, GLOBAL_ASSIGN, the online updaters), and the
latency story was a recent-window quantile summary that could not answer
"p999 under load". Now:

- `Registry` owns typed metrics and renders them in registration order;
  `Counter`/`Gauge`/`Histogram` are thread-safe primitives with optional
  labels. `Histogram` is a REAL fixed-bucket Prometheus histogram
  (cumulative `_bucket{le=...}` + `_sum` + `_count`), so p50/p99/p999
  are derivable by any Prometheus stack from the scrape alone.
- `Registry.callback(...)` registers a render-time value source — how
  the pre-existing process-wide counters (parallel/reduce.GLOBAL_COMMS,
  data/spill.GLOBAL_H2D, data/ingest.GLOBAL_INGEST,
  ops/subk.GLOBAL_ASSIGN, serve/online ledgers) publish through the
  registry without moving their (already thread-safe, already tested)
  state. The per-fit report shapes (`result.comms`/`h2d`/`ingest`/
  `assign`) are untouched.
- `CATALOG` is the authoritative name registry: every `tdc_*` family
  this repo exports, with type and help text. Registering a `tdc_*`
  name that is not in the catalog raises — the discipline the TDC009
  lint rule (metric-name drift) and the docs/OBSERVABILITY.md drift
  test are anchored on.

Stdlib-only; importable from anywhere (including producer threads and
the lint-adjacent tests) without touching jax.
"""

from __future__ import annotations

import re
import threading

# ---------------------------------------------------------------------------
# The metric-name catalog. Keys are FAMILY names (a histogram family
# `x` renders series `x_bucket`/`x_sum`/`x_count`). TDC009 cross-checks
# every literal `tdc_*` reference in the tree against these keys, and
# the docs/OBSERVABILITY.md drift test pins the doc's metrics table to
# them — add here first, then register, then document.
# ---------------------------------------------------------------------------

CATALOG = {
    # serve request layer (serve/server.py)
    "tdc_serve_requests_total": (
        "counter", "Requests by endpoint and status."),
    "tdc_serve_batches_total": (
        "counter", "Coalesced device batches executed."),
    "tdc_serve_batched_requests_total": (
        "counter", "Requests that went through the batcher."),
    "tdc_serve_rejected_total": (
        "counter", "Requests rejected with overloaded backpressure."),
    "tdc_serve_engine_rows_total": (
        "counter", "Real data rows computed on device."),
    "tdc_serve_engine_padded_rows_total": (
        "counter", "Bucket-padding rows computed on device."),
    "tdc_serve_engine_compiles_total": (
        "counter", "jit traces paid (bucket warmup)."),
    "tdc_serve_engine_device_ms_total": (
        "counter", "Device compute milliseconds."),
    # whole-engine LRU (serve/engine.py, PR 16)
    "tdc_serve_engine_evictions_total": (
        "counter", "Compiled engines evicted by the engine LRU under "
                   "budget pressure (serve/engine.py)."),
    "tdc_serve_engine_cached": (
        "gauge", "Compiled (model, generation) engines resident in the "
                 "engine LRU."),
    "tdc_serve_queue_wait_ms_total": (
        "counter", "Milliseconds requests spent queued before dispatch."),
    "tdc_serve_models": (
        "gauge", "Models currently registered."),
    "tdc_serve_draining": (
        "gauge", "1 while the server is draining (rejecting new work, "
                 "flushing in-flight batches)."),
    # admission governor / load shedding (serve/governor.py, PR 15)
    "tdc_serve_shed_total": (
        "counter", "Requests shed by the admission governor before any "
                   "work was queued, by model and trigger reason."),
    "tdc_serve_inflight": (
        "gauge", "Predict-family requests currently in flight (admitted "
                 "and not yet answered)."),
    "tdc_serve_admission_state": (
        "gauge", "Admission state: 0 admitting, 1 shedding, 2 draining."),
    "tdc_serve_offered_rps": (
        "gauge", "Offered request rate (admitted + shed) over the "
                 "governor's last evaluation window."),
    # serve latency histograms (PR 12: real fixed-bucket histograms
    # replacing the recent-window quantile summary)
    "tdc_serve_latency_ms": (
        "histogram", "End-to-end request latency per endpoint."),
    "tdc_serve_queue_wait_ms": (
        "histogram", "Per-request queue wait before batch dispatch."),
    "tdc_serve_engine_batch_device_ms": (
        "histogram", "Per-batch device compute milliseconds."),
    # scrape health (standard idioms)
    "tdc_up": (
        "gauge", "1 while the serve process is scrapable."),
    "tdc_build_info": (
        "gauge", "Build metadata as labels; value is always 1."),
    # cross-device stats-reduce accounting (parallel/reduce.py)
    "tdc_comms_stats_reduces_total": (
        "counter", "Cross-device stats reduces issued (parallel/reduce)."),
    "tdc_comms_stats_logical_bytes_total": (
        "counter", "Logical payload bytes moved by stats reduces and "
                   "model-axis gathers (cross-axis total)."),
    "tdc_comms_stats_gathers_total": (
        "counter", "Cross-device all_gathers issued (champion + sharded "
                   "finalize; parallel/gather)."),
    "tdc_comms_stats_axis_bytes_total": (
        "counter", "Logical payload bytes per mesh axis "
                   "(axis=\"data\"|\"model\"; data-axis stats reduces vs "
                   "model-axis champion/finalize gathers)."),
    # spill-tier H2D prefetch ring (data/spill.py)
    "tdc_h2d_bytes_total": (
        "counter", "Logical host->device bytes staged by the spill "
                   "prefetch ring (data/spill.py)."),
    "tdc_h2d_batches_total": (
        "counter", "Batches staged through the spill prefetch ring."),
    "tdc_h2d_copy_stall_seconds_total": (
        "counter", "Seconds spill-fit consumers stalled waiting on H2D "
                   "staging (copy time the overlap failed to hide)."),
    "tdc_h2d_prefetch_depth": (
        "gauge", "Deepest spill prefetch-ring fill observed."),
    "tdc_h2d_cross_pass_batches_total": (
        "counter", "Batches the pass-persistent spill ring staged across "
                   "iteration boundaries (next-pass prefetch while the "
                   "shift check drains)."),
    # object-store data plane (data/store.py)
    "tdc_store_reads_total": (
        "counter", "Successful ranged blob reads against object-store "
                   "backends (data/store.py)."),
    "tdc_store_retries_total": (
        "counter", "Failed store read attempts (each becomes an ingest "
                   "retry or an abandoned read)."),
    "tdc_store_bytes_total": (
        "counter", "Blob bytes fetched from object-store backends."),
    "tdc_store_stall_seconds_total": (
        "counter", "Wall-clock seconds burned inside failed store read "
                   "attempts (timeouts, 5xx round trips, resets)."),
    # hardened ingest (data/ingest.py)
    "tdc_ingest_retries_total": (
        "counter", "Stream read attempts retried after transient failures "
                   "(data/ingest.py)."),
    "tdc_ingest_read_failures_total": (
        "counter", "Stream reads abandoned: permanent classification or "
                   "retries/deadline exhausted."),
    "tdc_ingest_quarantined_batches_total": (
        "counter", "Batches quarantined (zero mass) by the ingest "
                   "integrity screen."),
    "tdc_ingest_quarantined_rows_total": (
        "counter", "Rows held by quarantined batches."),
    "tdc_ingest_crc_failures_total": (
        "counter", "Quarantines caused by CRC sidecar mismatches "
                   "(corrupt-on-disk)."),
    # sub-linear assignment (ops/subk.py)
    "tdc_assign_tiles_probed_total": (
        "counter", "Centroid tiles scanned by coarse-assignment refine "
                   "steps (ops/subk.py)."),
    "tdc_assign_tiles_total": (
        "counter", "Centroid tiles an exact all-K scan would have touched "
                   "across the same refine steps."),
    "tdc_assign_pruned_fraction": (
        "gauge", "Fraction of centroid tiles pruned by coarse assignment "
                 "(1 - probed/total; 0 when no coarse fit ran)."),
    # serve-time coarse predict (serve/engine.py coarse route)
    "tdc_predict_tiles_probed_total": (
        "counter", "Centroid tiles scanned by the compiled coarse-predict "
                   "route (serve/engine.py)."),
    "tdc_predict_tiles_total": (
        "counter", "Centroid tiles an exact all-K predict would have "
                   "touched across the same requests."),
    "tdc_predict_pruned_fraction": (
        "gauge", "Fraction of centroid tiles serve-time coarse predict "
                 "pruned (1 - probed/total; 0 when no coarse predict "
                 "ran)."),
    # zero-loss bounded assignment (ops/bounds.py)
    "tdc_bounds_dist_evals_total": (
        "counter", "Point-centroid distance evaluations performed by "
                   "bounded (Elkan/Hamerly) assignment (ops/bounds.py)."),
    "tdc_bounds_dist_evals_exact_total": (
        "counter", "Distance evaluations the exact all-K path would have "
                   "performed across the same bounded passes."),
    "tdc_bounds_pruned_fraction": (
        "gauge", "Fraction of exact-path distance evaluations the bounds "
                 "skipped (1 - done/exact; 0 when no bounded fit ran)."),
    # per-model registry state (serve/registry.py)
    "tdc_model_generation": (
        "gauge", "Monotonic reload generation per model."),
    "tdc_model_generation_age_seconds": (
        "gauge", "Seconds since the serving generation was loaded."),
    # online-update pipeline (serve/online.py)
    "tdc_online_quarantined_batches_total": (
        "counter", "serve/online updater metric."),
    "tdc_online_observed_batches_total": (
        "counter", "serve/online updater metric."),
    "tdc_online_folds_total": (
        "counter", "serve/online updater metric."),
    "tdc_online_publishes_total": (
        "counter", "serve/online updater metric."),
    "tdc_online_rejected_candidates_total": (
        "counter", "serve/online updater metric."),
    "tdc_online_rollbacks_total": (
        "counter", "serve/online updater metric."),
    "tdc_online_pending_rows": (
        "gauge", "serve/online updater metric."),
    "tdc_online_holdback_rows": (
        "gauge", "serve/online updater metric."),
    "tdc_online_pinned": (
        "gauge", "serve/online updater metric."),
    "tdc_online_live_inertia_per_point": (
        "gauge", "serve/online updater metric."),
    "tdc_online_candidate_inertia_per_point": (
        "gauge", "serve/online updater metric."),
    "tdc_online_window_sse_per_row": (
        "gauge", "serve/online updater metric."),
    "tdc_online_assignment_churn": (
        "gauge", "serve/online updater metric."),
    # serve fleet: readiness-routing proxy + autoscaler (tdc_tpu/fleet/,
    # PR 16). Exported by the ROUTER's registry, not the replicas'.
    "tdc_fleet_replicas": (
        "gauge", "Fleet replicas by lifecycle state (starting, ready, "
                 "not_ready, draining, dead)."),
    "tdc_fleet_routed_total": (
        "counter", "Requests the router forwarded, by replica and outcome "
                   "(ok, shed, backpressure, drain, error)."),
    "tdc_fleet_unrouted_total": (
        "counter", "Requests answered 503 at the fleet level because no "
                   "replica was ready."),
    "tdc_fleet_failovers_total": (
        "counter", "Routed requests retried on a second replica after a "
                   "shed or connect error."),
    "tdc_fleet_scale_events_total": (
        "counter", "Autoscaler actions by direction (up, down, replace)."),
    # router data plane (fleet/pool.py + the pooled/balanced router,
    # PR 20). Exported by the router's registry.
    "tdc_fleet_pool_checkouts_total": (
        "counter", "Connections checked out of the router's keep-alive "
                   "pool (one per forwarded request attempt)."),
    "tdc_fleet_pool_reuses_total": (
        "counter", "Pool checkouts satisfied by an idle kept-alive "
                   "socket instead of a fresh dial."),
    "tdc_fleet_pool_discards_total": (
        "counter", "Pooled sockets closed: transport failure, replica "
                   "left READY / generation restart, or pool overflow."),
    "tdc_fleet_balance_decisions_total": (
        "counter", "Router replica picks by balancing strategy "
                   "(p2c, rr)."),
    "tdc_fleet_router_rps": (
        "gauge", "Requests the router forwarded per second over its "
                 "recent view window."),
}

# Fixed buckets for the serve latency/queue-wait/device-ms histograms, in
# milliseconds. Wide enough that p999 under overload still lands inside a
# finite bucket on the CPU CI box, fine enough that p50 of a sub-ms warm
# predict is not crushed into one bucket.
LATENCY_MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "histogram")


def _fmt(v) -> str:
    """Prometheus sample value: ints render bare ('3'), floats via str
    ('0.0', '12.5') — byte-identical to the pre-registry hand renderer,
    which interpolated the same Python values into f-strings."""
    if isinstance(v, bool):
        return str(int(v))
    return str(v)


def escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, quote, newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _label_str(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{escape_label_value(v)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    """Base: one family, optional labels; children keyed by label values."""

    typ = "untyped"

    def __init__(self, name: str, help_: str, labelnames=()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r} on {name}")
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        if not self.labelnames:
            self._children[()] = self._new_child()

    def _new_child(self):
        raise NotImplementedError

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}"
            )
        key = tuple(str(kv[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled ({self.labelnames}); use .labels()"
            )
        return self._children[()]

    def samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._children.items())
        out = []
        for key, child in items:
            out.extend(child.render(self.name,
                                    _label_str(self.labelnames, key),
                                    self.labelnames, key))
        return out


class _CounterChild:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount

    def render(self, name, labels, _ln, _lv):
        return [f"{name}{labels} {_fmt(self.value)}"]


class Counter(_Metric):
    typ = "counter"

    def _new_child(self):
        return _CounterChild()

    def inc(self, amount=1):
        self._default().inc(amount)

    @property
    def value(self):
        return self._default().value


class _GaugeChild(_CounterChild):
    def set(self, v):
        with self._lock:
            self.value = v

    def inc(self, amount=1):
        with self._lock:
            self.value += amount


class Gauge(_Metric):
    typ = "gauge"

    def _new_child(self):
        return _GaugeChild()

    def set(self, v):
        self._default().set(v)

    def inc(self, amount=1):
        self._default().inc(amount)

    @property
    def value(self):
        return self._default().value


class _HistogramChild:
    def __init__(self, buckets):
        self._lock = threading.Lock()
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        v = float(v)
        with self._lock:
            i = 0
            for i, ub in enumerate(self.buckets):  # noqa: B007
                if v <= ub:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def render(self, name, labels, labelnames, labelvalues):
        out = []
        cum = 0
        with self._lock:
            counts = list(self.counts)
            total, s = self.count, self.sum
        for ub, n in zip(self.buckets, counts):
            cum += n
            le = _label_str(labelnames + ("le",), labelvalues + (_fmt(ub),))
            out.append(f"{name}_bucket{le} {cum}")
        le = _label_str(labelnames + ("le",), labelvalues + ("+Inf",))
        out.append(f"{name}_bucket{le} {total}")
        out.append(f"{name}_sum{labels} {_fmt(round(s, 6))}")
        out.append(f"{name}_count{labels} {total}")
        return out


class Histogram(_Metric):
    typ = "histogram"

    def __init__(self, name, help_, buckets, labelnames=()):
        buckets = tuple(float(b) for b in buckets)
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(f"{name}: buckets must be strictly increasing")
        if not buckets:
            raise ValueError(f"{name}: at least one finite bucket required")
        self.buckets = buckets
        super().__init__(name, help_, labelnames)

    def _new_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v):
        self._default().observe(v)

    def aggregate(self) -> tuple[tuple[float, ...], list[int]]:
        """(finite upper bounds, cumulative counts incl. +Inf) summed over
        every labeled child — the same numbers a scrape of this family
        would yield, for in-process consumers (the serve governor's
        recent-p99 signal) that must see what the scrape sees."""
        with self._lock:
            children = list(self._children.values())
        per_bucket = [0] * (len(self.buckets) + 1)
        for child in children:
            with child._lock:
                for i, n in enumerate(child.counts):
                    per_bucket[i] += n
        cum, out = 0, []
        for n in per_bucket:
            cum += n
            out.append(cum)
        return self.buckets, out


class _Callback:
    """Render-time value source: fn() -> scalar, or -> iterable of
    (labels_dict_or_None, value) rows. How the pre-existing counter
    globals and per-model/online stats publish through the registry
    without relocating their state."""

    def __init__(self, name, typ, help_, fn):
        self.name = name
        self.typ = typ
        self.help = help_
        self.fn = fn

    def samples(self) -> list[str]:
        got = self.fn()
        if isinstance(got, (int, float)):
            return [f"{self.name} {_fmt(got)}"]
        out = []
        for labels, value in got:
            if labels:
                ln = tuple(labels)
                ls = _label_str(ln, tuple(labels[n] for n in ln))
            else:
                ls = ""
            out.append(f"{self.name}{ls} {_fmt(value)}")
        return out


class Registry:
    """Ordered collection of metrics with the one text renderer.

    Rendering order is registration order (the serve endpoint registers
    in the historical exposition order, keeping the payload diffable
    against pre-registry scrapes). `tdc_*` names must be in CATALOG.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _resolve(self, name, typ, help_):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        cat = CATALOG.get(name)
        if name.startswith("tdc_") and cat is None:
            raise ValueError(
                f"{name!r} is not in obs/metrics.CATALOG — every tdc_* "
                "family must be declared there first (TDC009 and the "
                "docs drift test pin the catalog)"
            )
        if cat is not None:
            if typ is not None and typ != cat[0]:
                raise ValueError(
                    f"{name}: type {typ!r} != catalog type {cat[0]!r}"
                )
            typ = cat[0]
            help_ = help_ or cat[1]
        if typ not in _TYPES:
            raise ValueError(f"{name}: unknown metric type {typ!r}")
        return typ, (help_ or name)

    def _add(self, metric):
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric
        return metric

    def _get_or_make(self, name, typ, help_, factory):
        typ, help_ = self._resolve(name, typ, help_)
        with self._lock:
            existing = self._metrics.get(name)
        if existing is not None:
            if existing.typ != typ:
                raise ValueError(
                    f"{name} already registered as {existing.typ}, not {typ}"
                )
            return existing
        return self._add(factory(typ, help_))

    def counter(self, name, help_=None, labelnames=()) -> Counter:
        return self._get_or_make(
            name, "counter", help_,
            lambda typ, h: Counter(name, h, labelnames))

    def gauge(self, name, help_=None, labelnames=()) -> Gauge:
        return self._get_or_make(
            name, "gauge", help_,
            lambda typ, h: Gauge(name, h, labelnames))

    def histogram(self, name, buckets=LATENCY_MS_BUCKETS, help_=None,
                  labelnames=()) -> Histogram:
        return self._get_or_make(
            name, "histogram", help_,
            lambda typ, h: Histogram(name, h, buckets, labelnames))

    def callback(self, name, fn, typ=None, help_=None) -> None:
        """Register a render-time value source (see _Callback)."""
        typ, help_ = self._resolve(name, typ, help_)
        if typ == "histogram":
            raise ValueError(
                f"{name}: histogram families need a real Histogram "
                "(cumulative bucket state), not a callback"
            )
        self._add(_Callback(name, typ, help_, fn))

    def names(self) -> list[str]:
        with self._lock:
            return list(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: list[str] = []
        for m in metrics:
            samples = m.samples()
            if not samples and isinstance(m, _Callback):
                # A row-valued callback with nothing to report (e.g. no
                # models registered) still announces the family: HELP/
                # TYPE with zero samples is valid and keeps the family
                # discoverable.
                pass
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.typ}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# Scrape-derived quantiles. The load harness (obs/loadgen.py), the serving
# benchmarks, and the admission governor all report percentiles through
# quantile_from_buckets over histogram bucket counts — the SAME numbers a
# Prometheus stack derives from the scrape — so the committed latency
# curves prove the scrape is sufficient for SLO monitoring instead of
# reporting from a private client-side window that production would not
# have.
# ---------------------------------------------------------------------------


def quantile_from_buckets(q, uppers, cum_counts) -> float:
    """The q-quantile (0 <= q <= 1) of a fixed-bucket histogram, from its
    finite upper bounds and CUMULATIVE counts (last entry = the +Inf
    bucket, i.e. the total count) — `histogram_quantile` semantics:
    monotone linear interpolation within the bucket the rank lands in,
    a rank landing in the +Inf bucket reports the highest finite bound
    (the scrape cannot resolve beyond it), and an empty histogram is NaN.

    Raises ValueError on malformed input (shape mismatch, decreasing
    cumulative counts, q outside [0, 1]) rather than interpolating
    garbage — a scrape delta that went backwards means a counter reset
    mid-window and the window must be re-anchored, not averaged over.
    """
    uppers = [float(u) for u in uppers]
    cum = [float(c) for c in cum_counts]
    if not 0.0 <= float(q) <= 1.0:
        raise ValueError(f"quantile q={q} outside [0, 1]")
    if len(cum) != len(uppers) + 1:
        raise ValueError(
            f"{len(uppers)} finite buckets need {len(uppers) + 1} "
            f"cumulative counts (incl. +Inf), got {len(cum)}"
        )
    if any(b < a for a, b in zip(cum, cum[1:])):
        raise ValueError(f"cumulative counts not monotone: {cum}")
    if any(c < 0 for c in cum):
        raise ValueError(f"negative cumulative count: {cum}")
    total = cum[-1]
    if total == 0:
        return float("nan")
    rank = float(q) * total
    i = 0
    while cum[i] < rank:
        i += 1
    if i == len(uppers):  # the +Inf bucket
        return uppers[-1] if uppers else float("nan")
    lower = uppers[i - 1] if i > 0 else 0.0
    prev = cum[i - 1] if i > 0 else 0.0
    in_bucket = cum[i] - prev
    if in_bucket <= 0:
        return lower
    return lower + (uppers[i] - lower) * (rank - prev) / in_bucket


_SCRAPE_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(.*)\})?"
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|\+?Inf|NaN))$"
)
_SCRAPE_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label_value(v: str) -> str:
    """Exact inverse of escape_label_value. A sequential scan, not
    chained str.replace: replacing '\\n' before '\\\\' would corrupt a
    literal backslash-then-n ('a\\nb' escapes to 'a\\\\nb', which must
    unescape to backslash + 'n', not a newline)."""
    out = []
    i = 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def parse_scrape(text):
    """Prometheus text exposition -> list of (name, labels_dict, value)
    sample rows — the inverse of Registry.render, so harnesses and tests
    read percentiles/counters off the scrape exactly as a monitoring
    stack would. Comment/HELP/TYPE lines are skipped; malformed sample
    lines raise (a scrape this module rendered always parses)."""
    out = []
    for ln in text.splitlines():
        if not ln.strip() or ln.startswith("#"):
            continue
        m = _SCRAPE_SAMPLE_RE.match(ln)
        if m is None:
            raise ValueError(f"unparseable scrape line: {ln!r}")
        labels = {}
        if m.group(2) is not None:
            labels = {
                k: _unescape_label_value(v)
                for k, v in _SCRAPE_LABEL_RE.findall(m.group(2))
            }
        out.append((m.group(1), labels, float(m.group(3))))
    return out


def scrape_counter(text, family, match=None) -> float:
    """Sum of a counter/gauge family's samples whose labels include every
    (k, v) in `match` (None/{} = all series). 0.0 when nothing matches."""
    match = match or {}
    total = 0.0
    for name, labels, value in parse_scrape(text):
        if name != family:
            continue
        if all(labels.get(k) == str(v) for k, v in match.items()):
            total += value
    return total


def scrape_histogram(text, family, match=None):
    """Aggregate a histogram family off a scrape: returns (uppers,
    cum_counts) summed across every `<family>_bucket` series whose labels
    include `match` (cumulative counts sum to cumulative counts), or None
    when no series matches. Feed straight into quantile_from_buckets —
    or difference two scrapes' cum_counts for a windowed quantile."""
    match = match or {}
    by_le: dict[float, float] = {}
    for name, labels, value in parse_scrape(text):
        if name != f"{family}_bucket" or "le" not in labels:
            continue
        if not all(labels.get(k) == str(v) for k, v in match.items()):
            continue
        le = float(labels["le"])
        by_le[le] = by_le.get(le, 0.0) + value
    if not by_le:
        return None
    les = sorted(by_le)
    if les[-1] != float("inf"):
        raise ValueError(f"{family}: scrape has no +Inf bucket")
    uppers = tuple(le for le in les if le != float("inf"))
    cum = [int(by_le[le]) for le in les]
    return uppers, cum


def scrape_quantile(text, family, q, match=None, *, baseline=None) -> float:
    """q-quantile of a histogram family read off a scrape; `baseline` (an
    earlier scrape of the same endpoint) windows the quantile to the
    observations between the two scrapes. NaN when the window is empty."""
    got = scrape_histogram(text, family, match)
    if got is None:
        return float("nan")
    uppers, cum = got
    if baseline is not None:
        base = scrape_histogram(baseline, family, match)
        if base is not None:
            b_uppers, b_cum = base
            if b_uppers != uppers:
                raise ValueError(
                    f"{family}: bucket bounds changed between scrapes"
                )
            cum = [a - b for a, b in zip(cum, b_cum)]
    return quantile_from_buckets(q, uppers, cum)


__all__ = [
    "CATALOG",
    "Counter",
    "Gauge",
    "Histogram",
    "LATENCY_MS_BUCKETS",
    "Registry",
    "escape_label_value",
    "parse_scrape",
    "quantile_from_buckets",
    "scrape_counter",
    "scrape_histogram",
    "scrape_quantile",
]
