"""OPEN-loop load generation for the serving tier (the SLO observatory's
traffic half; `benchmarks/bench_load.py` is the harness around it).

`benchmarks/serve_latency.py`'s closed-loop clients wait for each
response before sending the next request — under overload they slow down
WITH the server, so offered load self-throttles to capacity and the tail
the SLO cares about is never generated (coordinated omission). This
module is the other discipline: requests fire on a PRECOMPUTED Poisson
arrival schedule regardless of completion, so offered load is an input,
not an emergent property, and driving the schedule past capacity is how
the overload path gets measured instead of assumed.

Three pieces:

- **Shape programs** (`make_shape`): offered-RPS-over-time profiles —
  `constant`, `step` (capacity-planning ramp), `spike` (the 2x-overload
  contract cell + recovery), `diurnal` (the traffic claim's daily
  curve). The schedule is drawn once up front (`poisson_schedule`) with
  a seeded RNG: deterministic, and provably independent of how the
  target responds.
- **Targets**: `InprocTarget` drives a `ServeApp.request()` directly
  (the CPU-CI path — same admission/batching/engine code as HTTP,
  minus the socket); `HttpTarget` drives a live endpoint. Both expose
  `scrape()` because the report's percentiles come from `/metrics`
  bucket deltas (`obs.metrics.scrape_quantile`), NOT from the client's
  own stopwatch — the harness proves the scrape is sufficient for SLO
  monitoring. The client-side window is kept only as a cross-check.
- **`run_open_loop`**: fires the schedule from a thread pool, classifies
  every outcome (ok / shed / backpressure / drain / error), counts late
  fires (scheduler fell behind — the open-loop guarantee degrading,
  reported rather than hidden) and HUNG requests (fired but unresolved
  past the deadline — the zero-hang contract's denominator).

Stdlib-only, like the rest of `obs/`: points are plain nested lists, so
no numpy/jax import is needed to generate traffic.
"""

from __future__ import annotations

import concurrent.futures
import json
import math
import random
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field

# ---------------------------------------------------------------------------
# Shape programs
# ---------------------------------------------------------------------------


def make_shape(kind: str, *, base_rps: float, peak_rps: float | None = None,
               duration_s: float, at_s: float | None = None,
               len_s: float | None = None, period_s: float | None = None):
    """rps(t) callable over [0, duration_s).

    - constant: base_rps throughout
    - step:     base_rps until `at_s` (default duration/3), then peak_rps
    - spike:    base_rps except [at_s, at_s + len_s) at peak_rps
                (defaults: middle third) — the recovery window after the
                spike is part of the program, not a separate run
    - diurnal:  sinusoid from base_rps up to peak_rps and back, period
                `period_s` (default = duration_s: one "day" per run)
    """
    if kind not in ("constant", "step", "spike", "diurnal"):
        raise ValueError(f"unknown shape {kind!r}; "
                         "have constant|step|spike|diurnal")
    if base_rps <= 0:
        raise ValueError(f"base_rps={base_rps} must be > 0")
    if kind == "constant":
        return lambda t: base_rps
    if peak_rps is None:
        raise ValueError(f"shape {kind!r} needs peak_rps")
    if kind == "step":
        t_step = duration_s / 3.0 if at_s is None else at_s
        return lambda t: base_rps if t < t_step else peak_rps
    if kind == "spike":
        t0 = duration_s / 3.0 if at_s is None else at_s
        t1 = t0 + (duration_s / 3.0 if len_s is None else len_s)
        return lambda t: peak_rps if t0 <= t < t1 else base_rps
    period = duration_s if period_s is None else period_s
    amp = (peak_rps - base_rps) / 2.0
    mid = base_rps + amp
    return lambda t: mid - amp * math.cos(2.0 * math.pi * t / period)


def poisson_schedule(rps_fn, duration_s: float, *, seed: int = 0,
                     max_arrivals: int = 1_000_000) -> list[float]:
    """Arrival times in [0, duration_s) from a piecewise-evaluated Poisson
    process with instantaneous rate rps_fn(t). Drawn entirely up front
    from a seeded RNG: the schedule cannot react to the target (the
    open-loop property, by construction)."""
    rng = random.Random(seed)
    out: list[float] = []
    t = 0.0
    while t < duration_s and len(out) < max_arrivals:
        rate = max(float(rps_fn(t)), 1e-9)
        t += rng.expovariate(rate)
        if t < duration_s:
            out.append(t)
    return out


# ---------------------------------------------------------------------------
# Targets
# ---------------------------------------------------------------------------


class InprocTarget:
    """Drive a started ServeApp in-process: same admission governor,
    batcher, and engine as the HTTP path, minus the socket."""

    def __init__(self, app, endpoint: str = "predict"):
        self.app = app
        self.endpoint = endpoint

    def __call__(self, model_id: str, points) -> tuple[int, str]:
        status, body = self.app.request(
            self.endpoint, {"model": model_id, "points": points}
        )
        return status, _classify(status, body)

    def scrape(self) -> str:
        return self.app.metrics_text()


class HttpTarget:
    """Drive a live serve endpoint over HTTP (base_url has no trailing
    path; scrape() reads GET /metrics)."""

    def __init__(self, base_url: str, endpoint: str = "predict",
                 timeout: float = 35.0):
        self.base_url = base_url.rstrip("/")
        self.endpoint = endpoint
        self.timeout = timeout

    def __call__(self, model_id: str, points) -> tuple[int, str]:
        req = urllib.request.Request(
            f"{self.base_url}/{self.endpoint}",
            data=json.dumps({"model": model_id, "points": points}).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, "ok"
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read())
            except ValueError:
                body = {}
            if not isinstance(body, dict):  # proxy/string error bodies
                body = {}
            return e.code, _classify(e.code, body)
        except OSError:
            return 599, "error"

    def scrape(self) -> str:
        with urllib.request.urlopen(
            f"{self.base_url}/metrics", timeout=self.timeout
        ) as r:
            return r.read().decode()


def _classify(status: int, body: dict) -> str:
    """Outcome class off the response's explicit `reason` field (PR 15
    disambiguated the 503s; "overloaded"/"draining" errors without a
    reason are pre-PR-15 payload shapes)."""
    if status == 200:
        return "ok"
    reason = body.get("reason")
    if reason in ("shed", "backpressure", "drain"):
        return reason
    if body.get("error") == "draining":
        return "drain"
    if body.get("error") == "overloaded":
        return "backpressure"
    return "error"


# ---------------------------------------------------------------------------
# The open-loop driver
# ---------------------------------------------------------------------------

_OUTCOME_KEYS = ("ok", "shed", "backpressure", "drain", "error")


@dataclass
class LoadReport:
    """One open-loop run's accounting. `offered` counts the schedule,
    `fired` what was actually launched (== offered unless the run was
    cancelled), `hung` requests that never resolved within the deadline
    — the zero-hang contract counts them directly. `client_ms` is the
    client-side latency window for CROSS-CHECKING the scrape-derived
    percentiles, never for reporting them."""

    offered: int = 0
    fired: int = 0
    completed: int = 0
    hung: int = 0
    late_fires: int = 0
    duration_s: float = 0.0
    counts: dict = field(default_factory=lambda: dict.fromkeys(
        _OUTCOME_KEYS, 0))
    by_model: dict = field(default_factory=dict)  # model -> outcome counts
    client_ms: list = field(default_factory=list)  # ok requests only

    @property
    def offered_rps(self) -> float:
        return self.offered / self.duration_s if self.duration_s else 0.0

    @property
    def goodput_rps(self) -> float:
        return (self.counts["ok"] / self.duration_s
                if self.duration_s else 0.0)

    def client_percentile(self, q: float) -> float:
        """Cross-check percentile from the client-side window (nearest-
        rank). NaN when no request succeeded."""
        if not self.client_ms:
            return float("nan")
        xs = sorted(self.client_ms)
        i = min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))
        return xs[i]


def gauss_points(rng: random.Random, rows: int, d: int) -> list[list[float]]:
    """Plain-list standard-normal request payload (no numpy in obs/)."""
    return [[rng.gauss(0.0, 1.0) for _ in range(d)] for _ in range(rows)]


def run_open_loop(target, shape_fn, duration_s: float, *, d: int,
                  model_mix: dict[str, float], seed: int = 0,
                  rows_choices=(2, 4, 8, 16), max_workers: int = 256,
                  late_slack_s: float = 0.05,
                  hang_timeout_s: float = 60.0) -> LoadReport:
    """Fire one open-loop schedule at `target` and account for every
    request. `model_mix` maps model id -> weight (each arrival draws a
    model independently — the multi-tenant mix is part of the schedule,
    so a flooded tenant's arrivals never depend on a light tenant's
    completions)."""
    if not model_mix:
        raise ValueError("model_mix must name at least one model")
    arrivals = poisson_schedule(shape_fn, duration_s, seed=seed)
    rng = random.Random(seed + 1)
    models = list(model_mix)
    weights = [float(model_mix[m]) for m in models]
    plan = [
        (t, rng.choices(models, weights)[0], rng.choice(list(rows_choices)))
        for t in arrivals
    ]

    rep = LoadReport(offered=len(plan), duration_s=duration_s)
    lock = threading.Lock()

    # Payload RNGs are per-thread: random.Random is lock-protected but
    # contended; thread-local instances keep the generator off the
    # critical path without sacrificing determinism of the SCHEDULE
    # (already drawn above).
    tls = threading.local()

    def rng_local() -> random.Random:
        r = getattr(tls, "rng", None)
        if r is None:
            r = tls.rng = random.Random(
                seed + 2 + threading.get_ident() % 9973
            )
        return r

    closed = False  # set once the report is returned: late completions
    # of requests already counted as HUNG are discarded, so the caller
    # never sees the report mutate under it (or a request double-counted
    # as both hung and ok).

    def one(model_id: str, rows: int):
        t0 = time.perf_counter()
        try:
            status, outcome = target(
                model_id, gauss_points(rng_local(), rows, d))
        except Exception:
            # Account-for-every-request contract: a target that RAISES
            # (transport bug, malformed response) is an "error" outcome,
            # never a silently dropped future.
            status, outcome = 599, "error"
        ms = (time.perf_counter() - t0) * 1e3
        with lock:
            if closed:
                return status
            rep.completed += 1
            rep.counts[outcome] = rep.counts.get(outcome, 0) + 1
            per = rep.by_model.setdefault(
                model_id, dict.fromkeys(_OUTCOME_KEYS, 0))
            per[outcome] = per.get(outcome, 0) + 1
            if outcome == "ok":
                rep.client_ms.append(ms)
        return status

    pool = concurrent.futures.ThreadPoolExecutor(max_workers=max_workers)
    futures = []
    t_start = time.perf_counter()
    try:
        for t_due, model_id, rows in plan:
            lag = (time.perf_counter() - t_start) - t_due
            if lag < 0:
                time.sleep(-lag)
            elif lag > late_slack_s:
                rep.late_fires += 1  # fired anyway: open loop never skips
            futures.append(pool.submit(one, model_id, rows))
            rep.fired += 1
        done, not_done = concurrent.futures.wait(
            futures, timeout=hang_timeout_s
        )
        with lock:
            closed = True  # freeze the report before handing it back
            rep.hung = len(not_done)
    finally:
        pool.shutdown(wait=False)
    return rep


__all__ = [
    "HttpTarget",
    "InprocTarget",
    "LoadReport",
    "gauss_points",
    "make_shape",
    "poisson_schedule",
    "run_open_loop",
]
