"""Unified telemetry layer (PR 12): span tracing with gang-merged
timelines (`obs/trace.py`, `python -m tdc_tpu.obs.merge_trace`), the
central metrics registry every `tdc_*` Prometheus series renders through
(`obs/metrics.py`, incl. the scrape-derived quantile helpers), and the
open-loop load generator that drives the serving tier to measured
saturation (`obs/loadgen.py`, PR 15).

Everything here is stdlib-only at import time (jax is imported lazily,
only when a hard sync is actually requested), so the hot-path guards —
`trace.span(...)` with tracing disabled, a registry that is never
rendered — cost a flag check, not an import.
"""

from __future__ import annotations

_LAZY = ("loadgen", "metrics", "trace")


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return importlib.import_module(f"tdc_tpu.obs.{name}")
    raise AttributeError(f"module 'tdc_tpu.obs' has no attribute {name!r}")


__all__ = list(_LAZY)
