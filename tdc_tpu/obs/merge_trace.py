"""Align N gang processes' per-process Chrome traces onto one timeline.

    python -m tdc_tpu.obs.merge_trace [--out merged_trace.json] DIR|FILE...

Inputs are the `trace_p<i>_<pid>.json` files obs/trace.flush() writes
(directories are globbed for `trace_*.json`). Each process keeps its own
track group (pid), renamed `tdc p<process_index>`; timestamps are
aligned on the `pass_boundary` instants the drivers emit — the earliest
pass number present in EVERY input is the anchor, and each trace is
shifted so its anchor lands at the same instant. Collective semantics
make this sound: a gang cannot start pass n before every process
finished pass n-1's reduce, so the anchor is a true simultaneity point
up to one barrier latency. Traces with no common anchor (e.g. a serve
process next to a fit) fall back to wall-clock alignment via the
`wall_t0` each export records.

Exit codes: 0 merged, 2 malformed/unusable input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

_REQUIRED_EVENT_KEYS = ("name", "ph", "pid", "tid")


class MergeError(Exception):
    pass


def load_trace(path: str) -> dict:
    """Load + validate one per-process trace export."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise MergeError(f"{path}: not readable JSON ({e})") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise MergeError(
            f"{path}: not a Chrome trace export (object with a "
            "'traceEvents' list)"
        )
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict) or any(
                k not in ev for k in _REQUIRED_EVENT_KEYS):
            raise MergeError(
                f"{path}: traceEvents[{i}] is missing required keys "
                f"{_REQUIRED_EVENT_KEYS}"
            )
        if ev["ph"] != "M" and "ts" not in ev:
            raise MergeError(f"{path}: traceEvents[{i}] has no 'ts'")
    return doc


def _anchors(doc: dict) -> dict[int, float]:
    """pass number -> ts of the FIRST pass_boundary instant for it."""
    out: dict[int, float] = {}
    for ev in doc["traceEvents"]:
        if ev.get("ph") == "i" and ev.get("name") == "pass_boundary":
            n = ev.get("args", {}).get("pass")
            if isinstance(n, int) and n not in out:
                out[n] = float(ev["ts"])
    return out


def _collect_inputs(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            found = sorted(glob.glob(os.path.join(p, "trace_*.json")))
            if not found:
                raise MergeError(f"{p}: no trace_*.json files")
            files.extend(found)
        elif os.path.exists(p):
            files.append(p)
        else:
            raise MergeError(f"{p}: no such file or directory")
    if not files:
        raise MergeError("no input traces")
    return files


def merge(paths: list[str]) -> dict:
    """Merge per-process exports into one aligned Chrome trace doc."""
    files = _collect_inputs(paths)
    docs = [load_trace(f) for f in files]

    anchor_sets = [_anchors(d) for d in docs]
    common = set(anchor_sets[0])
    for a in anchor_sets[1:]:
        common &= set(a)
    mode = "pass_boundary"
    if common:
        # Pass 0 is the END-of-fit reporting pass; prefer the earliest
        # real iteration boundary when one is shared.
        anchor = min(common - {0}) if common - {0} else 0
        shifts = [a[anchor] for a in anchor_sets]
    else:
        mode = "wall_clock"
        walls = []
        for f, d in zip(files, docs):
            w = d.get("otherData", {}).get("wall_t0")
            if not isinstance(w, (int, float)):
                raise MergeError(
                    f"{f}: no common pass_boundary anchor and no wall_t0 "
                    "fallback — cannot align"
                )
            walls.append(float(w))
        w0 = min(walls)
        # Later wall start => its ts 0 is LATER on the merged timeline.
        shifts = [-(w - w0) * 1e6 for w in walls]

    events: list[dict] = []
    seen_pids: set[int] = set()
    for i, (f, doc, shift) in enumerate(zip(files, docs, shifts)):
        other = doc.get("otherData", {})
        pid = other.get("pid")
        if not isinstance(pid, int) or pid in seen_pids:
            pid = 1_000_000 + i  # synthetic, collision-free track id
        seen_pids.add(pid)
        pidx = other.get("process_index")
        track = f"tdc p{pidx if pidx is not None else i} ({os.path.basename(f)})"
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": track},
        })
        events.append({
            "name": "process_sort_index", "ph": "M", "pid": pid, "tid": 0,
            "args": {"sort_index": (
                pidx if isinstance(pidx, int) else i
            )},
        })
        for ev in doc["traceEvents"]:
            if ev.get("name") == "process_name" and ev.get("ph") == "M":
                continue  # replaced by the per-file track name above
            ev = dict(ev)
            ev["pid"] = pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) - shift, 3)
            events.append(ev)

    # Normalize so the merged timeline starts at 0 (negative ts renders
    # unreliably across viewers).
    t_min = min((e["ts"] for e in events if "ts" in e), default=0.0)
    for e in events:
        if "ts" in e:
            e["ts"] = round(e["ts"] - t_min, 3)

    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "merged_from": [os.path.basename(f) for f in files],
            "alignment": mode,
        },
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tdc_tpu.obs.merge_trace",
        description=__doc__.splitlines()[0],
    )
    p.add_argument("inputs", nargs="+",
                   help="trace files and/or directories of trace_*.json")
    p.add_argument("--out", default="merged_trace.json",
                   help="merged output path (default merged_trace.json)")
    args = p.parse_args(argv)
    try:
        doc = merge(args.inputs)
    except MergeError as e:
        print(f"merge_trace: {e}", file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump(doc, f)
    n = sum(1 for e in doc["traceEvents"] if e.get("ph") != "M")
    print(
        f"merge_trace: {len(doc['otherData']['merged_from'])} traces, "
        f"{n} events, alignment={doc['otherData']['alignment']} "
        f"-> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
