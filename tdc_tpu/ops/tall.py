"""Feature-major (tall) kernels for the narrow-d regime — the reference's own
benchmark shape (d=5).

Why a second layout exists: TPU HBM stores a 2-D f32 array in (8, 128)
sublane×lane tiles, so a sample-major (N, d) buffer pads the minor axis
d → 128. At the reference grid's d=5 that is a 25.6× memory (and bandwidth)
blow-up — f32[100M, 5] costs 51.2 GB and cannot exist on a 16 GB chip, which
is structurally the same wall the reference hit (its n_obs ≥ 50M rows all
died, scripts/executions_log.csv). Storing the points feature-major as (d, N)
pads d only to the 8-sublane multiple: 1.6× at d=5, so 100M×5 is 3.2 GB and a
full Lloyd iteration is one bandwidth-bound pass over it.

These kernels are the fused single-pass sufficient-stats kernels
(pallas_kernels.lloyd_stats_fused / fuzzy_stats_fused) transposed: the grid
walks N-blocks of the (d, N) array, distances are computed as a
(K, d) × (d, BN) MXU contraction giving (K, BN) tiles, the argmin/membership
reductions run over the K sublane axis, and the (K, d) accumulators live in
VMEM scratch. No (N, K) or (K, N) buffer ever exists in HBM.

Reference counterpart: the per-tower tile/subtract/square/reduce/argmin body
(scripts/distribuitedClustering.py:207-251 for K-Means, :117-148 for fuzzy) —
re-laid-out for the TPU memory system instead of translated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tdc_tpu.ops.pallas_kernels import _PAD_CENTROID, _ARG_SENTINEL, _pad_axis


def tall_block_n(
    k: int,
    d: int,
    itemsize: int = 4,
    *,
    temps: int = 3,
    budget: int = 10 << 20,
    cap: int = 1 << 15,
) -> int:
    """Largest N-block (multiple of 128, ≤ cap) whose tall-kernel VMEM
    footprint fits the scoped-vmem budget, or 0 if even a 128-column block
    does not fit (huge K·d — use the sample-major kernels there; tall layout
    only wins at small d anyway).

    Footprint model: resident (K_s, d8) f32 accumulator + output + centroid
    tile + per-K vectors, plus per point column: the x tile (d8 sublanes ×
    itemsize) and `temps` live (K_s, BN) f32 temporaries across the
    distance → reduce → accumulate chain (≈3 for Lloyd: cross/d2, masked
    iota, one-hot; ≈5 for fuzzy: cross/d2, inv, u, mu + one live extra).

    The budget is deliberately ~64% of the 16 MB scope: measured on v5e, the
    model's 14 MB-budget pick at K=32, d=16 (block 32000, modeled 14.6 MB)
    actually allocated 16.30 MB and failed Mosaic's scoped-vmem check by
    305 KB — an ~11% model underestimate that then mis-routed the CLI's
    auto layout into a needless streamed fallback. 10 MB keeps ≥30%
    headroom over that worst observed error; the reference-grid shapes
    (K ≤ 15, d = 5) are cap-limited and unaffected.
    """
    k_s = -(-k // 8) * 8
    d8 = -(-d // 8) * 8
    fixed = k_s * max(d8, 128) * (8 + itemsize) + 32 * k_s
    per_col = temps * k_s * 4 + d8 * itemsize + 8
    avail = budget - fixed
    if avail < 128 * per_col:
        return 0
    return int(min(cap, avail // per_col // 128 * 128))


def _tall_lloyd_kernel(
    xt_ref, c_ref, c2_ref, sums_ref, counts_ref, sse_ref,
    acc_sums, acc_counts, acc_sse,
):
    """Grid over N-blocks of the (d8, N) array; K fully VMEM-resident.
    Per block: (K_s, BN) distance tile via one MXU contraction → argmin over
    the K sublane axis (masked-iota trick; jnp.argmin doesn't legalize) →
    exact one-hot → MXU accumulate into (K_s, d8) VMEM scratch."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_sums[...] = jnp.zeros_like(acc_sums)
        acc_counts[...] = jnp.zeros_like(acc_counts)
        acc_sse[...] = jnp.zeros_like(acc_sse)

    xt = xt_ref[...]  # (d8, BN)
    xf = xt.astype(jnp.float32)
    x2 = jnp.sum(xf * xf, axis=0, keepdims=True)  # (1, BN)
    # Same formulation/order/precision as ops.distance.pairwise_sq_dist so
    # boundary points assign identically to the XLA path.
    prec = (
        jax.lax.Precision.DEFAULT
        if xt.dtype == jnp.bfloat16
        else jax.lax.Precision.HIGHEST
    )
    cross = jax.lax.dot_general(
        c_ref[...],
        xt,
        (((1,), (0,)), ((), ())),
        precision=prec,
        preferred_element_type=jnp.float32,
    )  # (K_s, BN)
    d2 = jnp.maximum(x2 - 2.0 * cross + c2_ref[...], 0.0)
    tile_min = jnp.min(d2, axis=0, keepdims=True)  # (1, BN)
    row = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 0)
    masked = jnp.where(d2 <= tile_min, row, _ARG_SENTINEL)
    tile_arg = jnp.min(masked, axis=0, keepdims=True)  # (1, BN)
    one_hot = (row == tile_arg).astype(jnp.float32)  # (K_s, BN), single 1/col
    acc_sums[...] += jax.lax.dot_general(
        one_hot,
        xf,
        (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )  # (K_s, d8)
    acc_counts[...] += jnp.sum(one_hot, axis=1, keepdims=True)
    acc_sse[...] += jnp.sum(tile_min)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        sums_ref[...] = acc_sums[...]
        counts_ref[...] = acc_counts[...]
        sse_ref[...] = acc_sse[...]


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def lloyd_stats_tall(
    xt: jax.Array,
    centroids: jax.Array,
    *,
    block_n: int | None = None,
    interpret: bool | None = None,
):
    """Lloyd sufficient stats over feature-major points.

    Args:
      xt: (d, N) points — note the transposed storage; this is the layout
        that makes narrow-d datasets (d ≲ 32) fit TPU HBM without the
        128-lane padding blow-up.
      centroids: (K, d), standard orientation (API-compatible with
        ops.assign.lloyd_stats).

    Returns ops.assign.SufficientStats (sums (K, d) f32, counts (K,) f32,
    sse () f32), matching lloyd_stats(xt.T, centroids) exactly in f32.
    """
    from tdc_tpu.ops.assign import SufficientStats

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    d, n = xt.shape
    k = centroids.shape[0]
    if block_n is None:
        block_n = tall_block_n(k, d, xt.dtype.itemsize)
        if block_n == 0:
            raise ValueError(
                f"lloyd_stats_tall: K={k} too large for VMEM; use the "
                "sample-major kernels (tall layout only wins at small d)"
            )
    xp = _pad_axis(_pad_axis(xt, 0, 8, 0), 1, block_n, 0)
    cp = _pad_axis(
        _pad_axis(centroids.astype(xt.dtype), 1, 8, 0), 0, 8, _PAD_CENTROID
    )
    c2 = jnp.sum(cp.astype(jnp.float32) ** 2, axis=1, keepdims=True)  # (K_s, 1)
    d8, n_pad = xp.shape
    k_s = cp.shape[0]

    sums, counts, sse = pl.pallas_call(
        _tall_lloyd_kernel,
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((d8, block_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_s, d8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_s, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_s, d8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_s, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_s, d8), jnp.float32),
            jax.ShapeDtypeStruct((k_s, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((k_s, d8), jnp.float32),
            pltpu.VMEM((k_s, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, cp, c2)
    # Padded zero columns land on the argmin-‖c‖² cluster with zero Σx but
    # count/sse pollution — subtract exactly (same correction as the fused
    # sample-major kernel).
    n_fake = n_pad - n
    counts = counts[:k, 0]
    sse = sse[0, 0]
    if n_fake:
        c2v = c2[:k, 0]
        j = jnp.argmin(c2v)
        counts = counts.at[j].add(-float(n_fake))
        sse = sse - n_fake * c2v[j]
    return SufficientStats(
        sums=sums[:k, :d],
        counts=counts,
        sse=jnp.maximum(sse, 0.0),
    )


def _tall_fuzzy_kernel(
    xt_ref, c_ref, c2_ref, wsums_ref, weights_ref, obj_ref,
    acc_wsums, acc_weights, acc_obj, *, m: float, eps: float,
):
    """Fuzzy counterpart: true distances (‖x‖² recovered as the block's
    column sums) → memberships normalized over the K sublane axis →
    u^m-weighted MXU accumulate. The (N, K) membership matrix never exists."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_wsums[...] = jnp.zeros_like(acc_wsums)
        acc_weights[...] = jnp.zeros_like(acc_weights)
        acc_obj[...] = jnp.zeros_like(acc_obj)

    xt = xt_ref[...]  # (d8, BN)
    xf = xt.astype(jnp.float32)
    x2 = jnp.sum(xf * xf, axis=0, keepdims=True)  # (1, BN)
    prec = (
        jax.lax.Precision.DEFAULT
        if xt.dtype == jnp.bfloat16
        else jax.lax.Precision.HIGHEST
    )
    cross = jax.lax.dot_general(
        c_ref[...],
        xt,
        (((1,), (0,)), ((), ())),
        precision=prec,
        preferred_element_type=jnp.float32,
    )  # (K_s, BN)
    d2 = jnp.maximum(x2 - 2.0 * cross + c2_ref[...], 0.0)
    inv = (d2 + eps) ** (-1.0 / (m - 1.0))  # padded-centroid rows → ~0
    u = inv / jnp.sum(inv, axis=0, keepdims=True)
    mu = u**m  # (K_s, BN)
    acc_wsums[...] += jax.lax.dot_general(
        mu,
        xf,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (K_s, d8)
    acc_weights[...] += jnp.sum(mu, axis=1, keepdims=True)
    acc_obj[...] += jnp.sum(mu * d2)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        wsums_ref[...] = acc_wsums[...]
        weights_ref[...] = acc_weights[...]
        obj_ref[...] = acc_obj[...]


@functools.partial(jax.jit, static_argnames=("m", "eps", "block_n", "interpret"))
def fuzzy_stats_tall(
    xt: jax.Array,
    centroids: jax.Array,
    m: float = 2.0,
    eps: float = 1e-9,
    *,
    block_n: int | None = None,
    interpret: bool | None = None,
):
    """Fuzzy c-means sufficient stats over feature-major (d, N) points —
    matches ops.assign.fuzzy_stats(xt.T, centroids, m) in f32. Same storage
    rationale as lloyd_stats_tall."""
    from tdc_tpu.ops.assign import FuzzyStats

    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    d, n = xt.shape
    k = centroids.shape[0]
    if block_n is None:
        block_n = tall_block_n(k, d, xt.dtype.itemsize, temps=5)
        if block_n == 0:
            raise ValueError(
                f"fuzzy_stats_tall: K={k} too large for VMEM; use the "
                "sample-major kernels (tall layout only wins at small d)"
            )
    xp = _pad_axis(_pad_axis(xt, 0, 8, 0), 1, block_n, 0)
    cp = _pad_axis(
        _pad_axis(centroids.astype(xt.dtype), 1, 8, 0), 0, 8, _PAD_CENTROID
    )
    c2 = jnp.sum(cp.astype(jnp.float32) ** 2, axis=1, keepdims=True)  # (K_s, 1)
    d8, n_pad = xp.shape
    k_s = cp.shape[0]

    wsums, weights, obj = pl.pallas_call(
        functools.partial(_tall_fuzzy_kernel, m=float(m), eps=float(eps)),
        grid=(n_pad // block_n,),
        in_specs=[
            pl.BlockSpec((d8, block_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_s, d8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_s, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k_s, d8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k_s, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_s, d8), jnp.float32),
            jax.ShapeDtypeStruct((k_s, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((k_s, d8), jnp.float32),
            pltpu.VMEM((k_s, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, cp, c2)
    # Padded zero columns contribute ‖c‖²-softmin memberships (zero Σ u^m x
    # but nonzero weights/objective) — subtract their exact contribution.
    n_fake = n_pad - n
    weights = weights[:k, 0]
    obj = obj[0, 0]
    if n_fake:
        from tdc_tpu.ops.assign import fuzzy_stats

        zs = fuzzy_stats(jnp.zeros((1, d), jnp.float32), centroids, m=m, eps=eps)
        weights = weights - n_fake * zs.weights
        obj = obj - n_fake * zs.objective
    return FuzzyStats(
        weighted_sums=wsums[:k, :d],
        weights=weights,
        objective=jnp.maximum(obj, 0.0),
    )
